"""noslint's dataflow engine: CFG, def-use, inevitability, escape, symbols.

PR 2's rules are single-pass AST pattern matches — they cannot see that a
``get_node_for_write()`` result was *stored*, that a watched-field write
has a branch that skips its generation bump, or that a call three hops
away reaches ``api.*``.  This module is the analysis substrate the
dataflow rules (rules_flow.py, N007–N010) stand on:

- :func:`build_cfg` — an intraprocedural control-flow graph over
  *units* (elementary statements and branch/loop headers).  Branches,
  loops (with ``break``/``continue``), ``with``, ``try``/``except``/
  ``finally`` (abnormal exits are routed through enclosing ``finally``
  bodies by inlining them, the classic lowering) and ``match`` are
  modeled; exceptions are modeled only as edges from the ``try`` region
  to its handlers — a call that raises out of the function is *not* a
  modeled path (rules that need "all paths" semantics state this).
- :class:`FunctionFlow` — reaching definitions / def-use chains over
  the CFG, plus :meth:`FunctionFlow.always_reaches_after`, the backward
  must-analysis ("on every modeled path from here to the function exit,
  a unit matching ``pred`` occurs") that N008 uses for its
  post-domination check.
- :func:`escapes` — intraprocedural escape analysis: given taint
  sources (calls), propagate through name copies via def-use and report
  every way the value outlives the frame: stored on ``self``, returned,
  yielded, or captured by a closure that itself escapes (N007).
- :class:`SymbolIndex` — a cross-file symbol table + best-effort call
  resolution (``self.m()`` through base classes, module aliases,
  ``from``-imports, module-level singletons like ``REGISTRY``), the
  finalize-phase substrate for N009's callee-graph reachability.

Everything here is conservative in the direction each *rule* needs and
says so at the rule; the engine itself just reports facts.
"""

from __future__ import annotations

import ast
import copy
from dataclasses import dataclass, field
from typing import Callable, Iterable, Iterator

# ---------------------------------------------------------------------------
# Small AST helpers (shared with rules_flow)
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted name of a Name/Attribute chain ('' otherwise)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def attr_chain_root(node: ast.AST) -> ast.AST:
    """The innermost value of an Attribute/Subscript chain (peels both)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def walk_in_scope(node: ast.AST) -> Iterator[ast.AST]:
    """ast.walk that does NOT descend into nested function/class/lambda
    scopes (their statements belong to a different CFG)."""
    stack: list[ast.AST] = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(child))


def iter_functions(tree: ast.AST) -> Iterator[ast.FunctionDef]:
    """Every def in the module, at any nesting depth (methods included)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


# ---------------------------------------------------------------------------
# CFG
# ---------------------------------------------------------------------------


@dataclass
class Block:
    id: int
    units: list[ast.AST] = field(default_factory=list)
    succs: set[int] = field(default_factory=set)
    preds: set[int] = field(default_factory=set)


class CFG:
    """Intraprocedural control-flow graph.  ``entry`` holds the argument
    bindings (the FunctionDef node itself is its unit); ``exit`` holds
    no units.  ``pos(unit)`` locates a unit as (block id, index)."""

    def __init__(self) -> None:
        self.blocks: dict[int, Block] = {}
        self.entry = self._new().id
        self.exit = self._new().id
        self._pos: dict[int, tuple[int, int]] = {}

    def _new(self) -> Block:
        b = Block(len(self.blocks))
        self.blocks[b.id] = b
        return b

    def add_unit(self, block_id: int, unit: ast.AST) -> None:
        blk = self.blocks[block_id]
        self._pos[id(unit)] = (block_id, len(blk.units))
        blk.units.append(unit)

    def edge(self, a: int, b: int) -> None:
        self.blocks[a].succs.add(b)
        self.blocks[b].preds.add(a)

    def pos(self, unit: ast.AST) -> tuple[int, int]:
        return self._pos[id(unit)]

    def units(self) -> Iterator[ast.AST]:
        for blk in self.blocks.values():
            yield from blk.units


class _CFGBuilder:
    """One pass over a function body.  ``finally`` routing inlines the
    pending ``finally`` bodies at every abnormal exit (return / break /
    continue) — the classic lowering, so inevitability sees them."""

    def __init__(self, fn: ast.AST) -> None:
        self.cfg = CFG()
        self.fn = fn
        # (header_block, after_block, finally_depth) per open loop
        self._loops: list[tuple[int, int, int]] = []
        self._finallys: list[list[ast.stmt]] = []

    def build(self) -> CFG:
        self.cfg.add_unit(self.cfg.entry, self.fn)  # argument bindings
        end = self._body(getattr(self.fn, "body", []), self.cfg.entry)
        if end is not None:
            self.cfg.edge(end, self.cfg.exit)
        return self.cfg

    # -- statement dispatch -------------------------------------------------
    def _body(self, stmts: Iterable[ast.stmt], cur: int | None) -> int | None:
        for stmt in stmts:
            if cur is None:
                # unreachable code after return/raise/break: park it in a
                # fresh block with no predecessors so facts still exist
                cur = self.cfg._new().id
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, stmt: ast.stmt, cur: int) -> int | None:
        if isinstance(stmt, ast.If):
            return self._if(stmt, cur)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, cur)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self.cfg.add_unit(cur, stmt)       # context exprs + binds
            return self._body(stmt.body, cur)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, cur)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, cur)
        if isinstance(stmt, (ast.Return, ast.Raise)):
            self.cfg.add_unit(cur, stmt)
            cur = self._run_finallys(cur, 0)
            if cur is not None:
                self.cfg.edge(cur, self.cfg.exit)
            return None
        if isinstance(stmt, (ast.Break, ast.Continue)):
            self.cfg.add_unit(cur, stmt)
            if not self._loops:
                return None                    # malformed; be lenient
            header, after, depth = self._loops[-1]
            cur2 = self._run_finallys(cur, depth)
            if cur2 is not None:
                self.cfg.edge(cur2, after if isinstance(stmt, ast.Break)
                              else header)
            return None
        # simple statement (nested defs/classes are opaque binding units)
        self.cfg.add_unit(cur, stmt)
        return cur

    def _run_finallys(self, cur: int, down_to: int) -> int | None:
        """Inline every pending finally body (innermost first) above
        ``down_to`` on the abnormal-exit path starting at ``cur``.

        Each inlining gets a DEEP COPY of the statements: CFG positions
        and dataflow facts are keyed by node identity, so reusing the
        originals (which the normal path in _try already owns) would
        silently overwrite one copy's facts with the other's — judging
        a finally-body write's inevitability only on the last path
        registered.  Copies keep their source linenos for reporting."""
        for body in reversed(self._finallys[down_to:]):
            nxt = self.cfg._new().id
            self.cfg.edge(cur, nxt)
            end = self._body(copy.deepcopy(body), nxt)
            if end is None:
                return None                    # finally itself diverted
            cur = end
        return cur

    # -- compound forms -----------------------------------------------------
    def _if(self, stmt: ast.If, cur: int) -> int | None:
        self.cfg.add_unit(cur, stmt)           # the test
        join = self.cfg._new().id
        then = self.cfg._new().id
        self.cfg.edge(cur, then)
        then_end = self._body(stmt.body, then)
        if then_end is not None:
            self.cfg.edge(then_end, join)
        if stmt.orelse:
            other = self.cfg._new().id
            self.cfg.edge(cur, other)
            else_end = self._body(stmt.orelse, other)
            if else_end is not None:
                self.cfg.edge(else_end, join)
        else:
            self.cfg.edge(cur, join)
        return join if self.cfg.blocks[join].preds else None

    def _loop(self, stmt: ast.stmt, cur: int) -> int:
        header = self.cfg._new().id
        self.cfg.edge(cur, header)
        self.cfg.add_unit(header, stmt)        # test / iter+target bind
        after = self.cfg._new().id
        body_start = self.cfg._new().id
        self.cfg.edge(header, body_start)
        infinite = (isinstance(stmt, ast.While)
                    and isinstance(stmt.test, ast.Constant)
                    and bool(stmt.test.value))
        orelse = getattr(stmt, "orelse", [])
        if orelse and not infinite:
            else_start = self.cfg._new().id
            self.cfg.edge(header, else_start)
            else_end = self._body(orelse, else_start)
            if else_end is not None:
                self.cfg.edge(else_end, after)
        elif not infinite:
            self.cfg.edge(header, after)       # zero iterations / test false
        self._loops.append((header, after, len(self._finallys)))
        body_end = self._body(stmt.body, body_start)
        self._loops.pop()
        if body_end is not None:
            self.cfg.edge(body_end, header)    # back edge
        return after

    def _try(self, stmt: ast.Try, cur: int) -> int | None:
        region_lo = len(self.cfg.blocks)
        try_start = self.cfg._new().id
        self.cfg.edge(cur, try_start)
        if stmt.finalbody:
            self._finallys.append(stmt.finalbody)
        body_end = self._body(stmt.body, try_start)
        if body_end is not None and stmt.orelse:
            body_end = self._body(stmt.orelse, body_end)
        region_hi = len(self.cfg.blocks)
        ends: list[int] = [body_end] if body_end is not None else []
        for handler in stmt.handlers:
            h_start = self.cfg._new().id
            # an exception can surface from anywhere in the try region —
            # including mid-block, before any of a block's defs landed,
            # which the pre-try edge (cur) conservatively models
            for bid in [cur, *range(region_lo, region_hi)]:
                self.cfg.edge(bid, h_start)
            self.cfg.add_unit(h_start, handler)   # `except T as e:` binds e
            h_end = self._body(handler.body, h_start)
            if h_end is not None:
                ends.append(h_end)
        if stmt.finalbody:
            self._finallys.pop()
            fin = self.cfg._new().id
            for e in ends:
                self.cfg.edge(e, fin)
            if not ends:
                # every normal path diverted; finally still runs on them
                # via _run_finallys inlining — this block is the residual
                # exceptional pass-through
                self.cfg.edge(try_start, fin)
            return self._body(stmt.finalbody, fin)
        if not ends:
            return None
        join = self.cfg._new().id
        for e in ends:
            self.cfg.edge(e, join)
        return join

    def _match(self, stmt: ast.Match, cur: int) -> int | None:
        self.cfg.add_unit(cur, stmt)           # subject eval
        join = self.cfg._new().id
        exhaustive = False
        for case in stmt.cases:
            c_start = self.cfg._new().id
            self.cfg.edge(cur, c_start)
            self.cfg.add_unit(c_start, case)   # pattern binds
            c_end = self._body(case.body, c_start)
            if c_end is not None:
                self.cfg.edge(c_end, join)
            if (isinstance(case.pattern, ast.MatchAs)
                    and case.pattern.pattern is None and case.guard is None):
                exhaustive = True              # wildcard `case _:`
        if not exhaustive:
            self.cfg.edge(cur, join)
        return join if self.cfg.blocks[join].preds else None


def build_cfg(fn: ast.AST) -> CFG:
    """CFG of one function (or a synthetic Module treated as a body)."""
    return _CFGBuilder(fn).build()


# ---------------------------------------------------------------------------
# Per-unit def/use extraction
# ---------------------------------------------------------------------------


def unit_defs(unit: ast.AST, entry: bool = False) -> set[str]:
    """Names this unit binds (assignment targets, loop targets, with-as,
    imports, def/class names, except-as, match captures; plus the
    arguments when the unit is the CFG *entry* — a nested-def statement
    binds only its name, its parameters live in the inner scope)."""
    out: set[str] = set()
    if isinstance(unit, (ast.FunctionDef, ast.AsyncFunctionDef)):
        if entry:
            a = unit.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs):
                out.add(arg.arg)
            if a.vararg:
                out.add(a.vararg.arg)
            if a.kwarg:
                out.add(a.kwarg.arg)
        out.add(unit.name)
        return out
    if isinstance(unit, ast.ClassDef):
        return {unit.name}
    if isinstance(unit, ast.ExceptHandler):
        return {unit.name} if unit.name else set()
    if isinstance(unit, (ast.Import, ast.ImportFrom)):
        for alias in unit.names:
            if alias.name != "*":
                out.add(alias.asname or alias.name.split(".")[0])
        return out
    targets: list[ast.AST] = []
    if isinstance(unit, ast.Assign):
        targets = list(unit.targets)
    elif isinstance(unit, (ast.AugAssign, ast.AnnAssign)):
        targets = [unit.target]
    elif isinstance(unit, (ast.For, ast.AsyncFor)):
        targets = [unit.target]
    elif isinstance(unit, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in unit.items if i.optional_vars]
    elif isinstance(unit, ast.match_case):
        for sub in ast.walk(unit.pattern):
            for attr in ("name", "rest"):
                v = getattr(sub, attr, None)
                if isinstance(v, str):
                    out.add(v)
        return out
    for t in targets:
        for sub in ast.walk(t):
            # Store ctx only: `pod.status.phase = x` does NOT rebind
            # `pod` (the chain root is a Load) — treating it as a kill
            # would sever the def-use chain mid-object-mutation
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
                out.add(sub.id)
    # walrus binds anywhere in the unit's expressions
    for sub in walk_in_scope(unit):
        if isinstance(sub, ast.NamedExpr) and isinstance(sub.target, ast.Name):
            out.add(sub.target.id)
    return out


def use_roots(unit: ast.AST) -> list[ast.AST]:
    """The expression roots whose Name loads count as uses of this unit
    (compound statements contribute their header expressions only —
    their bodies are separate units)."""
    if isinstance(unit, ast.If):
        return [unit.test]
    if isinstance(unit, ast.While):
        return [unit.test]
    if isinstance(unit, (ast.For, ast.AsyncFor)):
        return [unit.iter]
    if isinstance(unit, (ast.With, ast.AsyncWith)):
        return [i.context_expr for i in unit.items]
    if isinstance(unit, ast.Match):
        return [unit.subject]
    if isinstance(unit, ast.match_case):
        return [unit.guard] if unit.guard else []
    if isinstance(unit, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.ExceptHandler)):
        return []
    if isinstance(unit, ast.Try):
        # no header expression at all — the bodies are separate units
        # (CFG) / separately-scanned statements (N010); falling through
        # to the default would re-walk the whole subtree with the wrong
        # context
        return []
    return [unit]


def iter_calls(unit: ast.AST) -> Iterator[ast.Call]:
    """Every Call in the unit's own expressions (its ``use_roots``) —
    the one place the 'walk headers, not bodies' subtlety lives for the
    rules that scan a statement's calls."""
    for root in use_roots(unit):
        # walk_in_scope yields children only, so a root that IS a Call
        # must be yielded itself — but never via a full ast.walk, which
        # would descend into lambda bodies (deferred execution the
        # scope-aware walk deliberately excludes)
        if isinstance(root, ast.Call):
            yield root
        for sub in walk_in_scope(root):
            if isinstance(sub, ast.Call):
                yield sub


def unit_uses(unit: ast.AST) -> set[str]:
    """Names this unit loads (nested function/lambda bodies excluded —
    those are closure captures, reported by :func:`closure_captures`)."""
    out: set[str] = set()
    for root in use_roots(unit):
        nodes = [root] if isinstance(root, ast.Name) else list(
            walk_in_scope(root))
        for sub in nodes:
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                out.add(sub.id)
    return out


def _free_names(closure: ast.AST) -> set[str]:
    """Names a def/lambda loads but does not bind itself (two passes:
    all bindings first, then loads outside them)."""
    bound: set[str] = set()
    a = closure.args
    for arg in (a.posonlyargs + a.args + a.kwonlyargs):
        bound.add(arg.arg)
    if a.vararg:
        bound.add(a.vararg.arg)
    if a.kwarg:
        bound.add(a.kwarg.arg)
    for inner in ast.walk(closure):
        if isinstance(inner, ast.Name) \
                and isinstance(inner.ctx, (ast.Store, ast.Del)):
            bound.add(inner.id)
        elif isinstance(inner, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)) and inner is not closure:
            bound.add(inner.name)
    return {inner.id for inner in ast.walk(closure)
            if isinstance(inner, ast.Name)
            and isinstance(inner.ctx, ast.Load)
            and inner.id not in bound}


def closure_captures(unit: ast.AST) -> dict[ast.AST, set[str]]:
    """Nested def/lambda nodes within this unit -> the free names their
    bodies load.  A statement-level ``def`` is itself a closure (the
    unit binds its name; the body captures the enclosing frame)."""
    out: dict[ast.AST, set[str]] = {}
    if isinstance(unit, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # a nested-def statement unit — NOT the CFG entry (escapes()
        # never passes the entry here; its reaching set is empty anyway)
        out[unit] = _free_names(unit)
        return out
    for root in use_roots(unit):
        for sub in ast.walk(root):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                out[sub] = _free_names(sub)
    return out


# ---------------------------------------------------------------------------
# Reaching definitions / def-use
# ---------------------------------------------------------------------------


class FunctionFlow:
    """Reaching-definitions dataflow over one function's CFG.

    A *definition* is (name, unit); ``reaching(unit)`` is the set of
    definitions live at the unit's entry.  ``defs_of(unit, name)``
    filters that to one name — the def-use chain read.  The analysis is
    a classic forward may-union fixpoint; loops converge because the
    lattice is finite.
    """

    def __init__(self, fn: ast.AST, cfg: CFG | None = None) -> None:
        self.fn = fn
        self.cfg = cfg or build_cfg(fn)
        self._defs: dict[int, set[str]] = {}
        self._in: dict[int, set[tuple[str, int]]] = {}
        self._unit_in: dict[int, set[tuple[str, int]]] = {}
        self._solve()

    def _solve(self) -> None:
        cfg = self.cfg
        gen: dict[int, dict[str, int]] = {}
        for bid, blk in cfg.blocks.items():
            g: dict[str, int] = {}
            for unit in blk.units:
                for name in unit_defs(unit, entry=(unit is self.fn)):
                    g[name] = id(unit)
            gen[bid] = g
        in_sets: dict[int, set[tuple[str, int]]] = {
            bid: set() for bid in cfg.blocks}
        work = list(cfg.blocks)
        out_sets: dict[int, set[tuple[str, int]]] = {
            bid: set() for bid in cfg.blocks}
        while work:
            bid = work.pop()
            blk = cfg.blocks[bid]
            new_in: set[tuple[str, int]] = set()
            for p in blk.preds:
                new_in |= out_sets[p]
            in_sets[bid] = new_in
            killed = set(gen[bid])
            new_out = {(n, u) for (n, u) in new_in if n not in killed}
            new_out |= {(n, u) for n, u in gen[bid].items()}
            if new_out != out_sets[bid]:
                out_sets[bid] = new_out
                work.extend(blk.succs)
        self._in = in_sets
        # per-unit IN: walk each block forward applying gen/kill
        for bid, blk in cfg.blocks.items():
            live = set(in_sets[bid])
            for unit in blk.units:
                self._unit_in[id(unit)] = set(live)
                bound = unit_defs(unit, entry=(unit is self.fn))
                if bound:
                    live = {(n, u) for (n, u) in live if n not in bound}
                    live |= {(n, id(unit)) for n in bound}

    def reaching(self, unit: ast.AST) -> set[tuple[str, int]]:
        return self._unit_in.get(id(unit), set())

    def defs_of(self, unit: ast.AST, name: str) -> set[int]:
        """id()s of the units whose definition of ``name`` reaches
        ``unit`` (the AugAssign/self-referential read sees the prior
        defs, since a unit's IN excludes its own bindings)."""
        return {u for (n, u) in self.reaching(unit) if n == name}

    # -- inevitability (the N008 post-domination read) ----------------------
    def always_reaches_after(self, unit: ast.AST,
                             pred: Callable[[ast.AST], bool]) -> bool:
        """True iff on EVERY modeled path from just after ``unit`` to the
        function exit, some unit matching ``pred`` occurs.  Exceptions
        escaping the function are not modeled paths (build_cfg)."""
        bid, idx = self.cfg.pos(unit)
        blk = self.cfg.blocks[bid]
        for later in blk.units[idx + 1:]:
            if pred(later):
                return True
        inev = self._inevitable_in(pred)
        succs = blk.succs
        return bool(succs) and all(inev[s] for s in succs)

    def _inevitable_in(self, pred: Callable[[ast.AST], bool]) -> dict[int, bool]:
        """inev[b]: every path starting at b's entry hits a pred unit.
        Greatest fixpoint (init True, exit False, iterate down)."""
        cfg = self.cfg
        has = {bid: any(pred(u) for u in blk.units)
               for bid, blk in cfg.blocks.items()}
        inev = {bid: True for bid in cfg.blocks}
        inev[cfg.exit] = False
        changed = True
        while changed:
            changed = False
            for bid, blk in cfg.blocks.items():
                if bid == cfg.exit or has[bid]:
                    continue
                val = bool(blk.succs) and all(inev[s] for s in blk.succs)
                if val != inev[bid]:
                    inev[bid] = val
                    changed = True
        return inev


# ---------------------------------------------------------------------------
# Escape analysis (N007)
# ---------------------------------------------------------------------------


def _direct_subexprs(expr: ast.AST) -> list[ast.AST]:
    """Sub-expressions reachable without crossing a Call boundary: the
    positions from which a value is handed onward verbatim (tuple/list
    elements, conditional arms) rather than consumed by a callee."""
    out: list[ast.AST] = []
    stack = [expr]
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, ast.Call):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


@dataclass(frozen=True)
class Escape:
    kind: str          # "stored-on-self" | "returned" | "yielded" |
    #                    "stored-global" | "closure"
    unit: ast.AST      # the escaping statement (line anchor)
    name: str          # the tainted name that escaped
    detail: str = ""


def escapes(fn: ast.AST, source: Callable[[ast.Call], bool],
            flow: FunctionFlow | None = None) -> list[Escape]:
    """Every way a value produced by a ``source`` call outlives ``fn``.

    Taint: a name assigned (directly or through name-copy chains, incl.
    annotated and tuple-destructured assignments) from a source call —
    plus the source call appearing *directly* in the escaping position
    (``self._x = snap.fork()``, ``return snap.fork()``) with no
    intermediate name at all.  Reported escapes: assignment into
    ``self.*`` (or a subscript/attribute thereof), assignment to a
    module global, return, yield, ``.append/.add/...`` of a tainted
    value into a ``self.*`` container, and capture by a closure that
    itself escapes (returned, yielded, or stored on ``self``).  A
    closure that stays local — a ``sorted(key=...)`` lambda — does not
    escape.
    """
    flow = flow or FunctionFlow(fn)
    units = list(flow.cfg.units())

    def direct_source(expr: ast.AST | None) -> bool:
        """A source call sits in ``expr`` without crossing another call
        boundary — the value is handed onward verbatim."""
        if expr is None:
            return False
        return any(isinstance(s, ast.Call) and source(s)
                   for s in _direct_subexprs(expr))

    # -- seed + propagate taint through name copies -------------------------
    # tainted definitions are (defining unit id, name): tuple targets
    # taint only the element actually paired with a source/copy value
    tainted: set[tuple[int, str]] = set()

    def is_tainted(unit: ast.AST, name: str) -> bool:
        return any((u, name) in tainted
                   for u in flow.defs_of(unit, name))

    def pairs(unit: ast.AST) -> Iterator[tuple[str, ast.AST]]:
        """(bound name, value expr) pairs of an assignment unit."""
        if isinstance(unit, ast.Assign):
            for t in unit.targets:
                if isinstance(t, ast.Name):
                    yield t.id, unit.value
                elif isinstance(t, (ast.Tuple, ast.List)) \
                        and isinstance(unit.value, (ast.Tuple, ast.List)) \
                        and len(t.elts) == len(unit.value.elts):
                    for el, v in zip(t.elts, unit.value.elts):
                        if isinstance(el, ast.Name):
                            yield el.id, v
        elif isinstance(unit, ast.AnnAssign) \
                and isinstance(unit.target, ast.Name) \
                and unit.value is not None:
            yield unit.target.id, unit.value

    changed = True
    while changed:
        changed = False
        for unit in units:
            for name, val in pairs(unit):
                if (id(unit), name) in tainted:
                    continue
                is_src = isinstance(val, ast.Call) and source(val)
                is_copy = (isinstance(val, ast.Name)
                           and is_tainted(unit, val.id))
                if is_src or is_copy:
                    tainted.add((id(unit), name))
                    changed = True

    def first_source_label(expr: ast.AST) -> str:
        for s in _direct_subexprs(expr):
            if isinstance(s, ast.Call) and source(s):
                return (dotted_name(s.func) or "<source>") + "(...)"
        return "<source>(...)"

    def target_value_pairs(unit: ast.Assign) -> Iterator[
            tuple[ast.AST, ast.AST]]:
        """(target element, value expr) with tuple destructuring paired
        element-wise so `self._x, y = fork(), 5` judges each side."""
        for t in unit.targets:
            if isinstance(t, (ast.Tuple, ast.List)) \
                    and isinstance(unit.value, (ast.Tuple, ast.List)) \
                    and len(t.elts) == len(unit.value.elts):
                yield from zip(t.elts, unit.value.elts)
            else:
                yield t, unit.value

    mutators = {"append", "add", "insert", "appendleft", "extend",
                "setdefault", "update"}

    # -- containers holding tainted values ----------------------------------
    # `out[k] = n` / `out.append(n)` put the alias inside a LOCAL
    # container; returning/yielding/storing that container then carries
    # every element past the frame.  Judged flow-insensitively (a name,
    # once a carrier, stays one) — the certifier errs conservative.
    container_hot: set[str] = set()

    def _value_carries(unit: ast.AST, val: ast.AST) -> bool:
        if isinstance(val, ast.Name):
            return is_tainted(unit, val.id) or val.id in container_hot
        return isinstance(val, ast.Call) and source(val)

    changed = True
    while changed:
        changed = False
        for unit in units:
            if isinstance(unit, ast.Assign):
                for t, val in target_value_pairs(unit):
                    if not isinstance(t, ast.Subscript):
                        continue
                    root = attr_chain_root(t)
                    if isinstance(root, ast.Name) and root.id != "self" \
                            and root.id not in container_hot \
                            and _value_carries(unit, val):
                        container_hot.add(root.id)
                        changed = True
                # `alias = out` keeps carrying
                for name, val in pairs(unit):
                    if isinstance(val, ast.Name) \
                            and val.id in container_hot \
                            and name not in container_hot:
                        container_hot.add(name)
                        changed = True
            if isinstance(unit, (ast.Expr, ast.Assign)):
                for sub in iter_calls(unit):
                    if not (isinstance(sub.func, ast.Attribute)
                            and sub.func.attr in mutators):
                        continue
                    recv = attr_chain_root(sub.func.value)
                    if isinstance(recv, ast.Name) and recv.id != "self" \
                            and recv.id not in container_hot \
                            and any(_value_carries(unit, a)
                                    for a in sub.args):
                        container_hot.add(recv.id)
                        changed = True

    out: list[Escape] = []
    # closure name -> (def unit, captured tainted names)
    closures: dict[str, tuple[ast.AST, set[str]]] = {}
    # names the function declares `global`: a bare-name store to one is
    # a module-level escape
    global_names: set[str] = set()
    for sub in walk_in_scope(fn):
        if isinstance(sub, ast.Global):
            global_names.update(sub.names)

    for unit in units:
        hot = {n for n in unit_uses(unit)
               if is_tainted(unit, n) or n in container_hot}
        if isinstance(unit, ast.Assign):
            for t, val in target_value_pairs(unit):
                root = attr_chain_root(t)
                rhs_names = {s.id for s in ast.walk(val)
                             if isinstance(s, ast.Name)
                             and isinstance(s.ctx, ast.Load)} & hot
                carried = bool(rhs_names) or direct_source(val)
                name = (sorted(rhs_names)[0] if rhs_names
                        else first_source_label(val))
                if not carried:
                    continue
                if t is root:
                    if isinstance(t, ast.Name) and t.id in global_names:
                        out.append(Escape("stored-global", unit,
                                          name, t.id))
                    continue
                if isinstance(root, ast.Name) and root.id == "self":
                    out.append(Escape("stored-on-self", unit, name,
                                      dotted_name(t) or "self.<...>"))
        if isinstance(unit, ast.AugAssign):
            # `self._dirty += [node]` / `self._seen |= {node}` store the
            # value exactly like the plain-assign container forms
            root = attr_chain_root(unit.target)
            rhs_names = {s.id for s in ast.walk(unit.value)
                         if isinstance(s, ast.Name)
                         and isinstance(s.ctx, ast.Load)} & hot
            carried = bool(rhs_names) or direct_source(unit.value)
            if carried:
                name = (sorted(rhs_names)[0] if rhs_names
                        else first_source_label(unit.value))
                if unit.target is not root and isinstance(root, ast.Name) \
                        and root.id == "self":
                    out.append(Escape("stored-on-self", unit, name,
                                      dotted_name(unit.target)
                                      or "self.<...>"))
                elif isinstance(unit.target, ast.Name) \
                        and unit.target.id in global_names:
                    out.append(Escape("stored-global", unit, name,
                                      unit.target.id))
        if isinstance(unit, ast.Return) and unit.value is not None:
            names = {s.id for s in ast.walk(unit.value)
                     if isinstance(s, ast.Name)
                     and isinstance(s.ctx, ast.Load)} & hot
            for n in sorted(names):
                out.append(Escape("returned", unit, n))
            if not names and direct_source(unit.value):
                out.append(Escape("returned", unit,
                                  first_source_label(unit.value)))
        if isinstance(unit, (ast.Expr, ast.Assign)):
            for sub in walk_in_scope(unit):
                if isinstance(sub, (ast.Yield, ast.YieldFrom)) \
                        and sub.value is not None:
                    names = {s.id for s in ast.walk(sub.value)
                             if isinstance(s, ast.Name)
                             and isinstance(s.ctx, ast.Load)} & hot
                    for n in sorted(names):
                        out.append(Escape("yielded", unit, n))
                    if not names and direct_source(sub.value):
                        out.append(Escape("yielded", unit,
                                          first_source_label(sub.value)))
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in mutators:
                    recv_root = attr_chain_root(sub.func.value)
                    arg_names: set[str] = set()
                    for a in sub.args:
                        if isinstance(a, ast.Name) and a.id in hot:
                            arg_names.add(a.id)
                        elif isinstance(a, ast.Call) and source(a):
                            arg_names.add(first_source_label(a))
                    if arg_names and isinstance(recv_root, ast.Name) \
                            and recv_root.id == "self":
                        for n in sorted(arg_names):
                            out.append(Escape(
                                "stored-on-self", unit, n,
                                f"{dotted_name(sub.func.value)}"
                                f".{sub.func.attr}(...)"))
        # closures capturing tainted names
        for closure, free in closure_captures(unit).items():
            cap = {n for n in free if is_tainted(unit, n)}
            if not cap:
                continue
            if isinstance(closure, ast.Lambda):
                # a lambda escapes only when the unit itself hands it
                # out DIRECTLY (returned, yielded, or stored on self —
                # incl. `self._cbs.append(lambda: ...)`); a lambda
                # consumed by any OTHER call argument (`sorted(key=...)`)
                # dies with the call (documented conservative assumption)
                if isinstance(unit, ast.Return) and unit.value is not None \
                        and closure in _direct_subexprs(unit.value):
                    out.append(Escape("closure", unit, sorted(cap)[0],
                                      "lambda returned"))
                elif isinstance(unit, ast.Assign) \
                        and closure in _direct_subexprs(unit.value) \
                        and any(
                            isinstance(attr_chain_root(t), ast.Name)
                            and attr_chain_root(t).id == "self"  # type: ignore[union-attr]
                            and t is not attr_chain_root(t)
                            for t in unit.targets):
                    out.append(Escape("closure", unit, sorted(cap)[0],
                                      "lambda stored on self"))
                elif isinstance(unit, (ast.Expr, ast.Assign)) and any(
                        isinstance(sub, (ast.Yield, ast.YieldFrom))
                        and sub.value is not None
                        and closure in _direct_subexprs(sub.value)
                        for sub in walk_in_scope(unit)):
                    out.append(Escape("closure", unit, sorted(cap)[0],
                                      "lambda yielded"))
                elif any(
                        isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in mutators
                        and closure in sub.args
                        and isinstance(attr_chain_root(sub.func.value),
                                       ast.Name)
                        and attr_chain_root(sub.func.value).id == "self"  # type: ignore[union-attr]
                        for sub in iter_calls(unit)):
                    out.append(Escape("closure", unit, sorted(cap)[0],
                                      "lambda stored on self"))
            else:
                closures[closure.name] = (unit, cap)

    # a named closure escapes if its NAME is returned/yielded/stored-on-self
    if closures:
        for unit in units:
            esc_names: set[str] = set()
            if isinstance(unit, ast.Return) and unit.value is not None:
                esc_names = {s.id for s in ast.walk(unit.value)
                             if isinstance(s, ast.Name)}
            elif isinstance(unit, ast.Assign):
                roots = [attr_chain_root(t) for t in unit.targets]
                if any(isinstance(r, ast.Name) and r.id == "self"
                       and t is not r
                       for r, t in zip(roots, unit.targets)):
                    esc_names = {s.id for s in ast.walk(unit.value)
                                 if isinstance(s, ast.Name)}
            if isinstance(unit, (ast.Expr, ast.Assign)):
                # `yield handler` and `self._cbs.append(handler)` hand
                # the closure out just like return/store-on-self
                for sub in walk_in_scope(unit):
                    if isinstance(sub, (ast.Yield, ast.YieldFrom)) \
                            and sub.value is not None:
                        esc_names |= {s.id for s in ast.walk(sub.value)
                                      if isinstance(s, ast.Name)}
                for sub in iter_calls(unit):
                    if isinstance(sub.func, ast.Attribute) \
                            and sub.func.attr in mutators:
                        recv = attr_chain_root(sub.func.value)
                        if isinstance(recv, ast.Name) and recv.id == "self":
                            esc_names |= {a.id for a in sub.args
                                          if isinstance(a, ast.Name)}
            for cname in esc_names & set(closures):
                def_unit, cap = closures[cname]
                out.append(Escape("closure", def_unit, sorted(cap)[0],
                                  f"closure {cname!r} outlives the frame"))
    return out


# ---------------------------------------------------------------------------
# Cross-file symbol index + call resolution (N009)
# ---------------------------------------------------------------------------


def module_name_of(relpath: str) -> str:
    """'nos_tpu/obs/journal.py' -> 'nos_tpu.obs.journal'."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


@dataclass
class FunctionSym:
    module: str
    qualname: str          # "Class.method" or "func"
    node: ast.AST
    cls: str | None = None


class SymbolIndex:
    """Best-effort cross-file symbol table: functions/methods, class
    bases, imports, and module-level singleton instances (``X = C()``).

    ``resolve_call`` maps a call site in a known function to the callee's
    (module, qualname) key when the receiver is: a bare local/imported
    name, ``self.m()`` (searched through indexed base classes), a module
    alias (``J.record``), an indexed singleton (``REGISTRY.inc``), or a
    locally-constructed instance is NOT tracked — unresolved calls return
    None and callers fall back to pattern checks on the dotted name."""

    def __init__(self) -> None:
        self.functions: dict[tuple[str, str], FunctionSym] = {}
        # (module, class) -> list of base (module, class) keys
        self.bases: dict[tuple[str, str], list[tuple[str, str]]] = {}
        # module -> {alias: module-dotted}
        self.mod_imports: dict[str, dict[str, str]] = {}
        # module -> {name: (source module, original name)}
        self.from_imports: dict[str, dict[str, tuple[str, str]]] = {}
        # (module, name) -> (module, class) for X = C() at module level
        self.instances: dict[tuple[str, str], tuple[str, str]] = {}

    # -- building -----------------------------------------------------------
    def add_module(self, relpath: str, tree: ast.AST) -> None:
        module = module_name_of(relpath)
        mi = self.mod_imports.setdefault(module, {})
        fi = self.from_imports.setdefault(module, {})
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mi[alias.asname or alias.name.split(".")[0]] = \
                        alias.name if alias.asname else \
                        alias.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                src = node.module
                if node.level:
                    parts = module.split(".")
                    src = ".".join(parts[: len(parts) - node.level]
                                   + [node.module])
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    fi[alias.asname or alias.name] = (src, alias.name)
        # classes/functions first: the singleton scan below resolves
        # `X = C()` against them, wherever C sits in the file
        self._index_scope(module, tree, cls=None)
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Name):
                cls_key = self._resolve_name(module, node.value.func.id)
                for t in node.targets:
                    if isinstance(t, ast.Name) and cls_key:
                        self.instances[(module, t.id)] = cls_key

    def _index_scope(self, module: str, scope: ast.AST,
                     cls: str | None) -> None:
        for node in ast.iter_child_nodes(scope):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{cls}.{node.name}" if cls else node.name
                self.functions[(module, qual)] = FunctionSym(
                    module, qual, node, cls)
                self._index_scope(module, node, cls)  # nested defs: parent qual
            elif isinstance(node, ast.ClassDef):
                bases = []
                for b in node.bases:
                    key = None
                    if isinstance(b, ast.Name):
                        key = self._resolve_name(module, b.id)
                    elif isinstance(b, ast.Attribute):
                        d = dotted_name(b)
                        head, _, tail = d.rpartition(".")
                        src = self.mod_imports.get(module, {}).get(head)
                        if src:
                            key = (src, tail)
                    if key:
                        bases.append(key)
                self.bases[(module, node.name)] = bases
                self._index_scope(module, node, node.name)
            elif isinstance(node, (ast.If, ast.Try, ast.With, ast.AsyncWith)):
                self._index_scope(module, node, cls)

    def _resolve_name(self, module: str, name: str) -> tuple[str, str] | None:
        """A bare name in `module` -> (defining module, qualname)."""
        if (module, name) in self.functions or (module, name) in self.bases:
            return (module, name)
        src = self.from_imports.get(module, {}).get(name)
        if src:
            return src
        return None

    # -- call resolution ----------------------------------------------------
    def resolve_call(self, module: str, cls: str | None,
                     call: ast.Call) -> tuple[str, str] | None:
        func = call.func
        if isinstance(func, ast.Name):
            key = self._resolve_name(module, func.id)
            if key is None:
                return None
            if key in self.functions:
                return key
            # a class: the call constructs it -> __init__
            init = self._method(key, "__init__")
            return init
        if not isinstance(func, ast.Attribute):
            return None
        recv, attr = func.value, func.attr
        if isinstance(recv, ast.Name):
            if recv.id == "self" and cls is not None:
                return self._method((module, cls), attr)
            # module alias?
            target_mod = self.mod_imports.get(module, {}).get(recv.id)
            if target_mod and (target_mod, attr) in self.functions:
                return (target_mod, attr)
            # from-imported module (``from nos_tpu.obs import journal``)
            src = self.from_imports.get(module, {}).get(recv.id)
            if src:
                submod = f"{src[0]}.{src[1]}"
                if (submod, attr) in self.functions:
                    return (submod, attr)
            # module-level singleton (REGISTRY.inc)
            inst = self.instances.get((module, recv.id))
            if inst is None and src:
                inst = self.instances.get(src)
            if inst is not None:
                return self._method(inst, attr)
        return None

    def _method(self, cls_key: tuple[str, str],
                name: str) -> tuple[str, str] | None:
        """Method lookup through indexed bases (best-effort MRO)."""
        seen: set[tuple[str, str]] = set()
        work = [cls_key]
        while work:
            key = work.pop(0)
            if key in seen:
                continue
            seen.add(key)
            fkey = (key[0], f"{key[1]}.{name}")
            if fkey in self.functions:
                return fkey
            work.extend(self.bases.get(key, []))
        return None

    def callees(self, key: tuple[str, str]) -> Iterator[
            tuple[ast.Call, tuple[str, str] | None]]:
        """(call site, resolved callee key or None) for every call in the
        function's body — including nested closures (conservative: the
        leaf contract cares that the code CAN run, not when)."""
        sym = self.functions.get(key)
        if sym is None:
            return
        for node in ast.walk(sym.node):
            if isinstance(node, ast.Call):
                yield node, self.resolve_call(sym.module, sym.cls, node)
