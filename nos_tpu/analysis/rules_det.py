"""noslint rules N011–N012: the determinism certification pass.

ROADMAP item 3 (delta-driven scheduling, 16k hosts) anchors on the
planner being a *pure function of the snapshot*: byte-identical decision
journals across hash seeds and worker counts (scripts/nosdiff.py proves
it dynamically).  These rules forbid, statically, the two nondeterminism
classes that would make that anchor flap:

- **N011** — unordered-collection iteration feeding a decision: a value
  of ``set``/``frozenset`` type (literal, constructor, comprehension,
  set operator, annotation) iterated by an *order-sensitive* consumer —
  a loop that appends/yields/breaks/returns/records, a list/generator
  comprehension, ``list()``/``tuple()``/``.join()`` materialization,
  ``next(iter(...))``/``.pop()`` (pure hash order), or a
  ``min``/``max`` with ``key=`` (ties break by iteration order) — in
  decision-plane code.  The fix is ``sorted(..., key=...)``; an audited
  stable order gets a reasoned pragma.  Plain dicts are
  insertion-ordered (3.7+) and exempt, BUT a dict *built by iterating a
  tainted source* (``{k: f(k) for k in some_set}``) inherits hash
  insertion order, so iterating it — or its
  ``.keys()/.values()/.items()`` views — is convicted too.

- **N012** — invalidation-protocol completeness: classes carrying
  ``@invalidated_by('<event>', '<field>', ...)``
  (nos_tpu/utils/guards.py) declare that in-place mutations of each
  watched source field must be post-dominated, on every modeled path,
  by an emission of the declared invalidation event — a call whose last
  segment is the event name, or a write to ``self.<event>`` (the
  counter-bump form).  Whole-field rebinds (``self._idx = {}``) are the
  invalidate-by-rebuild idiom and exempt, as are ``__init__``/
  ``__post_init__`` and the event method itself.  This extends N008
  (single watched-attribute writes on live API objects) to the full
  index protocol: the watch-maintained SchedulerCache indexes, the
  scheduler's per-cycle lister feeding the class-scan/window-busy
  caches, and the planner snapshot's epoch-memoised views.  A REQUIRED
  registry keeps the certification live across renames (the N009
  pattern): the named cache classes must carry the declaration at all.

Conservatism: both rules convict only what they can *show* — taint and
aliases propagate through plain name copies, one ``.get()``/subscript
element hop, and assignment pairs; mutations reached through deeper
aliasing or cross-function flow are blind spots the dynamic half
(scripts/nosdiff.py, the interleave explorer) covers at runtime.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator

from .core import ModuleSource, Rule, Violation
from .dataflow import (
    FunctionFlow, attr_chain_root, dotted_name, iter_calls, iter_functions,
    module_name_of, unit_uses, use_roots, walk_in_scope,
)

# ---------------------------------------------------------------------------
# N011 — unordered iteration feeding a decision
# ---------------------------------------------------------------------------


class UnorderedIterationHazard(Rule):
    """N011: set/frozenset iteration order must never reach a decision."""

    id = "N011"
    title = "unordered-collection iteration feeds an order-sensitive decision"
    scope = ("nos_tpu/scheduler/", "nos_tpu/partitioning/",
             "nos_tpu/capacity/", "nos_tpu/controllers/",
             "nos_tpu/serving/", "nos_tpu/quota/", "nos_tpu/sim/",
             "nos_tpu/requests/")

    SET_CONSTRUCTORS = frozenset({"set", "frozenset"})
    #: methods that return a set when their receiver is one
    SET_METHODS = frozenset({"union", "intersection", "difference",
                             "symmetric_difference", "copy"})
    SET_ANNOTATIONS = frozenset({"set", "frozenset", "Set", "FrozenSet",
                                 "AbstractSet", "MutableSet"})
    SET_BINOPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)
    #: dict views whose order is the dict's insertion order — hazardous
    #: exactly when the dict itself was built in hash order
    DICT_VIEWS = frozenset({"keys", "values", "items", "copy"})

    #: loop-body calls that make the iteration order observable
    ORDERED_SINKS = frozenset({"append", "extend", "insert", "appendleft",
                               "record", "emit"})
    #: consumers whose result is independent of argument order — a
    #: comprehension/list() handed DIRECTLY to one of these is fine.
    #: min/max qualify only without key= (ties break by encounter order)
    INSENSITIVE_CONSUMERS = frozenset({
        "sorted", "set", "frozenset", "sum", "any", "all", "len",
        "min", "max", "dict", "Counter"})

    # -- taint ---------------------------------------------------------------
    def _ann_is_set(self, ann: ast.AST) -> bool:
        if isinstance(ann, ast.Subscript):
            ann = ann.value
        d = dotted_name(ann)
        return (d.split(".")[-1] if d else "") in self.SET_ANNOTATIONS

    @staticmethod
    def _pairs(unit: ast.AST) -> Iterator[tuple[str, ast.AST]]:
        """(bound name, value expr) pairs of an assignment unit."""
        if isinstance(unit, ast.Assign):
            for t in unit.targets:
                if isinstance(t, ast.Name):
                    yield t.id, unit.value
                elif isinstance(t, (ast.Tuple, ast.List)) \
                        and isinstance(unit.value, (ast.Tuple, ast.List)) \
                        and len(t.elts) == len(unit.value.elts):
                    for el, v in zip(t.elts, unit.value.elts):
                        if isinstance(el, ast.Name):
                            yield el.id, v
        elif isinstance(unit, ast.AnnAssign) \
                and isinstance(unit.target, ast.Name) \
                and unit.value is not None:
            yield unit.target.id, unit.value

    def _analyze(self, fn: ast.AST) -> tuple[FunctionFlow, set, set]:
        """(flow, set-tainted defs, hash-ordered-dict defs) — defs are
        (unit id, name)."""
        flow = FunctionFlow(fn)
        units = list(flow.cfg.units())
        sets: set[tuple[int, str]] = set()
        ords: set[tuple[int, str]] = set()

        a = fn.args
        for arg in (a.posonlyargs + a.args + a.kwonlyargs):
            if arg.annotation is not None \
                    and self._ann_is_set(arg.annotation):
                sets.add((id(fn), arg.arg))

        def name_in(unit: ast.AST, name: str, pool: set) -> bool:
            return any((u, name) in pool for u in flow.defs_of(unit, name))

        def set_expr(unit: ast.AST, expr: ast.AST) -> bool:
            if isinstance(expr, (ast.Set, ast.SetComp)):
                return True
            if isinstance(expr, ast.Name):
                return name_in(unit, expr.id, sets)
            if isinstance(expr, ast.BinOp) \
                    and isinstance(expr.op, self.SET_BINOPS):
                return set_expr(unit, expr.left) \
                    or set_expr(unit, expr.right)
            if isinstance(expr, ast.IfExp):
                return set_expr(unit, expr.body) \
                    or set_expr(unit, expr.orelse)
            if isinstance(expr, ast.Call):
                f = expr.func
                if isinstance(f, ast.Name) \
                        and f.id in self.SET_CONSTRUCTORS:
                    return True
                if isinstance(f, ast.Attribute) \
                        and f.attr in self.SET_METHODS:
                    return set_expr(unit, f.value)
            return False

        def ord_expr(unit: ast.AST, expr: ast.AST) -> bool:
            """A dict whose INSERTION order is hash order."""
            if isinstance(expr, ast.DictComp):
                return any(set_expr(unit, g.iter)
                           for g in expr.generators)
            if isinstance(expr, ast.Name):
                return name_in(unit, expr.id, ords)
            if isinstance(expr, ast.Call):
                f = expr.func
                if isinstance(f, ast.Name) and f.id == "dict" and expr.args:
                    arg = expr.args[0]
                    if isinstance(arg, (ast.GeneratorExp, ast.ListComp)):
                        return any(set_expr(unit, g.iter)
                                   for g in arg.generators)
                    return set_expr(unit, arg) or ord_expr(unit, arg)
                if isinstance(f, ast.Attribute) \
                        and f.attr in self.DICT_VIEWS:
                    return ord_expr(unit, f.value)
            return False

        changed = True
        while changed:
            changed = False
            for unit in units:
                for name, val in self._pairs(unit):
                    key = (id(unit), name)
                    if key not in sets and set_expr(unit, val):
                        sets.add(key)
                        changed = True
                    if key not in ords and ord_expr(unit, val):
                        ords.add(key)
                        changed = True
                if isinstance(unit, ast.AnnAssign) \
                        and isinstance(unit.target, ast.Name) \
                        and self._ann_is_set(unit.annotation):
                    key = (id(unit), unit.target.id)
                    if key not in sets:
                        sets.add(key)
                        changed = True
        self._set_expr = set_expr
        self._ord_expr = ord_expr
        return flow, sets, ords

    # -- sinks ---------------------------------------------------------------
    def _hazardous_iter(self, unit: ast.AST, it: ast.AST) -> bool:
        """The iterable's order is hash-dependent (set-tainted or a
        hash-ordered dict / its views), after unwrapping enumerate()."""
        if isinstance(it, ast.Call) and isinstance(it.func, ast.Name) \
                and it.func.id == "enumerate" and it.args:
            it = it.args[0]
        return self._set_expr(unit, it) or self._ord_expr(unit, it)

    def _body_is_order_sensitive(self, body: list[ast.stmt]) -> bool:
        """The loop body makes iteration order observable: ordered
        accumulation, first-match selection, emission, or keyed stores
        (insertion order of the result).  Pure set/counter building
        (``.add``, ``|=``, ``sum``) is order-insensitive and exempt."""
        for stmt in body:
            for sub in [stmt, *walk_in_scope(stmt)]:
                if isinstance(sub, (ast.Break, ast.Return,
                                    ast.Yield, ast.YieldFrom)):
                    return True
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in self.ORDERED_SINKS:
                    return True
                if isinstance(sub, ast.Assign) and any(
                        isinstance(t, ast.Subscript) for t in sub.targets):
                    return True
        return False

    def _blessed(self, unit: ast.AST) -> set[int]:
        """id()s of argument nodes handed DIRECTLY to an
        order-insensitive consumer (``sorted(list(s))`` &c)."""
        out: set[int] = set()
        for call in iter_calls(unit):
            f = call.func
            fname = f.id if isinstance(f, ast.Name) else ""
            if fname not in self.INSENSITIVE_CONSUMERS:
                continue
            if fname in ("min", "max") \
                    and any(kw.arg == "key" for kw in call.keywords):
                continue
            out.update(id(a) for a in call.args)
        return out

    _FIX = ("; iterate sorted(..., key=...) or document the stable "
            "order with a reasoned pragma")

    def check(self, mod: ModuleSource) -> Iterator[Violation]:
        for fn in iter_functions(mod.tree):
            # cheap pre-scan: any set-producing syntax or annotation at
            # all?  Most functions skip the dataflow entirely.
            if not self._prescan(fn):
                continue
            flow, sets, ords = self._analyze(fn)
            if not sets and not ords:
                continue
            for unit in flow.cfg.units():
                yield from self._judge_unit(mod, unit)

    def _prescan(self, fn: ast.AST) -> bool:
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.Set, ast.SetComp)):
                return True
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Name) \
                    and sub.func.id in self.SET_CONSTRUCTORS:
                return True
            if isinstance(sub, ast.arg) and sub.annotation is not None \
                    and self._ann_is_set(sub.annotation):
                return True
            if isinstance(sub, ast.AnnAssign) \
                    and self._ann_is_set(sub.annotation):
                return True
        return False

    def _judge_unit(self, mod: ModuleSource,
                    unit: ast.AST) -> Iterator[Violation]:
        if isinstance(unit, (ast.For, ast.AsyncFor)) \
                and self._hazardous_iter(unit, unit.iter) \
                and self._body_is_order_sensitive(unit.body):
            yield Violation(
                self.id, mod.relpath, unit.lineno,
                "for-loop over an unordered collection (set/frozenset "
                "or hash-ordered dict) with an order-sensitive body "
                "(append/yield/break/return/record/keyed store) — the "
                "decision depends on PYTHONHASHSEED" + self._FIX)
            return
        blessed = self._blessed(unit)
        for root in use_roots(unit):
            nodes = [root] if isinstance(
                root, (ast.Call, ast.ListComp, ast.GeneratorExp)) else []
            nodes += list(walk_in_scope(root))
            for sub in nodes:
                v = self._judge_expr(mod, unit, sub, blessed)
                if v is not None:
                    yield v

    def _judge_expr(self, mod: ModuleSource, unit: ast.AST, sub: ast.AST,
                    blessed: set[int]) -> Violation | None:
        if isinstance(sub, (ast.ListComp, ast.GeneratorExp)) \
                and id(sub) not in blessed \
                and any(self._hazardous_iter(unit, g.iter)
                        for g in sub.generators):
            return Violation(
                self.id, mod.relpath, sub.lineno,
                "comprehension over an unordered collection materializes "
                "hash order into a sequence" + self._FIX)
        if not isinstance(sub, ast.Call):
            return None
        f = sub.func
        if isinstance(f, ast.Name):
            if f.id in ("list", "tuple") and len(sub.args) == 1 \
                    and id(sub) not in blessed \
                    and self._hazardous_iter(unit, sub.args[0]):
                return Violation(
                    self.id, mod.relpath, sub.lineno,
                    f"{f.id}() over an unordered collection materializes "
                    "hash order into a sequence" + self._FIX)
            if f.id in ("min", "max") and sub.args \
                    and any(kw.arg == "key" for kw in sub.keywords) \
                    and self._hazardous_iter(unit, sub.args[0]):
                return Violation(
                    self.id, mod.relpath, sub.lineno,
                    f"{f.id}(..., key=) over an unordered collection "
                    "breaks ties by hash iteration order" + self._FIX)
            if f.id == "next" and sub.args \
                    and isinstance(sub.args[0], ast.Call) \
                    and isinstance(sub.args[0].func, ast.Name) \
                    and sub.args[0].func.id == "iter" \
                    and sub.args[0].args \
                    and self._hazardous_iter(unit, sub.args[0].args[0]):
                return Violation(
                    self.id, mod.relpath, sub.lineno,
                    "next(iter(...)) over an unordered collection picks a "
                    "hash-order-dependent element" + self._FIX)
        if isinstance(f, ast.Attribute):
            if f.attr == "pop" and not sub.args \
                    and self._set_expr(unit, f.value):
                return Violation(
                    self.id, mod.relpath, sub.lineno,
                    "set.pop() removes a hash-order-dependent element"
                    + self._FIX)
            if f.attr == "join" and len(sub.args) == 1 \
                    and self._hazardous_iter(unit, sub.args[0]):
                return Violation(
                    self.id, mod.relpath, sub.lineno,
                    "str.join() over an unordered collection materializes "
                    "hash order" + self._FIX)
        return None


# ---------------------------------------------------------------------------
# N012 — @invalidated_by, the static half
# ---------------------------------------------------------------------------


class InvalidationProtocol(Rule):
    """N012: declared watched-field mutations emit their invalidation
    event on every modeled path.

    Checked per ``@invalidated_by``-decorated class (see the module
    docstring for the mutation/emission/exemption model).  Cross-file
    half: the REQUIRED registry below pins the cache classes ROADMAP
    item 3's incremental rewrite depends on — a rename that silently
    drops the declaration is itself a violation, so the certification
    cannot rot into a no-op.
    """

    id = "N012"
    title = "@invalidated_by watched-field mutation without its event"
    scope = ("nos_tpu/",)
    exclude = ("nos_tpu/analysis/",)
    cross_file = True

    #: (module, class, what the declaration certifies) — these classes
    #: MUST carry @invalidated_by; see ROADMAP item 3
    REQUIRED = (
        ("nos_tpu.scheduler.cache", "SchedulerCache",
         "the watch-maintained node/pod indexes behind snapshot()"),
        ("nos_tpu.scheduler.scheduler", "Scheduler",
         "the cycle lister feeding the class-scan caches, and the "
         "window-busy map (_busy_map_cache) whose mutations must ride "
         "_mark_busy"),
        ("nos_tpu.partitioning.core.snapshot", "ClusterSnapshot",
         "the node map behind the epoch-memoised planner views"),
    )

    MUTATORS = frozenset({
        "append", "add", "insert", "extend", "appendleft", "pop",
        "popitem", "popleft", "clear", "update", "setdefault", "remove",
        "discard", "add_pod", "remove_pod",
    })
    EXEMPT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

    def __init__(self) -> None:
        # (module, class) -> (relpath, lineno, carries declaration)
        self._classes: dict[tuple[str, str], tuple[str, int, bool]] = {}
        self._required_mods = {m for m, _, _ in self.REQUIRED}
        self._seen_modules: set[str] = set()

    # -- per-file ------------------------------------------------------------
    def check(self, mod: ModuleSource) -> Iterator[Violation]:
        module = module_name_of(mod.relpath)
        if module in self._required_mods:
            self._seen_modules.add(module)
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            table, errs = self._decl_table(mod, cls)
            yield from errs
            if module in self._required_mods:
                self._classes[(module, cls.name)] = (
                    mod.relpath, cls.lineno, bool(table))
            if table:
                yield from self._check_class(mod, cls, table)

    @staticmethod
    def _is_decorator(func: ast.AST) -> bool:
        return (isinstance(func, ast.Name)
                and func.id == "invalidated_by") or (
            isinstance(func, ast.Attribute)
            and func.attr == "invalidated_by")

    def _decl_table(self, mod: ModuleSource, cls: ast.ClassDef) -> tuple[
            dict[str, str], list[Violation]]:
        table: dict[str, str] = {}
        errs: list[Violation] = []
        for deco in cls.decorator_list:
            if not (isinstance(deco, ast.Call)
                    and self._is_decorator(deco.func)):
                continue
            args = deco.args
            if not args or not all(
                    isinstance(a, ast.Constant) and isinstance(a.value, str)
                    for a in args):
                errs.append(Violation(
                    self.id, mod.relpath, deco.lineno,
                    "@invalidated_by arguments must be string literals — "
                    "the static checker cannot follow computed names"))
                continue
            if len(args) < 2:
                errs.append(Violation(
                    self.id, mod.relpath, deco.lineno,
                    "@invalidated_by declares an event but no watched "
                    "fields — the contract is a no-op; list the fields"))
                continue
            event = args[0].value
            for a in args[1:]:
                table[a.value] = event
        if table:
            errs.extend(self._check_events_exist(mod, cls, table))
        return table, errs

    def _check_events_exist(self, mod: ModuleSource, cls: ast.ClassDef,
                            table: dict[str, str]) -> Iterator[Violation]:
        """Each declared event must be a method of the class or an
        attribute its __init__ creates (counter form) — only checkable
        when the class has no bases that could supply it."""
        from .rules_flow import GuardedByDiscipline

        bases = [b for b in cls.bases
                 if dotted_name(b.value if isinstance(b, ast.Subscript)
                                else b).split(".")[-1]
                 not in ("object", "Generic", "Protocol")]
        if bases:
            return
        methods = {item.name for item in cls.body
                   if isinstance(item, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
        created = GuardedByDiscipline._attrs_created(cls)
        for event in sorted(set(table.values())):
            if event not in methods and event not in created:
                yield Violation(
                    self.id, mod.relpath, cls.lineno,
                    f"@invalidated_by names event {event!r} but "
                    f"{cls.name} defines no such method and __init__ "
                    "creates no such attribute — the declared protocol "
                    "cannot fire")

    # -- the dataflow check --------------------------------------------------
    def _check_class(self, mod: ModuleSource, cls: ast.ClassDef,
                     table: dict[str, str]) -> Iterator[Violation]:
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in self.EXEMPT_METHODS:
                continue
            # the event method IS the emitter: its own mutations of the
            # fields it invalidates are the protocol, not a breach
            fields = {f: e for f, e in table.items() if e != item.name}
            if not fields:
                continue
            if not self._mentions_fields(item, fields):
                continue
            yield from self._check_method(mod, cls, item, fields)

    @staticmethod
    def _mentions_fields(fn: ast.AST, fields: dict[str, str]) -> bool:
        return any(isinstance(sub, ast.Attribute) and sub.attr in fields
                   for sub in ast.walk(fn))

    def _check_method(self, mod: ModuleSource, cls: ast.ClassDef,
                      fn: ast.AST, fields: dict[str, str]
                      ) -> Iterator[Violation]:
        flow = FunctionFlow(fn)
        units = list(flow.cfg.units())
        alias, elem = self._aliases(flow, units, fields)

        def field_of(unit: ast.AST, name: str) -> str | None:
            for pool in (alias, elem):
                for u in flow.defs_of(unit, name):
                    fld = pool.get((u, name))
                    if fld is not None:
                        return fld
            return None

        def emission_pred(event: str, exclude: ast.AST | None = None):
            def is_emission(unit: ast.AST) -> bool:
                for call in iter_calls(unit):
                    if call is exclude:
                        continue
                    f = call.func
                    last = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else "")
                    if last == event:
                        return True
                targets: list[ast.AST] = []
                if isinstance(unit, ast.Assign):
                    targets = list(unit.targets)
                elif isinstance(unit, (ast.AugAssign, ast.AnnAssign)):
                    targets = [unit.target]
                return any(isinstance(t, ast.Attribute) and t.attr == event
                           for t in targets)
            return is_emission

        for unit in units:
            for fld, node, mut_call in self._mutations(unit, fields,
                                                       field_of):
                if emission_pred(fields[fld], exclude=mut_call)(unit):
                    continue
                if flow.always_reaches_after(
                        unit, emission_pred(fields[fld])):
                    continue
                yield Violation(
                    self.id, mod.relpath, node.lineno,
                    f"{cls.name}.{fld} is @invalidated_by"
                    f"({fields[fld]!r}) but this mutation in "
                    f"{getattr(fn, 'name', '?')}() has a path to return "
                    "with NO emission of the event — a derived cache "
                    "keyed on it goes stale; emit on every path or "
                    "rebuild the field wholesale")

    def _aliases(self, flow: FunctionFlow, units: list[ast.AST],
                 fields: dict[str, str]) -> tuple[
                     dict[tuple[int, str], str], dict[tuple[int, str], str]]:
        """Local names copying a watched field (``x = self._idx``) and
        one-hop element reads (``ni = x.get(k)`` / ``ni = x[k]``) —
        mutator calls through either count as field mutations."""
        alias: dict[tuple[int, str], str] = {}
        elem: dict[tuple[int, str], str] = {}

        def src_field(unit: ast.AST, expr: ast.AST) -> str | None:
            if isinstance(expr, ast.Attribute) \
                    and isinstance(expr.value, ast.Name) \
                    and expr.value.id == "self" and expr.attr in fields:
                return expr.attr
            if isinstance(expr, ast.Name):
                for u in flow.defs_of(unit, expr.id):
                    fld = alias.get((u, expr.id))
                    if fld is not None:
                        return fld
            return None

        changed = True
        while changed:
            changed = False
            for unit in units:
                for name, val in UnorderedIterationHazard._pairs(unit):
                    key = (id(unit), name)
                    fld = src_field(unit, val)
                    if fld is not None and alias.get(key) != fld:
                        alias[key] = fld
                        changed = True
                        continue
                    container: ast.AST | None = None
                    if isinstance(val, ast.Subscript):
                        container = val.value
                    elif isinstance(val, ast.Call) \
                            and isinstance(val.func, ast.Attribute) \
                            and val.func.attr == "get":
                        container = val.func.value
                    if container is not None:
                        fld = src_field(unit, container)
                        if fld is not None and elem.get(key) != fld:
                            elem[key] = fld
                            changed = True
        return alias, elem

    def _chain_field(self, node: ast.AST,
                     fields: dict[str, str]) -> tuple[str | None, bool]:
        """(watched field, chain-is-deep) for a target/receiver chain.
        Peels Attribute/Subscript AND call results
        (``self._idx.setdefault(k, {})[p] = v``); "deep" means the
        write goes THROUGH the field (mutation) rather than rebinding
        it (``self._idx = {}``, exempt)."""
        deep = False
        first_attr: str | None = None
        cur = node
        while True:
            if isinstance(cur, ast.Attribute):
                if first_attr is not None:
                    deep = True
                first_attr = cur.attr
                cur = cur.value
            elif isinstance(cur, ast.Subscript):
                deep = True
                first_attr = None
                cur = cur.value
            elif isinstance(cur, ast.Call):
                deep = True
                first_attr = None
                cur = cur.func
            else:
                break
        if isinstance(cur, ast.Name) and cur.id == "self" \
                and first_attr in fields:
            return first_attr, deep
        return None, deep

    def _mutations(self, unit: ast.AST, fields: dict[str, str],
                   field_of) -> Iterator[
                       tuple[str, ast.AST, ast.AST | None]]:
        """(field, anchor node, mutator call or None) per watched
        mutation in this unit."""
        targets: list[ast.AST] = []
        aug = False
        if isinstance(unit, ast.Assign):
            targets = list(unit.targets)
        elif isinstance(unit, ast.AugAssign):
            targets, aug = [unit.target], True
        elif isinstance(unit, ast.AnnAssign) and unit.value is not None:
            targets = [unit.target]
        elif isinstance(unit, ast.Delete):
            targets = list(unit.targets)
        flat: list[ast.AST] = []
        while targets:
            t = targets.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
            elif isinstance(t, ast.Starred):
                targets.append(t.value)
            else:
                flat.append(t)
        for t in flat:
            fld, deep = self._chain_field(t, fields)
            if fld is not None and (deep or aug):
                yield fld, t, None
                continue
            # writes through a local alias / element alias
            root = attr_chain_root(t)
            if isinstance(root, ast.Name) and root is not t:
                fld = field_of(unit, root.id)
                if fld is not None:
                    yield fld, t, None
        for call in iter_calls(unit):
            f = call.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in self.MUTATORS):
                continue
            fld, _ = self._chain_field(f.value, fields)
            if fld is None:
                root = attr_chain_root(f.value)
                if isinstance(root, ast.Name):
                    fld = field_of(unit, root.id)
            if fld is not None:
                yield fld, call, call

    # -- cross-file: the certification must stay live ------------------------
    def finalize(self) -> Iterator[Violation]:
        for module, cls_name, what in self.REQUIRED:
            if module not in self._seen_modules:
                continue                 # module not in this sweep's paths
            entry = self._classes.get((module, cls_name))
            relpath = module.replace(".", "/") + ".py"
            if entry is None:
                yield Violation(
                    self.id, relpath, 1,
                    f"N012 registry root {module}.{cls_name} no longer "
                    "resolves — it was renamed or moved; update "
                    "InvalidationProtocol.REQUIRED so the determinism "
                    "certification stays live")
            elif not entry[2]:
                yield Violation(
                    self.id, entry[0], entry[1],
                    f"{cls_name} maintains {what} but declares no "
                    "@invalidated_by protocol — every cross-cycle cache "
                    "source must name its invalidation event "
                    "(utils/guards.py; docs/static-analysis.md v3)")


def det_rules() -> list[Rule]:
    """Fresh instances of the determinism rules N011–N012."""
    return [UnorderedIterationHazard(), InvalidationProtocol()]
