"""noslint rules N007–N010: the dataflow-backed invariants.

These are the load-bearing contracts of the *parallel* decision plane
(ROADMAP item 1 shards planning across topology pools): each one is a
convention PR 2/3/4 wrote down in comments and docstrings, now enforced
by the dataflow engine (nos_tpu/analysis/dataflow.py) before the
parallel-shard planner turns conventions into race conditions.

- **N007** — COW escape: values from ``ClusterSnapshot.fork()`` /
  ``get_node_for_write()`` are only safe inside the fork's
  commit/revert scope; storing one on ``self``, returning/yielding it,
  or capturing it in an escaping closure detaches it from the dirty-set
  (``revert()`` restores the *snapshot's* object — the escaped alias
  keeps mutating a node no rollback can see).
- **N008** — cache-invalidation completeness: a write to a *watched*
  field (``.status.phase``, ``.spec.node_name``,
  ``.metadata.annotations[...]``, ``.metadata.labels[...]``) of an
  object obtained live from the API (``api.get``/``api.list``) must be
  post-dominated by an invalidation (API write-back, generation bump,
  or watch-event emission) on every modeled path — the PR 3
  vanished-pod class, where one early-out skipped the bump.
- **N009** — leaf-lock contract: ``DecisionJournal.record()`` and the
  tracer export paths must stay leaves — their transitive callee graph
  (cross-file, via the symbol index) must not reach ``api.*``,
  ``threading.*``, or another ``record()``/``emit()``, and under their
  own lock they may call nothing but ``self._push_locked``.
- **N010** — ``@guarded_by`` (nos_tpu/utils/guards.py): every write to
  a declared field must sit syntactically under ``with self.<lock>:``,
  or inside a ``*_locked`` method whose call sites are themselves
  checked.  The same declaration drives the dynamic check
  (``lockcheck.guard_state``) — one contract, two proofs.

Conservatism notes live on each rule; the shared principle: a rule only
convicts what it can *show* (a stored alias, a bump-free path, a banned
reachable call, an unlocked write site) — unresolved calls and nested
closures are documented blind spots covered by the dynamic half.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Iterator

from .core import ModuleSource, Rule, Violation
from .dataflow import (
    FunctionFlow, SymbolIndex, attr_chain_root, dotted_name, escapes,
    iter_calls, iter_functions, module_name_of, walk_in_scope,
)

# ---------------------------------------------------------------------------
# N007 — COW escape
# ---------------------------------------------------------------------------


class CowEscape(Rule):
    """N007: fork-scoped COW references must not outlive the fork."""

    id = "N007"
    title = "COW node/fork reference escapes its commit/revert scope"
    scope = ("nos_tpu/",)
    # the snapshot itself RETURNS these objects — that is the mechanism
    exclude = ("nos_tpu/partitioning/core/snapshot.py",
               "nos_tpu/analysis/")

    SOURCES = frozenset({"fork", "get_node_for_write"})

    _KIND_MSG = {
        "stored-on-self": "stored on {detail} — the alias outlives the "
                          "fork and revert() cannot restore through it",
        "returned": "returned from the function — it leaves the fork's "
                    "commit/revert scope",
        "yielded": "yielded — the consumer sees it after commit/revert "
                   "may have replaced the snapshot's object",
        "stored-global": "stored in module global {detail} — the alias "
                         "outlives every fork scope",
        "closure": "captured by an escaping closure ({detail}) — it can "
                   "run after the fork is gone",
    }

    def _is_source(self, call: ast.Call) -> bool:
        func = call.func
        if not isinstance(func, ast.Attribute):
            return False
        if func.attr not in self.SOURCES:
            return False
        recv = dotted_name(func.value)
        return recv.split(".")[0] not in ("os", "multiprocessing")

    def check(self, mod: ModuleSource) -> Iterator[Violation]:
        for fn in iter_functions(mod.tree):
            if not any(self._is_source(c) for c in ast.walk(fn)
                       if isinstance(c, ast.Call)):
                continue
            for esc in escapes(fn, self._is_source):
                template = self._KIND_MSG[esc.kind]
                yield Violation(
                    self.id, mod.relpath, esc.unit.lineno,
                    f"COW reference {esc.name!r} (from fork()/"
                    "get_node_for_write()) "
                    + template.format(detail=esc.detail)
                    + "; keep it local to the fork scope")


# ---------------------------------------------------------------------------
# N008 — cache-invalidation completeness
# ---------------------------------------------------------------------------


class CacheInvalidation(Rule):
    """N008: watched-field writes on live API objects need an
    invalidation on every path.

    "Live" is dataflow-derived: the written object's name must reach the
    write from an ``api.get(...)`` / ``api.list(...)`` definition
    (including iteration targets and name copies).  Writes through
    deep copies, constructor results, or function parameters are not
    convicted — a mutate-callback's parameter is the substrate's object
    and the substrate emits the event after invoking it, which is why
    the scheduler's ``def mutate(p)`` closures stay clean.  The
    post-domination check runs on the CFG's modeled paths only
    (exceptions escaping the function are not paths — see build_cfg).
    """

    id = "N008"
    title = "watched-field write without invalidation on every path"
    scope = ("nos_tpu/scheduler/", "nos_tpu/partitioning/",
             "nos_tpu/kube/")
    # the substrate emits watch events itself; its direct store writes
    # ARE the invalidation everyone else must pair with
    exclude = ("nos_tpu/kube/client.py", "nos_tpu/kube/rest.py",
               "nos_tpu/kube/objects.py")

    #: attribute tails that watch consumers key on
    WATCHED_ATTRS = (("status", "phase"), ("spec", "node_name"))
    WATCHED_DICTS = (("metadata", "annotations"), ("metadata", "labels"))
    DICT_MUTATORS = frozenset({"pop", "update", "setdefault", "clear"})

    #: a call whose last segment is one of these counts as invalidation
    INVALIDATORS = frozenset({
        "retry_on_conflict", "_patch_pod", "assume",
        "bump", "_bump", "_bump_locked", "_bump_node", "bump_node",
        "notify", "_notify", "emit", "_emit", "_emit_event",
    })
    #: generic CRUD verbs invalidate ONLY on an api receiver — `update`
    #: is also a dict mutator and `delete` a common method name; an
    #: unqualified match would let `labels.update(...)` silence the rule
    API_VERBS = frozenset({"patch", "update", "create", "delete"})

    @staticmethod
    def _is_api_read(call: ast.Call) -> bool:
        func = call.func
        if not isinstance(func, ast.Attribute) \
                or func.attr not in ("get", "list"):
            return False
        recv = dotted_name(func.value)
        last = recv.split(".")[-1] if recv else ""
        return last in ("api", "_api")

    def _live_defs(self, flow: FunctionFlow) -> set[int]:
        """Unit ids defining names that hold live API objects."""
        live: set[int] = set()
        units = list(flow.cfg.units())
        changed = True
        while changed:
            changed = False
            for unit in units:
                if id(unit) in live:
                    continue
                # every value position that can carry a live object:
                # plain/annotated assigns (mypy strict pushes scheduler
                # code toward `pod: Pod = api.get(...)`), tuple-valued
                # assigns, and loop iterables
                vals: list[ast.AST] = []
                if isinstance(unit, ast.Assign):
                    vals = (list(unit.value.elts)
                            if isinstance(unit.value, (ast.Tuple, ast.List))
                            else [unit.value])
                elif isinstance(unit, ast.AnnAssign) \
                        and unit.value is not None:
                    vals = [unit.value]
                elif isinstance(unit, (ast.For, ast.AsyncFor)):
                    vals = [unit.iter]
                for val in vals:
                    if isinstance(val, ast.Subscript):
                        # `pods[0]` pulls a live element out of a live
                        # list — same object, same staleness hazard
                        val = val.value
                    if (isinstance(val, ast.Call)
                            and self._is_api_read(val)) or (
                            isinstance(val, ast.Name)
                            and flow.defs_of(unit, val.id) & live):
                        live.add(id(unit))
                        changed = True
                        break
        return live

    def _watched_write(
            self, unit: ast.AST) -> tuple[ast.Name, str, ast.Call | None] | None:
        """(root name node, field description, the mutator call or None)
        when this unit writes a watched field, else None.  The call is
        carried so the invalidation check can exclude it — `labels.pop`
        shares its NAME with api-verb invalidators and must not count
        as invalidating the very write it is."""
        targets: list[ast.AST] = []
        if isinstance(unit, ast.Assign):
            targets = list(unit.targets)
        elif isinstance(unit, ast.AugAssign):
            targets = [unit.target]
        elif isinstance(unit, ast.AnnAssign) and unit.value is not None:
            targets = [unit.target]
        elif isinstance(unit, ast.Delete):
            targets = list(unit.targets)
        for t in targets:
            hit = self._match_watched(t)
            if hit:
                return hit[0], hit[1], None
        if isinstance(unit, ast.Expr) and isinstance(unit.value, ast.Call):
            call = unit.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in self.DICT_MUTATORS:
                hit = self._match_watched_dict(call.func.value)
                if hit:
                    return hit[0], hit[1], call
        return None

    def _match_watched(self, target: ast.AST) -> tuple[ast.Name, str] | None:
        if isinstance(target, ast.Attribute):
            if isinstance(target.value, ast.Attribute):
                pair = (target.value.attr, target.attr)
                # a watched DICT matched as a whole-attribute target is
                # its most drastic write: `pod.metadata.labels = {...}`
                if pair in self.WATCHED_ATTRS \
                        or pair in self.WATCHED_DICTS:
                    root = attr_chain_root(target)
                    if isinstance(root, ast.Name):
                        return root, ".".join(pair)
        if isinstance(target, ast.Subscript):
            return self._match_watched_dict(target.value)
        return None

    def _match_watched_dict(self, value: ast.AST) -> tuple[ast.Name, str] | None:
        if isinstance(value, ast.Attribute) \
                and isinstance(value.value, ast.Attribute):
            pair = (value.value.attr, value.attr)
            if pair in self.WATCHED_DICTS:
                root = attr_chain_root(value)
                if isinstance(root, ast.Name):
                    return root, ".".join(pair)
        return None

    #: "_gen", "gen", "generation(s)", "node_gen" — but not "agenda" or
    #: "regenerate_hint": the bump-counter match is boundary-anchored
    _GEN_RE = re.compile(r"(^|_)gen(eration)?s?($|_)")

    def _is_invalidation(self, unit: ast.AST,
                         exclude: ast.Call | None = None) -> bool:
        # iter_calls walks a unit's own expressions only — compound
        # units (If/While/For headers) expose their headers, never
        # their bodies (those are separate CFG units)
        for sub in iter_calls(unit):
            if sub is exclude:
                continue
            func = sub.func
            last = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else "")
            if last in self.INVALIDATORS:
                return True
            if last in self.API_VERBS and isinstance(func, ast.Attribute):
                recv = dotted_name(func.value)
                if (recv.split(".")[-1] if recv else "") in ("api", "_api"):
                    return True
        # writing a generation counter directly also invalidates
        targets: list[ast.AST] = []
        if isinstance(unit, ast.Assign):
            targets = list(unit.targets)
        elif isinstance(unit, ast.AugAssign):
            targets = [unit.target]
        for t in targets:
            if isinstance(t, ast.Subscript) and self._GEN_RE.search(
                    dotted_name(t.value).split(".")[-1] or ""):
                return True
            if isinstance(t, ast.Attribute) and self._GEN_RE.search(t.attr):
                return True
        return False

    def check(self, mod: ModuleSource) -> Iterator[Violation]:
        for fn in iter_functions(mod.tree):
            # cheap pre-scan: only build the CFG where a watched write
            # even appears (most functions skip the dataflow entirely)
            if not any(self._watched_write(s) is not None
                       for s in ast.walk(fn)
                       if isinstance(s, (ast.Assign, ast.AugAssign,
                                         ast.AnnAssign, ast.Expr,
                                         ast.Delete))):
                continue
            flow = FunctionFlow(fn)
            live = self._live_defs(flow)
            if not live:
                continue
            for unit in flow.cfg.units():
                hit = self._watched_write(unit)
                if hit is None:
                    continue
                root, field, write_call = hit
                if not (flow.defs_of(unit, root.id) & live):
                    continue            # not a live API object
                if self._is_invalidation(unit, exclude=write_call):
                    continue
                if flow.always_reaches_after(unit, self._is_invalidation):
                    continue
                yield Violation(
                    self.id, mod.relpath, unit.lineno,
                    f"write to watched field {root.id}.{field} of a live "
                    "API object has a path to return with NO invalidation "
                    "(api write-back, generation bump, or event emission) "
                    "— stale-cache hazard; bump/emit on every path or "
                    "mutate a copy")


# ---------------------------------------------------------------------------
# N009 — leaf-lock contract (cross-file)
# ---------------------------------------------------------------------------


class LeafLockContract(Rule):
    """N009: the journal/tracer export paths stay leaf locks.

    Instrumentation must never add a lock-order edge: any code path may
    call ``record()`` while holding any lock, so ``record()`` itself
    must reach no other lock-ordered subsystem.  ``check`` only feeds
    the symbol index; the verdicts come from ``finalize`` once the whole
    tree is indexed.  Unresolvable calls are judged by their dotted
    pattern only — the documented blind spot the lockcheck'd chaos soak
    covers at runtime.
    """

    id = "N009"
    title = "journal/tracer leaf-lock contract breach"
    scope = ("nos_tpu/",)
    cross_file = True

    ROOTS = (
        ("nos_tpu.obs.journal", "DecisionJournal.record"),
        ("nos_tpu.obs.trace", "_SpanHandle.__exit__"),
        ("nos_tpu.obs.trace", "RingExporter.export"),
    )
    BANNED_ATTRS = frozenset({"record", "emit"})
    UNDER_LOCK_OK = frozenset({"len", "list", "dict", "tuple", "min",
                               "max", "id"})

    def __init__(self) -> None:
        self.index = SymbolIndex()
        self._mods: dict[str, ModuleSource] = {}

    def check(self, mod: ModuleSource) -> Iterable[Violation]:
        self.index.add_module(mod.relpath, mod.tree)
        self._mods[module_name_of(mod.relpath)] = mod
        return ()

    def _banned(self, call: ast.Call,
                resolved: tuple[str, str] | None) -> str:
        dotted = dotted_name(call.func)
        segs = dotted.split(".") if dotted else []
        if segs and any(s in ("api", "_api") for s in segs[:-1]):
            return f"reaches the API client ({dotted})"
        if dotted.startswith("threading."):
            return f"reaches threading ({dotted})"
        last = segs[-1] if segs else ""
        if last in self.BANNED_ATTRS:
            return f"re-enters a journal/exporter ({dotted}())"
        if resolved is not None and resolved[1].split(".")[-1] \
                in self.BANNED_ATTRS:
            return (f"re-enters a journal/exporter "
                    f"({resolved[0]}.{resolved[1]})")
        return ""

    def finalize(self) -> Iterator[Violation]:
        # a root whose MODULE was indexed but whose function is gone was
        # renamed or moved — without this, the refactor silently voids
        # the whole certification (noslint exits 0 checking nothing)
        for mod_name, qual in self.ROOTS:
            if (mod_name, qual) not in self.index.functions \
                    and mod_name in self._mods:
                m = self._mods[mod_name]
                yield Violation(
                    self.id, m.relpath, 1,
                    f"leaf-lock contract root {mod_name}.{qual} no "
                    "longer resolves — it was renamed or moved; update "
                    "LeafLockContract.ROOTS so the certification stays "
                    "live")
        seen: set[tuple[str, str]] = set()
        work = [r for r in self.ROOTS if r in self.index.functions]
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            sym = self.index.functions[key]
            mod = self._mods.get(sym.module)
            relpath = mod.relpath if mod else sym.module
            for call, resolved in self.index.callees(key):
                why = self._banned(call, resolved)
                if why:
                    via = ""
                    if key not in self.ROOTS:
                        via = f" (reached via {'.'.join(key)})"
                    yield Violation(
                        self.id, relpath, call.lineno,
                        f"leaf-lock contract: {key[1]} {why}{via} — "
                        "record()/export must stay a leaf so "
                        "instrumenting any call site can never add a "
                        "lock-order edge")
                    continue
                if resolved is not None and resolved not in seen:
                    work.append(resolved)
        # under-lock strictness: the roots' own `with self._lock:` body
        # may call nothing but self._push_locked (+ trivial builtins)
        for key in self.ROOTS:
            sym = self.index.functions.get(key)
            if sym is None:
                continue
            mod = self._mods.get(sym.module)
            relpath = mod.relpath if mod else sym.module
            for node in ast.walk(sym.node):
                if not isinstance(node, (ast.With, ast.AsyncWith)):
                    continue
                if not any(dotted_name(i.context_expr).endswith("_lock")
                           for i in node.items):
                    continue
                for stmt in node.body:
                    for sub in walk_in_scope(stmt):
                        if not isinstance(sub, ast.Call):
                            continue
                        dotted = dotted_name(sub.func)
                        if dotted == "self._push_locked" or \
                                dotted in self.UNDER_LOCK_OK:
                            continue
                        yield Violation(
                            self.id, relpath, sub.lineno,
                            f"{key[1]} calls {dotted or '<expr>'}() under "
                            "its own lock — the leaf contract allows only "
                            "the bare append (self._push_locked); move "
                            "this call outside the critical section")


# ---------------------------------------------------------------------------
# N010 — @guarded_by, the static half
# ---------------------------------------------------------------------------


class GuardedByDiscipline(Rule):
    """N010: declared guarded fields are written only under their lock.

    Checked per decorated class:

    - every write (assign / augassign / subscript / attribute-through /
      known container mutators / del) to a declared field must be inside
      ``with self.<lock>:`` — except in ``__init__``/``__post_init__``
      (pre-publication) and in methods named ``*_locked`` (the
      caller-holds-lock convention);
    - every call of a ``self.*_locked()`` method must itself be under
      the lock (or inside another ``*_locked`` method / ``__init__``);
    - the declared lock attribute must actually be created in
      ``__init__`` (or at class level);
    - decorator arguments must be string literals — the contract is
      static or it is nothing.

    Writes inside nested defs/lambdas are not judged (deferred
    execution); the dynamic half convicts those at runtime.
    """

    id = "N010"
    title = "@guarded_by field written without its lock"
    scope = ("nos_tpu/",)
    exclude = ("nos_tpu/analysis/",)

    MUTATORS = frozenset({
        "append", "add", "insert", "extend", "appendleft", "pop",
        "popitem", "popleft", "clear", "update", "setdefault", "remove",
        "discard", "__setitem__",
    })
    EXEMPT_METHODS = frozenset({"__init__", "__post_init__", "__new__",
                                "__del__"})

    def check(self, mod: ModuleSource) -> Iterator[Violation]:
        for cls in ast.walk(mod.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(mod, cls)
        yield from self._external_locked_calls(mod)

    # -- external *_locked call sites ---------------------------------------
    def _external_locked_calls(self, mod: ModuleSource) -> Iterator[Violation]:
        """`other._bump_locked()` from OUTSIDE the owning class must sit
        under a ``with`` on that same receiver (``with other._lock:``) —
        the in-class self.* form is judged precisely against the
        declared lock by _locked_call_sites; this is the syntactic
        best-effort for every other caller, so the convention the docs
        promise ('a future unlocked caller is a tier-1 failure') holds
        across class and module boundaries too."""
        for fn in iter_functions(mod.tree):
            if fn.name.endswith("_locked") or fn.name in self.EXEMPT_METHODS:
                continue
            yield from self._scan_external(mod, fn, fn.body, frozenset())

    def _scan_external(self, mod: ModuleSource, fn: ast.AST,
                       body: Iterable[ast.stmt],
                       held: frozenset[str]) -> Iterator[Violation]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                 # nested scopes scanned on their own
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly = {dotted_name(i.context_expr)
                         for i in stmt.items if dotted_name(i.context_expr)}
                yield from self._scan_external(mod, fn, stmt.body,
                                               held | frozenset(newly))
                continue
            for sub in iter_calls(stmt):
                if not (isinstance(sub.func, ast.Attribute)
                        and sub.func.attr.endswith("_locked")):
                    continue
                recv = dotted_name(sub.func.value)
                if not recv or recv == "self":
                    continue             # in-class form: precise check
                if any(h == recv or h.startswith(recv + ".")
                       for h in held):
                    continue             # a lock on that receiver is held
                yield Violation(
                    self.id, mod.relpath, sub.lineno,
                    f"call to {recv}.{sub.func.attr}() without a "
                    f"`with {recv}.<lock>:` in scope — *_locked methods "
                    "assume their caller holds the owning object's lock "
                    "(the convention N010 certifies)")
            for child_body in self._child_bodies(stmt):
                yield from self._scan_external(mod, fn, child_body, held)

    # -- per-class ----------------------------------------------------------
    def _check_class(self, mod: ModuleSource,
                     cls: ast.ClassDef) -> Iterator[Violation]:
        table: dict[str, str] = {}       # field -> lock attr
        for deco in cls.decorator_list:
            if not (isinstance(deco, ast.Call)
                    and self._is_guarded_by(deco.func)):
                continue
            args = deco.args
            if not args or not all(
                    isinstance(a, ast.Constant) and isinstance(a.value, str)
                    for a in args):
                yield Violation(
                    self.id, mod.relpath, deco.lineno,
                    "@guarded_by arguments must be string literals — "
                    "the static checker cannot follow computed names")
                continue
            if len(args) < 2:
                # guards.guarded_by raises this at import time too; the
                # static half flags it so a never-imported module can't
                # carry a vacuous contract
                yield Violation(
                    self.id, mod.relpath, deco.lineno,
                    "@guarded_by declares a lock but no fields — the "
                    "contract is a no-op; list the guarded fields")
                continue
            lock = args[0].value
            for a in args[1:]:
                table[a.value] = lock
        if not table:
            return
        locks = set(table.values())

        # the declared lock(s) must exist — only checkable when the class
        # has no bases that could create it (DecisionJournal's _lock
        # comes from BoundedRing; cross-file inheritance is out of a
        # per-file rule's sight)
        bases = [b for b in cls.bases
                 if dotted_name(b.value if isinstance(b, ast.Subscript)
                                else b).split(".")[-1]
                 not in ("object", "Generic", "Protocol")]
        if not bases:
            created = self._attrs_created(cls)
            for lock in sorted(locks):
                if lock not in created:
                    yield Violation(
                        self.id, mod.relpath, cls.lineno,
                        f"@guarded_by names lock attribute {lock!r} but "
                        f"{cls.name}.__init__ never creates it")

        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if item.name in self.EXEMPT_METHODS:
                continue
            held_free = item.name.endswith("_locked")
            yield from self._scan(mod, cls, item, item.body, table,
                                  frozenset(locks) if held_free
                                  else frozenset())

    @staticmethod
    def _is_guarded_by(func: ast.AST) -> bool:
        return (isinstance(func, ast.Name) and func.id == "guarded_by") or (
            isinstance(func, ast.Attribute) and func.attr == "guarded_by")

    @staticmethod
    def _attrs_created(cls: ast.ClassDef) -> set[str]:
        out: set[str] = set()
        for item in cls.body:
            if isinstance(item, ast.Assign):
                out.update(t.id for t in item.targets
                           if isinstance(t, ast.Name))
            if isinstance(item, ast.AnnAssign) and item.value is not None \
                    and isinstance(item.target, ast.Name):
                out.add(item.target.id)
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and item.name in ("__init__", "__post_init__"):
                for node in ast.walk(item):
                    if isinstance(node, ast.Attribute) \
                            and isinstance(node.ctx, ast.Store) \
                            and isinstance(node.value, ast.Name) \
                            and node.value.id == "self":
                        out.add(node.attr)
        return out

    # -- recursive body scan with lock context ------------------------------
    def _scan(self, mod: ModuleSource, cls: ast.ClassDef,
              method: ast.AST, body: Iterable[ast.stmt],
              table: dict[str, str],
              held: frozenset[str]) -> Iterator[Violation]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                 # deferred: dynamic half's job
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly = {dotted_name(i.context_expr)[len("self."):]
                         for i in stmt.items
                         if dotted_name(i.context_expr).startswith("self.")}
                yield from self._scan(mod, cls, method, stmt.body, table,
                                      held | frozenset(newly))
                continue
            for v in self._stmt_writes(stmt, table, held):
                field, lock, node = v
                yield Violation(
                    self.id, mod.relpath, node.lineno,
                    f"{cls.name}.{field} is @guarded_by({lock!r}) but "
                    f"this write in {getattr(method, 'name', '?')}() is "
                    f"not under `with self.{lock}:` — take the lock, or "
                    "move the write into a *_locked helper whose callers "
                    "hold it")
            yield from self._locked_call_sites(mod, cls, method, stmt,
                                               table, held)
            # recurse into compound statements (if/for/try/...)
            for child_body in self._child_bodies(stmt):
                yield from self._scan(mod, cls, method, child_body,
                                      table, held)

    @staticmethod
    def _child_bodies(stmt: ast.stmt) -> Iterator[list[ast.stmt]]:
        for name in ("body", "orelse", "finalbody"):
            val = getattr(stmt, name, None)
            if isinstance(val, list) and val \
                    and isinstance(val[0], ast.stmt):
                yield val
        for h in getattr(stmt, "handlers", []) or []:
            yield h.body
        for c in getattr(stmt, "cases", []) or []:
            yield c.body

    def _stmt_writes(self, stmt: ast.stmt, table: dict[str, str],
                     held: frozenset[str]) -> Iterator[
                         tuple[str, str, ast.AST]]:
        """(field, lock, node) for each unlocked guarded write in the
        statement's own expressions (compound headers included; nested
        bodies handled by _scan's recursion)."""
        targets: list[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if not (isinstance(stmt, ast.AnnAssign) and stmt.value is None):
                targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        # flatten tuple/list destructuring: `self._a, self._b = ...`
        # writes both declared fields
        flat: list[ast.AST] = []
        while targets:
            t = targets.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
            elif isinstance(t, ast.Starred):
                targets.append(t.value)
            else:
                flat.append(t)
        for t in flat:
            field = self._guarded_field_of(t, table)
            if field and table[field] not in held:
                yield field, table[field], t
        # container mutators in the statement's OWN expressions (compound
        # statements contribute their headers only — their bodies are
        # re-scanned by _scan's recursion, once)
        for sub in iter_calls(stmt):
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in self.MUTATORS:
                field = self._guarded_field_of(sub.func.value, table)
                if field and table[field] not in held:
                    yield field, table[field], sub

    @staticmethod
    def _guarded_field_of(target: ast.AST,
                          table: dict[str, str]) -> str | None:
        """The declared field a write target touches: the FIRST attribute
        off ``self`` in the chain (``self._gen[k]``, ``self._x.y = ...``,
        ``self._items.append``)."""
        node = target
        first_attr: str | None = None
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            if isinstance(node, ast.Attribute):
                first_attr = node.attr
            node = node.value
        if isinstance(node, ast.Name) and node.id == "self" \
                and first_attr in table:
            return first_attr
        return None

    def _locked_call_sites(self, mod: ModuleSource, cls: ast.ClassDef,
                           method: ast.AST, stmt: ast.stmt,
                           table: dict[str, str],
                           held: frozenset[str]) -> Iterator[Violation]:
        if held:
            return
        for sub in iter_calls(stmt):
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr.endswith("_locked") \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id == "self":
                yield Violation(
                    self.id, mod.relpath, sub.lineno,
                    f"call to self.{sub.func.attr}() outside "
                    f"`with self.{sorted(set(table.values()))[0]}:` — "
                    "*_locked methods assume their caller holds the "
                    "lock (the convention N010 certifies)")


def flow_rules() -> list[Rule]:
    """Fresh instances of the dataflow rules N007–N010."""
    return [CowEscape(), CacheInvalidation(), LeafLockContract(),
            GuardedByDiscipline()]
