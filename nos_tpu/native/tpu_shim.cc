// nos_tpu native device shim.
//
// The single native component of the framework (SURVEY.md §2: the analog of
// the reference's CGo/NVML boundary, pkg/gpu/nvml/client.go — there the one
// piece that must talk to a C driver; here the piece that talks to the TPU
// runtime).  In production this wraps libtpu topology introspection and the
// Cloud TPU API's slice lifecycle; the device bookkeeping, placement search
// and geometry validation below are the real algorithms either way, and the
// in-memory device table stands in for the runtime calls (exactly as the
// reference isolates NVML behind an interface so everything above is
// testable without hardware).
//
// Exposed as a plain C ABI consumed via ctypes (nos_tpu/device/native.py);
// no pybind11 dependency.
//
// Placement search: a slice shape is placed into the host chip block
// (≤ 3-D, tiny cell count) by exact bitmask cover with orientation
// permutations and backtracking — the analog of the reference's NVML
// creation-order permutation search (pkg/gpu/nvml/client.go:286-340), but
// exhaustive instead of capped at 20 attempts: blocks are ≤ 8 cells, so
// exhaustive search is both exact and fast.

#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int kMaxDims = 3;

struct Shape {
  int dims[kMaxDims];  // padded with 1s
  int ndims;

  int chips() const {
    int c = 1;
    for (int i = 0; i < kMaxDims; ++i) c *= dims[i];
    return c;
  }
  std::string name() const {
    std::ostringstream os;
    for (int i = 0; i < ndims; ++i) {
      if (i) os << 'x';
      os << dims[i];
    }
    return os.str();
  }
};

struct Device {
  std::string id;
  int unit;
  Shape shape;      // canonical (as requested)
  uint64_t mask;    // occupied cells of the unit's block; 0 = whole host
                    // dedicated (multi-host shard)
  bool multihost;
  int offset[kMaxDims];
  int placed_dims[kMaxDims];
};

struct Runtime {
  std::mutex mu;
  Shape host_block;
  std::string accel;
  int next_id = 1;
  std::map<std::string, Device> devices;
};

uint64_t cell_bit(const int* coord, const int* block) {
  int idx = 0;
  for (int i = 0; i < kMaxDims; ++i) idx = idx * block[i] + coord[i];
  return 1ull << idx;
}

// All aligned placements of oriented `dims` within `block` as bitmasks.
void placements_for(const int* dims, const int* block,
                    std::vector<std::pair<uint64_t, int[kMaxDims]>>*) = delete;

struct Candidate {
  uint64_t mask;
  int offset[kMaxDims];
  int dims[kMaxDims];
};

// Aligned enumeration: an oriented shape with dims d sits only at offsets
// o with o[i] % d[i] == 0 — the same shape-aligned discipline as the
// Python packer (topology/packing.py), so native and Python searches agree
// exactly on feasibility and produce interchangeable placements.
void enumerate_orientation(const int* dims, const int* block,
                           std::vector<Candidate>* out) {
  int limit[kMaxDims];
  for (int i = 0; i < kMaxDims; ++i) {
    if (dims[i] > block[i]) return;
    limit[i] = block[i] - dims[i];
  }
  for (int x = 0; x <= limit[0]; x += dims[0])
    for (int y = 0; y <= limit[1]; y += dims[1])
      for (int z = 0; z <= limit[2]; z += dims[2]) {
        Candidate c{};
        c.offset[0] = x; c.offset[1] = y; c.offset[2] = z;
        std::memcpy(c.dims, dims, sizeof(c.dims));
        uint64_t m = 0;
        for (int dx = 0; dx < dims[0]; ++dx)
          for (int dy = 0; dy < dims[1]; ++dy)
            for (int dz = 0; dz < dims[2]; ++dz) {
              int coord[kMaxDims] = {x + dx, y + dy, z + dz};
              m |= cell_bit(coord, block);
            }
        c.mask = m;
        out->push_back(c);
      }
}

std::vector<Candidate> candidates_for(const Shape& s, const Shape& block) {
  std::vector<Candidate> out;
  int d[kMaxDims];
  std::memcpy(d, s.dims, sizeof(d));
  std::sort(d, d + kMaxDims);
  std::set<uint64_t> seen;  // dedupe identical masks across orientations
  do {
    std::vector<Candidate> tmp;
    enumerate_orientation(d, block.dims, &tmp);
    for (auto& c : tmp)
      if (seen.insert(c.mask).second) out.push_back(c);
  } while (std::next_permutation(d, d + kMaxDims));
  return out;
}

// Exact backtracking placement of `shapes` around `occupied`.
bool place_all(const std::vector<Shape>& shapes, size_t i, uint64_t occupied,
               const Shape& block, std::vector<Candidate>* chosen) {
  if (i == shapes.size()) return true;
  for (const auto& c : candidates_for(shapes[i], block)) {
    if (c.mask & occupied) continue;
    chosen->push_back(c);
    if (place_all(shapes, i + 1, occupied | c.mask, block, chosen))
      return true;
    chosen->pop_back();
  }
  return false;
}

int write_out(const std::string& s, char* out, int cap) {
  if ((int)s.size() + 1 > cap) return -2;  // buffer too small
  std::memcpy(out, s.c_str(), s.size() + 1);
  return 0;
}

int first_empty_cell(uint64_t occ, int total) {
  for (int i = 0; i < total; ++i)
    if (!(occ & (1ull << i))) return i;
  return -1;
}

// Exact multiset packer with the Python packer's exact semantics
// (topology/packing.py:_pack_masks): first-empty-cell driven backtracking
// over aligned candidate placements, largest shapes first, with the
// skip-cell branch when a full tiling is not required.
struct PackEntry {
  Shape shape;        // canonical
  int count;
  std::vector<Candidate> cands;
};

bool pack_rec(std::vector<PackEntry>& entries, uint64_t occ, int total,
              bool require_full, const uint64_t full_mask,
              std::vector<std::pair<int, Candidate>>* acc) {
  bool all_done = true;
  for (auto& e : entries)
    if (e.count > 0) { all_done = false; break; }
  if (all_done) return !require_full || occ == full_mask;
  int cell = first_empty_cell(occ, total);
  if (cell == -1) return false;
  uint64_t cell_bit = 1ull << cell;
  for (size_t i = 0; i < entries.size(); ++i) {
    auto& e = entries[i];
    if (e.count == 0) continue;
    for (const auto& c : e.cands) {
      if (!(c.mask & cell_bit) || (c.mask & occ)) continue;
      --e.count;
      acc->push_back({(int)i, c});
      if (pack_rec(entries, occ | c.mask, total, require_full, full_mask,
                   acc))
        return true;
      acc->pop_back();
      ++e.count;
    }
  }
  if (!require_full)
    return pack_rec(entries, occ | cell_bit, total, require_full, full_mask,
                    acc);
  return false;
}

}  // namespace

extern "C" {

void* nos_runtime_new(const char* accel, const int* host_block, int ndims) {
  if (ndims < 1 || ndims > kMaxDims) return nullptr;
  auto* rt = new Runtime();
  rt->accel = accel ? accel : "";
  rt->host_block.ndims = ndims;
  for (int i = 0; i < kMaxDims; ++i)
    rt->host_block.dims[i] = i < ndims ? host_block[i] : 1;
  return rt;
}

void nos_runtime_free(void* h) { delete static_cast<Runtime*>(h); }

int nos_runtime_chips_per_host(void* h) {
  return static_cast<Runtime*>(h)->host_block.chips();
}

// shapes: flat array of n*3 ints (padded with 1s).  On success writes
// newline-separated device ids and returns the count; -1 = cannot place,
// -2 = output buffer too small, -3 = bad arguments.  All-or-nothing.
int nos_runtime_create_slices(void* h, int unit, const int* shapes_flat,
                              int n, char* out, int out_cap) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lock(rt->mu);
  if (n <= 0) return -3;

  std::vector<Shape> shapes(n);
  bool any_multi = false;
  for (int i = 0; i < n; ++i) {
    shapes[i].ndims = rt->host_block.ndims;
    int chips = 1;
    for (int d = 0; d < kMaxDims; ++d) {
      shapes[i].dims[d] = shapes_flat[i * kMaxDims + d];
      if (shapes[i].dims[d] < 1) return -3;
      chips *= shapes[i].dims[d];
    }
    // restore caller dim count for naming: trailing 1s beyond ndims kept
    if (chips > rt->host_block.chips()) any_multi = true;
  }

  uint64_t occupied = 0;
  int unit_devices = 0;
  for (auto& [id, d] : rt->devices)
    if (d.unit == unit) {
      occupied |= d.mask;
      ++unit_devices;
      if (d.multihost) occupied = ~0ull;
    }

  std::ostringstream ids;
  if (any_multi) {
    // a multi-host shard takes this host's entire block as its share
    if (n != 1 || unit_devices > 0) return -1;
    Device dev{};
    dev.unit = unit;
    dev.shape = shapes[0];
    dev.multihost = true;
    dev.mask = ~0ull;
    dev.id = "tpu-" + std::to_string(unit) + "-" + shapes[0].name() + "-" +
             std::to_string(rt->next_id++);
    rt->devices[dev.id] = dev;
    ids << dev.id;
    int rc = write_out(ids.str(), out, out_cap);
    return rc == 0 ? 1 : rc;
  }

  // largest-first improves backtracking speed
  std::vector<Shape> ordered = shapes;
  std::stable_sort(ordered.begin(), ordered.end(),
                   [](const Shape& a, const Shape& b) {
                     return a.chips() > b.chips();
                   });
  std::vector<Candidate> chosen;
  if (!place_all(ordered, 0, occupied, rt->host_block, &chosen)) return -1;

  for (size_t i = 0; i < ordered.size(); ++i) {
    Device dev{};
    dev.unit = unit;
    dev.shape = ordered[i];
    dev.multihost = false;
    dev.mask = chosen[i].mask;
    std::memcpy(dev.offset, chosen[i].offset, sizeof(dev.offset));
    std::memcpy(dev.placed_dims, chosen[i].dims, sizeof(dev.placed_dims));
    dev.id = "tpu-" + std::to_string(unit) + "-" + ordered[i].name() + "-" +
             std::to_string(rt->next_id++);
    rt->devices[dev.id] = dev;
    if (i) ids << '\n';
    ids << dev.id;
  }
  int rc = write_out(ids.str(), out, out_cap);
  return rc == 0 ? (int)ordered.size() : rc;
}

// Standalone exact packer backing the Python search (the hot loop of
// geometry planning).  block: 3 dims; shapes_flat: n*3 canonical dims;
// counts: n; occupied: bitmask of taken cells; require_full: exact tiling.
// Writes one line per placement: "dx;dy;dz,ox;oy;oz" (oriented dims,
// offset).  Returns placement count, -1 = infeasible, -2 = buffer too
// small, -3 = bad args.
int nos_pack(const int* block_dims, int ndims, const int* shapes_flat,
             const int* counts, int n, uint64_t occupied, int require_full,
             char* out, int out_cap) {
  if (ndims < 1 || ndims > kMaxDims || n < 0) return -3;
  Shape block;
  block.ndims = ndims;
  for (int i = 0; i < kMaxDims; ++i)
    block.dims[i] = i < ndims ? block_dims[i] : 1;
  int total = block.chips();
  if (total > 64) return -3;
  const uint64_t full_mask =
      total == 64 ? ~0ull : ((1ull << total) - 1);

  std::vector<PackEntry> entries;
  for (int i = 0; i < n; ++i) {
    PackEntry e;
    e.shape.ndims = ndims;
    for (int d = 0; d < kMaxDims; ++d) {
      e.shape.dims[d] = shapes_flat[i * kMaxDims + d];
      if (e.shape.dims[d] < 1) return -3;
    }
    e.count = counts[i];
    if (e.count < 0) return -3;
    e.cands = candidates_for(e.shape, block);
    entries.push_back(std::move(e));
  }
  // Largest-first at every level, matching the Python packer's ordering.
  std::stable_sort(entries.begin(), entries.end(),
                   [](const PackEntry& a, const PackEntry& b) {
                     return a.shape.chips() > b.shape.chips();
                   });

  std::vector<std::pair<int, Candidate>> acc;
  if (!pack_rec(entries, occupied, total, require_full != 0, full_mask,
                &acc))
    return -1;

  std::ostringstream os;
  for (size_t i = 0; i < acc.size(); ++i) {
    const auto& c = acc[i].second;
    if (i) os << '\n';
    os << c.dims[0] << ';' << c.dims[1] << ';' << c.dims[2] << ','
       << c.offset[0] << ';' << c.offset[1] << ';' << c.offset[2];
  }
  int rc = write_out(os.str(), out, out_cap);
  return rc == 0 ? (int)acc.size() : rc;
}

// Batch resource-fit screen backing the scheduler/planner Filter hot
// loop (nos_tpu/scheduler/native_filter.py).  Semantics mirror
// framework.py NodeResourcesFit exactly, on the same doubles:
//   fit(i, j) = for every resource r with req[j][r] > 0:
//                 free[i][r] >= req[j][r]
//               and (class_chips[j] == 0 or
//                    node_used_chips[i] + class_chips[j]
//                      <= node_cap_chips[i])
// free_m: n_nodes*n_res doubles (row per node, resource order fixed by
// the caller); req_m: n_classes*n_res.  out: n_nodes*n_classes bytes
// (1 = fits).  miss_out (may be null): per (node, class) bitmask of
// failing resource indices, bit 63 = chip-guard failure — the caller
// reconstructs NodeResourcesFit's exact rejection message from it.
// The chip guard is only evaluated when the resource check passed,
// matching the Python control flow.  Returns 0, or -3 on bad args
// (n_res must leave bit 63 free).
//
// Stateless and lock-free by design: concurrent plan shards call this
// through ctypes' CDLL, which releases the GIL for the duration, so
// native filtering from parallel shards genuinely overlaps.
int nos_fit_batch(const double* free_m, const double* req_m,
                  const double* node_cap_chips,
                  const double* node_used_chips,
                  const double* class_chips,
                  int n_nodes, int n_classes, int n_res,
                  uint8_t* out, uint64_t* miss_out) {
  if (n_nodes < 0 || n_classes < 0 || n_res < 0 || n_res > 63 ||
      !free_m || !req_m || !node_cap_chips || !node_used_chips ||
      !class_chips || !out)
    return -3;
  for (int i = 0; i < n_nodes; ++i) {
    const double* free_row = free_m + (size_t)i * n_res;
    for (int j = 0; j < n_classes; ++j) {
      const double* req_row = req_m + (size_t)j * n_res;
      uint64_t miss = 0;
      bool fit = true;
      for (int r = 0; r < n_res; ++r) {
        double v = req_row[r];
        if (v > 0 && free_row[r] < v) {
          fit = false;
          miss |= 1ull << r;
        }
      }
      if (fit && class_chips[j] > 0 &&
          node_used_chips[i] + class_chips[j] > node_cap_chips[i]) {
        fit = false;
        miss |= 1ull << 63;
      }
      out[(size_t)i * n_classes + j] = fit ? 1 : 0;
      if (miss_out) miss_out[(size_t)i * n_classes + j] = miss;
    }
  }
  return 0;
}

// Lexicographic sort of the window-busy triples (gid, host-index,
// busy) — the native form of the Score path's membership table
// (scheduler.py _busy_score_arrays).  Sorts the three parallel arrays
// in place by (gid, idx, val), exactly Python's `sorted(triples)`, so
// nos_score_batch below can binary-search window membership.  Returns
// 0, or -3 on bad args.  Stateless; GIL released via ctypes CDLL.
int nos_window_busy(long long* gid, long long* idx, uint8_t* val,
                    long long n) {
  if (n < 0 || (n > 0 && (!gid || !idx || !val))) return -3;
  std::vector<std::array<long long, 3>> triples((size_t)n);
  for (long long i = 0; i < n; ++i)
    triples[(size_t)i] = {gid[i], idx[i], (long long)val[i]};
  std::sort(triples.begin(), triples.end());
  for (long long i = 0; i < n; ++i) {
    gid[i] = triples[(size_t)i][0];
    idx[i] = triples[(size_t)i][1];
    val[i] = (uint8_t)triples[(size_t)i][2];
  }
  return 0;
}

// Native Score argmin backing Scheduler._choose_node.  Replays the
// Python _score_key tuple ordering
//   (avoided, headroom, window_penalty, host_index, name_rank)
// lexicographically over n candidates and writes the index of the
// minimum (rank is the candidate's position in sorted name order —
// unique, so the order is strict and ties cannot arise).  The window
// penalty for candidate i with window group gid[i] >= 0 sums, over
// its generation's window sizes wsizes[woff[i]..woff[i+1]), each size
// whose aligned window [(widx/size)*size, +size) is WHOLLY present in
// the sorted (busy_gid, busy_idx, busy_val) table with every slot
// idle (val == 0) — breaking a whole free window costs its size,
// exactly scheduler.py's window_penalty.  gid[i] < 0 => penalty 0 (no
// window key, or pod-id absent from the busy map); m == 0 => penalty
// 0 everywhere (Python's `if not busy: return 0`).  Host and window
// indexes must be non-negative — the caller falls back to Python
// otherwise, because C truncating division differs from Python floor
// division below zero.  Returns 0, or -3 on bad args (including any
// non-positive window size, where Python would raise).
//
// Stateless and lock-free: planner shards score concurrently through
// the GIL-released ctypes CDLL binding.
int nos_score_batch(const uint8_t* avoided, const double* headroom,
                    const long long* gid, const long long* widx,
                    const long long* hidx, const long long* rank,
                    const long long* wsizes, const long long* woff,
                    const long long* busy_gid, const long long* busy_idx,
                    const uint8_t* busy_val, long long n, long long m,
                    long long* out_index) {
  if (n < 1 || m < 0 || !avoided || !headroom || !gid || !widx ||
      !hidx || !rank || !wsizes || !woff || !busy_gid || !busy_idx ||
      !busy_val || !out_index)
    return -3;
  for (long long i = 0; i < n; ++i)
    if (gid[i] >= 0)
      for (long long k = woff[i]; k < woff[i + 1]; ++k)
        if (wsizes[k] <= 0) return -3;
  // lower_bound on the sorted (gid, idx) pairs; true iff the slot
  // exists, with *idle reporting val == 0
  auto probe = [&](long long g, long long x, bool* idle) -> bool {
    long long lo = 0, hi = m;
    while (lo < hi) {
      long long mid = lo + (hi - lo) / 2;
      if (busy_gid[mid] < g || (busy_gid[mid] == g && busy_idx[mid] < x))
        lo = mid + 1;
      else
        hi = mid;
    }
    if (lo >= m || busy_gid[lo] != g || busy_idx[lo] != x) return false;
    *idle = busy_val[lo] == 0;
    return true;
  };
  auto penalty = [&](long long i) -> long long {
    if (gid[i] < 0 || m == 0) return 0;
    long long pen = 0;
    for (long long k = woff[i]; k < woff[i + 1]; ++k) {
      long long size = wsizes[k];
      long long start = (widx[i] / size) * size;
      bool whole = true;
      for (long long w = start; w < start + size && whole; ++w) {
        bool idle = false;
        if (!probe(gid[i], w, &idle) || !idle) whole = false;
      }
      if (whole) pen += size;
    }
    return pen;
  };
  long long best = 0;
  long long best_pen = penalty(0);
  for (long long i = 1; i < n; ++i) {
    if (avoided[i] != avoided[best]) {
      if (avoided[i] < avoided[best]) { best = i; best_pen = penalty(i); }
      continue;
    }
    if (headroom[i] != headroom[best]) {
      if (headroom[i] < headroom[best]) { best = i; best_pen = penalty(i); }
      continue;
    }
    long long pen = penalty(i);
    if (pen != best_pen) {
      if (pen < best_pen) { best = i; best_pen = pen; }
      continue;
    }
    if (hidx[i] != hidx[best]) {
      if (hidx[i] < hidx[best]) { best = i; }
      continue;
    }
    if (rank[i] < rank[best]) { best = i; }
  }
  *out_index = best;
  return 0;
}

// Empty-node fit mask backing CapacityScheduling._victim_screen:
// could the preemptor fit on node i with every pod evicted?
// out[i] = 1 iff every requested resource r satisfies
// (req[r] <= 0 or alloc[i*n_res + r] >= req[r]) and
// (pod_chips == 0 or pod_chips <= cap_chips[i]) — NodeResourcesFit at
// zero occupancy.  Returns 0, or -3 on bad args.  Stateless.
int nos_victim_prescreen(const double* alloc, const double* req,
                         const long long* cap_chips, long long pod_chips,
                         long long n, long long n_res, uint8_t* out) {
  if (n < 0 || n_res < 0 || !alloc || !req || !cap_chips || !out)
    return -3;
  for (long long i = 0; i < n; ++i) {
    const double* row = alloc + (size_t)i * (size_t)n_res;
    bool ok = pod_chips == 0 || pod_chips <= cap_chips[i];
    for (long long r = 0; ok && r < n_res; ++r)
      if (req[r] > 0 && row[r] < req[r]) ok = false;
    out[i] = ok ? 1 : 0;
  }
  return 0;
}

// Two-party GIL-release handshake backing the test suite's overlap
// check (tests/test_native.py).  The caller allocates `cell` zeroed and
// starts two threads, each calling this through the ctypes CDLL
// binding.  Each participant atomically increments the cell, then
// spin-waits until it reads >= 2 or the deadline passes.  Both return 1
// IFF both threads were inside this function at once — possible only
// when the binding releases the GIL for the call's duration (CDLL
// semantics).  A binding that held the GIL (PyDLL) deadlocks the
// second thread outside, the first times out, and the handshake
// reports 0 — an event-based proof of the GIL-released property with
// no wall-clock speedup threshold for machine noise to flake on.
int nos_gil_handshake(long long* cell, double timeout_s) {
  if (!cell || timeout_s < 0) return -3;
  using steady = std::chrono::steady_clock;
  const auto deadline =
      steady::now() + std::chrono::duration_cast<steady::duration>(
                          std::chrono::duration<double>(timeout_s));
  __atomic_fetch_add(cell, 1, __ATOMIC_SEQ_CST);
  while (__atomic_load_n(cell, __ATOMIC_SEQ_CST) < 2) {
    if (steady::now() >= deadline) return 0;
    std::this_thread::yield();
  }
  return 1;
}

int nos_runtime_delete_slice(void* h, const char* id) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lock(rt->mu);
  return rt->devices.erase(id) ? 0 : -1;
}

// Lines: id,unit,shape,multihost,offset(x;y;z),dims(x;y;z)
int nos_runtime_list(void* h, char* out, int out_cap) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lock(rt->mu);
  std::ostringstream os;
  bool first = true;
  for (auto& [id, d] : rt->devices) {
    if (!first) os << '\n';
    first = false;
    os << id << ',' << d.unit << ',' << d.shape.name() << ','
       << (d.multihost ? 1 : 0) << ','
       << d.offset[0] << ';' << d.offset[1] << ';' << d.offset[2] << ','
       << d.placed_dims[0] << ';' << d.placed_dims[1] << ';'
       << d.placed_dims[2];
  }
  int rc = write_out(os.str(), out, out_cap);
  return rc == 0 ? (int)rt->devices.size() : rc;
}

// keep: newline-separated ids.  Deletes everything else; writes deleted ids.
int nos_runtime_delete_all_except(void* h, const char* keep, char* out,
                                  int out_cap) {
  auto* rt = static_cast<Runtime*>(h);
  std::lock_guard<std::mutex> lock(rt->mu);
  std::set<std::string> keep_set;
  std::istringstream is(keep ? keep : "");
  for (std::string line; std::getline(is, line);)
    if (!line.empty()) keep_set.insert(line);
  std::vector<std::string> doomed;
  for (auto& [id, d] : rt->devices)
    if (!keep_set.count(id)) doomed.push_back(id);
  std::ostringstream os;
  for (size_t i = 0; i < doomed.size(); ++i) {
    rt->devices.erase(doomed[i]);
    if (i) os << '\n';
    os << doomed[i];
  }
  int rc = write_out(os.str(), out, out_cap);
  return rc == 0 ? (int)doomed.size() : rc;
}

}  // extern "C"
