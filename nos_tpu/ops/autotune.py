"""Flash-attention block autotuner: microbench search + persistent cache.

The pallas kernels in ``nos_tpu/ops/attention.py`` are parameterized by
``(block_q, block_k)``, and the best blocks are a property of the chip
generation and the workload shape, not of the kernel: the v5e sweep that
produced the old hardcoded 512/512 (fwd) and 512/1024 (bwd) defaults
(scripts/sweep_attention.py / sweep_bwd.py) does not transfer to v5p or
v6e VMEM sizes, and the forward and backward prefer different blocks on
the SAME chip.  This module makes block choice a lookup instead of a
constant:

- **Keying.**  An entry is keyed by
  ``(device class, pass, seq_len, head_dim, dtype, causal)`` where the
  device class normalizes jax's ``device_kind`` strings ("TPU v5 lite",
  "v5litepod-16", ...) into the generation families the blocks actually
  depend on.  The forward and backward are independent entries.
- **Sources, in precedence order.**  (1) the measured cache — a JSON
  file (``NOS_TPU_AUTOTUNE_CACHE`` or
  ``~/.cache/nos_tpu/flash_autotune.json``) written by ``search()`` runs
  on real hardware; (2) the shipped ``PRETUNED`` tables for v5e/v5p/v6e
  at the common training shapes; (3) nothing — the caller
  (``attention._plan`` call sites) falls back to the hardcoded defaults.
  Unknown devices (CPU interpret mode, future generations) therefore
  degrade to exactly the pre-autotuner behavior.
- **Search.**  ``search()`` microbenches every VMEM-feasible candidate
  with the same chained-iteration slope method the bench uses (the
  tunneled TPU platform does not block in ``block_until_ready``; the
  per-iteration time is the slope between a small and a large chain
  length, which cancels the tunnel round-trip).  Backward candidates are
  timed through ``jax.grad`` with the forward pinned to its own best
  blocks, so the ranking isolates the backward kernels.

Every candidate the search can emit is validated by ``attention._plan``
before use, and tests pin flash-vs-dense equivalence across the
candidate space — an autotuner that picks a NEW block can never pick a
WRONG one.
"""

from __future__ import annotations

import json
import logging
import os
import pathlib

logger = logging.getLogger(__name__)

#: Block sizes the search draws from; every value keeps the TPU lane
#: width (128) as a divisor so candidates are kernel-legal by
#: construction.
BLOCK_CHOICES = (128, 256, 512, 1024)

#: Rough per-grid-step VMEM budget (bytes) a candidate may claim.  v5-
#: generation chips have ~16 MB/core; Mosaic double-buffers the streamed
#: inputs and needs headroom for the score tile, so candidates are
#: filtered against a deliberately conservative 12 MB.  v6e doubles the
#: VMEM, which is what admits its pretuned (1024, 1024) backward blocks
#: — the search's budget must agree or a tuning run on v6e would record
#: a smaller-block winner that permanently outranks the better table
#: entry (measured cache beats PRETUNED).
VMEM_BUDGET = 12 << 20
VMEM_BUDGET_BY_CLASS = {"v6e": 24 << 20}


def vmem_budget(dev_class: str) -> int:
    return VMEM_BUDGET_BY_CLASS.get(dev_class, VMEM_BUDGET)

_CACHE_ENV = "NOS_TPU_AUTOTUNE_CACHE"
_CACHE_VERSION = 1

#: In-memory measured entries (string key -> [bq, bk]); lazily seeded
#: from the cache file, updated by record().  None = not yet loaded.
_cache_entries: dict[str, list[int]] | None = None


def device_class(device_kind: str) -> str:
    """Normalize a jax ``device_kind`` string to the generation family
    block tuning actually depends on ("TPU v5 lite" / "v5litepod-16" ->
    "v5e").  Unknown kinds pass through lowercased, so their cache
    entries stay self-consistent without colliding with known families."""
    kind = device_kind.lower()
    for cls, needles in (
        ("v6e", ("v6e", "trillium")),
        ("v5p", ("v5p",)),
        ("v5e", ("v5e", "v5litepod", "v5 lite")),
        ("v4", ("v4",)),
    ):
        if any(n in kind for n in needles):
            return cls
    return kind.replace(" ", "_") or "unknown"


def _key(dev_class: str, pass_: str, seq_len: int, head_dim: int,
         dtype: str, causal: bool) -> str:
    return (f"{dev_class}|{pass_}|s{seq_len}|d{head_dim}|{dtype}|"
            f"{'causal' if causal else 'full'}")


def _family_tables() -> dict[str, tuple[int, int]]:
    """Shipped pre-tuned tables.  v5e fwd 512/512 and bwd 512/1024 are
    the measured sweep optima (scripts/sweep_attention.py, sweep_bwd.py,
    BENCH_r03/r04); v5p shares the v5e core geometry so it ships the
    same blocks; v6e's doubled VMEM admits a wider k block per step.
    Entries are seeds, not ceilings — a measured cache entry from
    ``search()`` on the actual host always wins."""
    table: dict[str, tuple[int, int]] = {}
    families = (
        ("v5e", (512, 512), (512, 1024)),
        ("v5p", (512, 512), (512, 1024)),
        ("v6e", (512, 1024), (1024, 1024)),
    )
    for dev, fwd_blocks, bwd_blocks in families:
        for seq in (1024, 2048, 4096, 8192):
            for causal in (True, False):
                table[_key(dev, "fwd", seq, 128, "bfloat16", causal)] = \
                    fwd_blocks
                table[_key(dev, "bwd", seq, 128, "bfloat16", causal)] = \
                    bwd_blocks
    return table


PRETUNED: dict[str, tuple[int, int]] = _family_tables()


# -- persistent cache -------------------------------------------------------

def cache_path() -> pathlib.Path:
    override = os.environ.get(_CACHE_ENV, "")
    if override:
        return pathlib.Path(override)
    return (pathlib.Path.home() / ".cache" / "nos_tpu"
            / "flash_autotune.json")


def _load_cache() -> dict[str, list[int]]:
    global _cache_entries
    if _cache_entries is not None:
        return _cache_entries
    path = cache_path()
    entries: dict[str, list[int]] = {}
    if path.is_file():
        try:
            raw = json.loads(path.read_text())
            loaded = raw.get("entries") if isinstance(raw, dict) else {}
            entries = {
                k: [int(v[0]), int(v[1])]
                for k, v in (loaded or {}).items()
                if isinstance(v, (list, tuple)) and len(v) == 2
            }
        except (OSError, ValueError, TypeError, AttributeError):
            # a corrupt cache (unparseable OR structurally wrong) must
            # degrade to the pretuned tables, not take down the
            # training job that consulted it
            logger.warning("autotune cache %s unreadable; ignoring",
                           path, exc_info=True)
    _cache_entries = entries
    return entries


def reload_cache() -> None:
    """Drop the in-memory cache so the next lookup re-reads the file
    (tests point ``NOS_TPU_AUTOTUNE_CACHE`` at a tmp dir per case)."""
    global _cache_entries
    _cache_entries = None


def _save_cache(entries: dict[str, list[int]]) -> bool:
    path = cache_path()
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(
            {"version": _CACHE_VERSION, "entries": entries},
            indent=1, sort_keys=True))
        tmp.replace(path)
    except OSError:
        # read-only HOME (hermetic CI): the in-memory entry still
        # serves this process; only persistence is lost
        logger.warning("autotune cache %s not writable", path,
                       exc_info=True)
        return False
    return True


def record(device_kind: str, pass_: str, seq_len: int, head_dim: int,
           dtype: str, causal: bool, blocks: tuple[int, int],
           persist: bool = True) -> str:
    """Store a measured (block_q, block_k) for the key; returns the
    cache key.  ``persist=False`` keeps it in-memory only."""
    if pass_ not in ("fwd", "bwd"):
        raise ValueError(f"pass_ must be 'fwd'/'bwd', got {pass_!r}")
    entries = _load_cache()
    key = _key(device_class(device_kind), pass_, seq_len, head_dim,
               dtype, causal)
    entries[key] = [int(blocks[0]), int(blocks[1])]
    if persist:
        _save_cache(entries)
    return key


def lookup(device_kind: str, pass_: str, seq_len: int, head_dim: int,
           dtype: str, causal: bool) -> tuple[int, int] | None:
    """Tuned (block_q, block_k) for the key, or None (caller falls back
    to the hardcoded defaults).  Measured cache entries win over the
    shipped PRETUNED tables."""
    key = _key(device_class(device_kind), pass_, seq_len, head_dim,
               dtype, causal)
    entry = _load_cache().get(key)
    if entry is None:
        pre = PRETUNED.get(key)
        return tuple(pre) if pre is not None else None
    return (entry[0], entry[1])


# -- candidate space --------------------------------------------------------

def _vmem_estimate(pass_: str, block_q: int, block_k: int, head_dim: int,
                   dtype_bytes: int) -> int:
    """Conservative per-grid-step VMEM bytes for a candidate.  Streamed
    inputs count twice (Mosaic double-buffers their DMAs); the score
    tile and softmax stats are fp32."""
    score_tile = block_q * block_k * 4
    stats = 2 * block_q * 128 * 4                       # m, l (or lse, delta)
    if pass_ == "fwd":
        io = (2 * block_q + 2 * 2 * block_k) * head_dim * dtype_bytes
        scratch = block_q * head_dim * 4                # acc
        return io + scratch + 2 * score_tile + stats
    # bwd (fused): q/do stream (x2 buffered), k/v resident, dk/dv scratch
    io = (2 * 2 * block_q + 2 * block_k) * head_dim * dtype_bytes
    scratch = 2 * block_k * head_dim * 4 + block_q * head_dim * dtype_bytes
    return io + scratch + 4 * score_tile + stats


def candidates(pass_: str, seq_q: int, seq_k: int, head_dim: int,
               dtype_bytes: int = 2,
               budget: int = VMEM_BUDGET) -> list[tuple[int, int]]:
    """Kernel-legal, VMEM-feasible (block_q, block_k) candidates for the
    shapes, largest-block-first (ties in the search resolve toward fewer
    grid steps).  `budget` defaults to the v5-sized VMEM; the search
    passes vmem_budget(device_class) so bigger-VMEM chips see their
    bigger blocks."""
    out = []
    for bq in BLOCK_CHOICES:
        if bq > seq_q or seq_q % bq:
            continue
        for bk in BLOCK_CHOICES:
            if bk > seq_k or seq_k % bk or bk % 128:
                continue
            if _vmem_estimate(pass_, bq, bk, head_dim,
                              dtype_bytes) > budget:
                continue
            out.append((bq, bk))
    return sorted(out, key=lambda c: (-c[0] * c[1], -c[0]))


# -- microbench search ------------------------------------------------------

def _time_forward(q, k, v, causal, blocks, interpret, n1, n2, reps):
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops.attention import flash_attention
    from nos_tpu.ops.roofline import slope as _slope

    bq, bk = blocks

    @jax.jit
    def run(q, k, v, iters):
        return jax.lax.fori_loop(
            0, iters,
            lambda i, acc: flash_attention(acc, k, v, causal, bq, bk,
                                           interpret),
            q)[0, 0, 0, 0]

    def make(iters):
        i = jnp.int32(iters)
        return lambda: float(run(q, k, v, i))
    return _slope(make, n1, n2, reps)


def _time_backward(q, k, v, causal, fwd_blocks, bwd_blocks, interpret,
                   n1, n2, reps):
    import jax
    import jax.numpy as jnp

    from nos_tpu.ops.attention import flash_attention
    from nos_tpu.ops.roofline import slope as _slope

    fq, fk = fwd_blocks
    bq, bk = bwd_blocks

    def loss(qq, kk, vv):
        out = flash_attention(qq, kk, vv, causal, fq, fk, interpret,
                              bq, bk)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def gstep(qx):
        gq, gk, gv = jax.grad(loss, (0, 1, 2))(qx, k, v)
        return gq + gk + gv   # all backward kernels stay live

    @jax.jit
    def run(q, k, v, iters):
        return jax.lax.fori_loop(
            0, iters, lambda i, acc: gstep(acc), q)[0, 0, 0, 0]

    def make(iters):
        i = jnp.int32(iters)
        return lambda: float(run(q, k, v, i))
    return _slope(make, n1, n2, reps)


def search(pass_: str, q, k, v, causal: bool = True, *,
           interpret: bool = False, n1: int = 10, n2: int = 40,
           reps: int = 3) -> tuple[tuple[int, int], dict]:
    """Microbench every feasible candidate at these concrete arrays;
    returns (best_blocks, {blocks: seconds}).  Backward candidates run
    through jax.grad with the forward pinned (its tuned-or-default
    blocks), so the constant forward cost cannot reorder the ranking."""
    from nos_tpu.ops import attention as A

    import jax

    if pass_ not in ("fwd", "bwd"):
        raise ValueError(f"pass_ must be 'fwd'/'bwd', got {pass_!r}")
    seq_q, head_dim = q.shape[1], q.shape[3]
    budget = vmem_budget(device_class(jax.devices()[0].device_kind))
    cands = [c for c in candidates(pass_, seq_q, k.shape[1], head_dim,
                                   q.dtype.itemsize, budget=budget)
             if A._plan(q, k, causal, *c) == c]
    if not cands:
        raise ValueError(
            f"no kernel-legal candidates for shapes q={q.shape} "
            f"k={k.shape} causal={causal}")
    if pass_ == "bwd":
        fwd_blocks = (
            lookup_for_arrays(q, k, "fwd", causal)
            or (A.DEFAULT_BLOCK_Q, A.DEFAULT_BLOCK_K))
    timings: dict[tuple[int, int], float] = {}
    for blocks in cands:
        if pass_ == "fwd":
            t = _time_forward(q, k, v, causal, blocks, interpret,
                              n1, n2, reps)
        else:
            t = _time_backward(q, k, v, causal, fwd_blocks, blocks,
                               interpret, n1, n2, reps)
        timings[blocks] = t
        logger.info("autotune %s %s: %.4f ms", pass_, blocks, t * 1e3)
    best = min(timings, key=lambda c: timings[c])
    return best, timings


def lookup_for_arrays(q, k, pass_: str, causal: bool
                      ) -> tuple[int, int] | None:
    """lookup() keyed from concrete arrays on the current backend.  Self-
    attention only — a decode rectangle (seq_q != seq_k) is not a tuned
    shape (attention._plan routes causal rectangles to the fallback
    anyway)."""
    import jax

    if q.shape[1] != k.shape[1]:
        return None
    devices = jax.devices()
    if not devices:
        return None
    return lookup(devices[0].device_kind, pass_, int(q.shape[1]),
                  int(q.shape[3]), str(q.dtype.name), causal)


def tune_and_record(q, k, v, causal: bool = True, *,
                    interpret: bool = False, persist: bool = True,
                    n1: int = 10, n2: int = 40, reps: int = 3) -> dict:
    """Search fwd then bwd at these arrays and record both winners;
    returns {"fwd": blocks, "bwd": blocks, "timings_ms": {...}}."""
    import jax

    kind = jax.devices()[0].device_kind
    out: dict = {"device_class": device_class(kind), "timings_ms": {}}
    for pass_ in ("fwd", "bwd"):
        best, timings = search(pass_, q, k, v, causal,
                               interpret=interpret, n1=n1, n2=n2,
                               reps=reps)
        record(kind, pass_, int(q.shape[1]), int(q.shape[3]),
               str(q.dtype.name), causal, best, persist=persist)
        out[pass_] = list(best)
        out["timings_ms"][pass_] = {
            f"{bq}x{bk}": round(t * 1e3, 4)
            for (bq, bk), t in sorted(timings.items())}
    return out


def main(argv=None) -> int:
    """CLI: tune the current backend at the given shapes and persist.

        python -m nos_tpu.ops.autotune --seq 2048 --heads 8 --batch 8
    """
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--no-causal", action="store_true")
    ap.add_argument("--interpret", action="store_true",
                    help="interpret-mode kernels (CPU; validates the "
                    "search plumbing, not real timings)")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(0)
    shape = (args.batch, args.seq, args.heads, args.head_dim)
    dtype = jnp.dtype(args.dtype)
    q, k, v = (jax.random.normal(kk, shape, dtype)
               for kk in jax.random.split(key, 3))
    # interpret-mode timings validate the plumbing, not the hardware:
    # persisting them would poison the real cache (measured entries
    # outrank PRETUNED) with CPU-interpret rankings
    result = tune_and_record(q, k, v, not args.no_causal,
                             interpret=args.interpret,
                             persist=not args.interpret)
    result["persisted"] = not args.interpret
    result["cache"] = str(cache_path())
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
