"""Flash attention: fused pallas TPU kernel + pure-XLA fallback.

The kernel streams K/V blocks through VMEM with online-softmax accumulation
so the [S, S] score matrix never hits HBM (HBM bandwidth, not FLOPs, bounds
naive attention).  Grid is (batch, heads, q-blocks); the causal variant
skips K/V blocks entirely above the diagonal.  Written per
/opt/skills/guides/pallas_guide.md: fp32 accumulation on the MXU
(preferred_element_type), (block, 128)-aligned tiles, broadcasted_iota for
position masks.

Training: the op carries a custom VJP whose backward recomputes attention
with the XLA fallback (pallas kernels are not auto-differentiable);
dedicated backward kernels are a later optimization.

Layout convention everywhere in nos_tpu: [batch, seq, heads, head_dim].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nos_tpu.parallel.ring import dense_attention

_NEG_INF = -1e30


def _xla_attention(q, k, v, causal):
    return dense_attention(q, k, v, causal=causal)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal,
                  block_q, block_k):
    # refs are [1, block, D] slices of the [B*H, S, D] folded layout.
    qi = pl.program_id(1)
    seq_k = k_ref.shape[1]
    num_k_blocks = seq_k // block_k
    q = q_ref[0].astype(jnp.float32) * scale               # [bq, D]
    head_dim = q.shape[-1]

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    if causal:
        # blocks fully above the diagonal contribute nothing
        hi = jnp.minimum(num_k_blocks,
                         pl.cdiv((qi + 1) * block_q, block_k))
    else:
        hi = num_k_blocks

    def body(j, carry):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = q_pos >= k_pos
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, vb, preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    scale = head_dim ** -0.5

    # Fold batch*heads into the leading dim: TPU block shapes constrain
    # only the last two dims, which become (seq-block, head_dim).
    def fold(x):
        b, s, h, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    qf, kf, vf = fold(q), fold(k), fold(v)
    grid = (batch * heads, seq_q // block_q)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        cost_estimate=pl.CostEstimate(
            flops=4 * batch * heads * seq_q * seq_k * head_dim,
            bytes_accessed=2 * (q.size + k.size + v.size),
            transcendentals=batch * heads * seq_q * seq_k,
        ),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(batch, heads, seq_q, head_dim).transpose(0, 2, 1, 3)


def _supported(q, k, block_q, block_k) -> bool:
    _, seq_q, _, head_dim = q.shape
    seq_k = k.shape[1]
    return (seq_q % block_q == 0 and seq_k % block_k == 0
            and head_dim % 128 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 256, block_k: int = 512,
                    interpret: bool = False):
    """Fused attention, [B, S, H, D], K/V already at full head count
    (repeat grouped KV heads first — see repeat_kv).  Falls back to the
    XLA implementation off-TPU or for unaligned shapes."""
    on_tpu = jax.default_backend() == "tpu"
    if (on_tpu or interpret) and _supported(q, k, block_q, block_k):
        return _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return _xla_attention(q, k, v, causal)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    return flash_attention(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Expand grouped KV heads to the full head count ([B, S, Hkv, D] ->
    [B, S, Hkv*n_rep, D])."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)
