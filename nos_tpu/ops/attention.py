"""Flash attention: fused pallas TPU kernels (forward + backward) + XLA fallback.

Online-softmax attention where the [S, S] score matrix never hits HBM (HBM
bandwidth, not FLOPs, bounds naive attention).  Structured for the Mosaic
pipeline rather than as a literal transcription of the CUDA algorithm:

- **K/V are grid-streamed, not kernel-looped.**  The grid is
  (batch*heads, q-blocks, k-blocks) with the k dimension marked
  "arbitrary"; softmax state (m, l, acc) lives in VMEM scratch across the
  k steps of one q-block.  Mosaic double-buffers the K/V block DMAs across
  grid steps, overlapping HBM traffic with compute — an in-kernel
  fori_loop over a VMEM-resident K/V gets no such pipelining.
- **Row statistics stay lane-replicated.**  m and l are kept as
  [block_q, 128] (every lane carries the row value) so every VPU op in the
  update is lane-aligned; broadcasting a [block_q, 1] column into a
  [block_q, block_k] tile per step costs more than the matmuls it feeds.
  `jnp.tile` of the replicated stats is a cheap lane-copy.
- **Matmul inputs keep the array dtype** (bf16 in training): the MXU
  multiplies bf16 natively with fp32 accumulation via
  preferred_element_type; upcasting first forces fp32 multiplies at a
  fraction of peak.  Softmax statistics are always fp32.
- **Causal blocks above the diagonal are skipped** with @pl.when; their
  K/V index maps redirect the prefetch to the next q-row's first block
  (the skipped step fetches something useful instead of stalling).

Measured on a real v5e at the training shapes (B8 S2048 H8 D128, causal
bf16; BENCH_r03/r04 record the per-round numbers, which move a few
TFLOP/s run to run through the tunnel): ~89-97 TFLOP/s forward at
blocks 512/512 — at or above the official pallas TPU kernel
(jax.experimental.pallas.ops.tpu.flash_attention, 88 TFLOP/s at its
best block config, same process, r3) — and ~45-50% of the chip's
bf16 peak.  The naive ports measured along the way: 43 TFLOP/s for
the in-kernel-loop structure, 70 with "parallel" grid hints, 84 with
paired q-chains; the streamed + lane-replicated form above beat them all.

The backward recomputes p = exp(s - lse) from the saved logsumexp
(flash-attention-2 style) and uses ds = p * (dp - delta) with
delta = rowsum(dO * O) computed once in XLA; lse/delta are
pre-replicated to lane width XLA-side so the per-step subtraction stays
lane-aligned.  Two implementations (see the backward section): the
default FUSED kernel computes dq, dk and dv in one pass (5 matmuls per
block pair, dq via per-k-block partials summed XLA-side — measured ~30%
faster on v5e grad time), and the classic SPLIT dq/dkv pair (7 matmuls,
no partial buffer — the long-context fallback).

Layout convention everywhere in nos_tpu: [batch, seq, heads, head_dim].
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nos_tpu.ops import autotune as _autotune
from nos_tpu.parallel.ring import dense_attention

_NEG_INF = -1e30
_LANES = 128

# Hardware-tuned defaults (v5e sweep at S=2048; see module docstring) —
# the LAST fallback: block choice is normally a per-device autotune
# lookup (nos_tpu/ops/autotune.py), consulted by the _plan call sites
# when the caller passes no explicit blocks.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
# The backward prefers larger blocks than the forward (fewer grid steps
# amortize the per-step recompute; scripts/sweep_bwd.py on v5e).  Used by
# _bwd regardless of the forward's blocks; shrunk by _plan for short
# sequences.
DEFAULT_BWD_BLOCK_Q = 512
DEFAULT_BWD_BLOCK_K = 1024

# Backward implementation: "fused" (one 5-matmul kernel + dq partials) or
# "split" (classic dq/dkv pair, 7 matmuls) — see the backward section.
_BWD_IMPL = os.environ.get("NOS_TPU_FLASH_BWD", "fused")
if _BWD_IMPL not in ("fused", "split"):
    import logging
    logging.getLogger(__name__).warning(
        "NOS_TPU_FLASH_BWD=%r is not 'fused'/'split'; using 'fused'",
        _BWD_IMPL)
    _BWD_IMPL = "fused"

# The fused backward materialises dq partials of shape
# [B*H, Sk/block_k, Sq, D] in the array dtype — quadratic in sequence
# length.  Above this budget (bytes) fall back to the split kernels,
# which need no partial buffer (long-context shapes that fit before must
# keep fitting).
FUSED_PARTIAL_BUDGET = 1 << 30


def set_backward_impl(impl: str) -> str:
    """Select the flash backward ("fused"/"split"); returns the previous
    value.  For benchmarking — traced programs pick it up on next trace."""
    global _BWD_IMPL
    if impl not in ("fused", "split"):
        raise ValueError(f"unknown flash backward impl {impl!r}")
    prev, _BWD_IMPL = _BWD_IMPL, impl
    return prev


def _xla_attention(q, k, v, causal):
    return dense_attention(q, k, v, causal=causal)


def _on_or_below_diag(i, j, block_q, block_k):
    """Does q-block i intersect at-or-below the diagonal of k-block j?
    The single source of truth for the causal skip, shared by the kernels'
    @pl.when gates and the index maps' prefetch redirects — they must
    agree or a skipped grid step computes on a stale block."""
    return i * block_q + block_q - 1 >= j * block_k


def _kv_index_map(block_q, block_k, causal):
    """K/V stream map for (b, q-block, k-block) grids: skipped
    above-diagonal steps prefetch the next q-row's first k block instead
    of the unused one."""
    def kv_map(b, i, j):
        if causal:
            j = lax.select(_on_or_below_diag(i, j, block_q, block_k), j, 0)
        return (b, j, 0)
    return kv_map


def _causal_mask(qi, kj, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos >= k_pos


def _fold(x):
    """[B, S, H, D] -> [B*H, S, D] (TPU block shapes constrain only the
    last two dims, which become (seq-block, head_dim))."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, batch, heads):
    bh, s, d = x.shape
    return x.reshape(batch, heads, s, d).transpose(0, 2, 1, 3)


def _replicate_rows(x):
    """[BH, S, 1] fp32 row stats -> [BH, S, 128] lane-replicated, so kernel
    blocks of it are [block, 128] and their use is lane-aligned."""
    return jnp.broadcast_to(x, (*x.shape[:2], _LANES))


# jax renamed TPUCompilerParams -> CompilerParams across the versions
# this repo runs against (0.4.x has only the old name); same fields.
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


def _grid_params(n):
    # Innermost dim carries scratch state ("arbitrary"); the rest are
    # disjoint-output parallel.
    return _CompilerParams(
        dimension_semantics=("parallel",) * (n - 1) + ("arbitrary",))


# -- forward ----------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, l_sc, acc_sc, *,
                scale, causal, block_q, block_k, num_k_blocks):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        m_sc[:, :] = jnp.full(m_sc.shape, _NEG_INF, jnp.float32)
        l_sc[:, :] = jnp.zeros(l_sc.shape, jnp.float32)
        acc_sc[:, :] = jnp.zeros(acc_sc.shape, jnp.float32)

    diag = _on_or_below_diag(qi, kj, block_q, block_k) if causal else True

    @pl.when(diag)
    def _compute():
        reps = block_k // _LANES
        s = jnp.dot(q_ref[0], k_ref[0].T,
                    preferred_element_type=jnp.float32) * scale
        if causal:
            s = s + jnp.where(_causal_mask(qi, kj, block_q, block_k),
                              0.0, _NEG_INF)
        m_prev, l_prev = m_sc[:, :], l_sc[:, :]          # [bq, 128]
        m_cur = jnp.max(s, axis=1)[:, None]              # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)               # [bq, 128]
        p = jnp.exp(s - jnp.tile(m_new, (1, reps)))
        alpha = jnp.exp(m_prev - m_new)                  # [bq, 128]
        l_sc[:, :] = alpha * l_prev + jnp.sum(p, axis=1)[:, None]
        m_sc[:, :] = m_new
        acc_sc[:, :] = acc_sc[:, :] * alpha + jnp.dot(
            p.astype(v_ref.dtype), v_ref[0],
            preferred_element_type=jnp.float32)

    @pl.when(kj == num_k_blocks - 1)
    def _flush():
        l = jnp.maximum(l_sc[:, :], 1e-20)               # [bq, 128]
        o_ref[0] = (acc_sc[:, :] / l).astype(o_ref.dtype)
        lse_ref[0] = (m_sc[:, :] + jnp.log(l))[:, :1]


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    scale = head_dim ** -0.5
    num_k_blocks = seq_k // block_k

    qf, kf, vf = _fold(q), _fold(k), _fold(v)

    kv_map = _kv_index_map(block_q, block_k, causal)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_k_blocks=num_k_blocks)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            # [BH, Sq, 1]: a trailing unit dim keeps the block's last two
            # dims TPU-legal ((block_q, 1) with 1 == array dim).
            jax.ShapeDtypeStruct((batch * heads, seq_q, 1), jnp.float32),
        ],
        grid=(batch * heads, seq_q // block_q, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, head_dim), kv_map),
            pl.BlockSpec((1, block_k, head_dim), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # m
            pltpu.VMEM((block_q, _LANES), jnp.float32),   # l
            pltpu.VMEM((block_q, head_dim), jnp.float32),  # acc
        ],
        compiler_params=_grid_params(3),
        interpret=interpret,
    )(qf, kf, vf)
    return out, lse


# -- backward ---------------------------------------------------------------
#
# Two implementations, selected by set_backward_impl / NOS_TPU_FLASH_BWD:
#
# - "split" (the standard TPU two-kernel split): a dq kernel (grid over
#   q-blocks, streams K/V) and a dkv kernel (grid over k-blocks, streams
#   Q/dO).  7 matmuls per (i, j) block pair — s and dp are computed twice.
# - "fused" (default, measured faster on v5e): ONE kernel with the dkv
#   grid computes s/p/dp/ds once and produces dk, dv AND dq — 5 matmuls
#   per pair and half the Q/dO/K/V streaming.  TPU has no atomics and a
#   pallas grid must write disjoint output blocks, so the cross-j dq
#   accumulation is done by writing one dq partial per k-block
#   ([BH, J, Sq, D]) and summing the J partials XLA-side; the extra HBM
#   round-trip costs less than the two matmuls + second stream it saves.

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_sc, *, scale, causal, block_q, block_k, num_k_blocks):
    qi = pl.program_id(1)
    kj = pl.program_id(2)

    @pl.when(kj == 0)
    def _init():
        acc_sc[:, :] = jnp.zeros(acc_sc.shape, jnp.float32)

    diag = _on_or_below_diag(qi, kj, block_q, block_k) if causal else True

    @pl.when(diag)
    def _compute():
        reps = block_k // _LANES
        kb, vb = k_ref[0], v_ref[0]
        s = scale * jnp.dot(q_ref[0], kb.T,
                            preferred_element_type=jnp.float32)
        if causal:
            s = s + jnp.where(_causal_mask(qi, kj, block_q, block_k),
                              0.0, _NEG_INF)
        lse = lse_ref[0]                                  # [bq, 128]
        delta = delta_ref[0]                              # [bq, 128]
        p = jnp.exp(s - jnp.tile(lse, (1, reps)))
        dp = jnp.dot(do_ref[0], vb.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - jnp.tile(delta, (1, reps)))).astype(kb.dtype)
        acc_sc[:, :] += jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    @pl.when(kj == num_k_blocks - 1)
    def _flush():
        dq_ref[0] = (scale * acc_sc[:, :]).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *,
                scale, causal, block_q, block_k, num_q_blocks):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:, :] = jnp.zeros(dk_sc.shape, jnp.float32)
        dv_sc[:, :] = jnp.zeros(dv_sc.shape, jnp.float32)

    diag = _on_or_below_diag(qi, kj, block_q, block_k) if causal else True

    @pl.when(diag)
    def _compute():
        reps = block_k // _LANES
        qb, dob = q_ref[0], do_ref[0]
        kb, vb = k_ref[0], v_ref[0]
        s = scale * jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + jnp.where(_causal_mask(qi, kj, block_q, block_k),
                              0.0, _NEG_INF)
        lse = lse_ref[0]                                  # [bq, 128]
        delta = delta_ref[0]                              # [bq, 128]
        p = jnp.exp(s - jnp.tile(lse, (1, reps)))
        dv_sc[:, :] += jnp.dot(p.astype(dob.dtype).T, dob,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - jnp.tile(delta, (1, reps)))).astype(qb.dtype)
        dk_sc[:, :] += jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _flush():
        dk_ref[0] = (scale * dk_sc[:, :]).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:, :].astype(dv_ref.dtype)


def _fused_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dk_sc, dv_sc, *,
                      scale, causal, block_q, block_k, num_q_blocks):
    kj = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:, :] = jnp.zeros(dk_sc.shape, jnp.float32)
        dv_sc[:, :] = jnp.zeros(dv_sc.shape, jnp.float32)

    diag = _on_or_below_diag(qi, kj, block_q, block_k) if causal else True

    @pl.when(diag)
    def _compute():
        reps = block_k // _LANES
        qb, dob = q_ref[0], do_ref[0]
        kb, vb = k_ref[0], v_ref[0]
        s = scale * jnp.dot(qb, kb.T, preferred_element_type=jnp.float32)
        if causal:
            s = s + jnp.where(_causal_mask(qi, kj, block_q, block_k),
                              0.0, _NEG_INF)
        lse = lse_ref[0]                                  # [bq, 128]
        delta = delta_ref[0]                              # [bq, 128]
        p = jnp.exp(s - jnp.tile(lse, (1, reps)))
        dv_sc[:, :] += jnp.dot(p.astype(dob.dtype).T, dob,
                               preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, vb.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - jnp.tile(delta, (1, reps)))).astype(qb.dtype)
        dk_sc[:, :] += jnp.dot(ds.T, qb, preferred_element_type=jnp.float32)
        # this k-block's dq contribution; the J partials are summed (and
        # scaled) XLA-side.  Stored in the array dtype — fp32 partials
        # double the extra HBM round-trip this design pays, and the
        # fp32-accumulated sum over J<=Sk/block_k terms keeps the final
        # dq within bf16 gradient tolerance.
        dq_ref[0, 0] = jnp.dot(
            ds, kb, preferred_element_type=jnp.float32
        ).astype(dq_ref.dtype)

    if causal:
        @pl.when(jnp.logical_not(diag))
        def _zero():
            # a skipped step still owns its dq partial block
            dq_ref[0, 0] = jnp.zeros(dq_ref.shape[2:], dq_ref.dtype)

    @pl.when(qi == num_q_blocks - 1)
    def _flush():
        dk_ref[0] = (scale * dk_sc[:, :]).astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:, :].astype(dv_ref.dtype)


def _flash_backward_fused(q, k, v, o, lse, g, causal, block_q, block_k,
                          interpret):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    scale = head_dim ** -0.5
    bh = batch * heads
    num_q_blocks = seq_q // block_q
    num_k_blocks = seq_k // block_k

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    dof = _fold(g)
    delta = jnp.sum(dof.astype(jnp.float32) * _fold(o).astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BH, Sq, 1]
    lse_rep = _replicate_rows(lse)
    delta_rep = _replicate_rows(delta)

    def kv_fixed(b, j, i):
        return (b, j, 0)

    def q_stream(b, j, i):
        if causal:
            lo = (j * block_k) // block_q
            i = lax.select(_on_or_below_diag(i, j, block_q, block_k), i, lo)
        return (b, i, 0)

    qspec = pl.BlockSpec((1, block_q, head_dim), q_stream)
    kspec = pl.BlockSpec((1, block_k, head_dim), kv_fixed)
    rowspec = pl.BlockSpec((1, block_q, _LANES), q_stream)

    dq_partial, dk, dv = pl.pallas_call(
        functools.partial(
            _fused_bwd_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, num_q_blocks=num_q_blocks),
        out_shape=[
            jax.ShapeDtypeStruct((bh, num_k_blocks, seq_q, head_dim),
                                 q.dtype),
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ],
        grid=(bh, num_k_blocks, num_q_blocks),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim),
                         lambda b, j, i: (b, j, i, 0)),
            kspec, kspec,
        ],
        scratch_shapes=[pltpu.VMEM((block_k, head_dim), jnp.float32),
                        pltpu.VMEM((block_k, head_dim), jnp.float32)],
        compiler_params=_grid_params(3),
        interpret=interpret,
    )(qf, kf, vf, dof, lse_rep, delta_rep)

    dq = (scale * jnp.sum(dq_partial, axis=1,
                          dtype=jnp.float32)).astype(q.dtype)
    return (_unfold(dq, batch, heads), _unfold(dk, batch, heads),
            _unfold(dv, batch, heads))


def _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k, interpret):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    scale = head_dim ** -0.5
    bh = batch * heads

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    dof = _fold(g)
    # delta_i = sum_d dO_id * O_id — one fused elementwise+reduce, XLA-side.
    delta = jnp.sum(dof.astype(jnp.float32) * _fold(o).astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BH, Sq, 1]
    # Lane-replicate the row stats so per-step use is lane-aligned.
    lse_rep = _replicate_rows(lse)
    delta_rep = _replicate_rows(delta)

    def q_stream(b, i, j):
        return (b, i, 0)

    qspec = pl.BlockSpec((1, block_q, head_dim), q_stream)
    kspec = pl.BlockSpec((1, block_k, head_dim),
                         _kv_index_map(block_q, block_k, causal))
    rowspec = pl.BlockSpec((1, block_q, _LANES), q_stream)

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, num_k_blocks=seq_k // block_k),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(bh, seq_q // block_q, seq_k // block_k),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=_grid_params(3),
        interpret=interpret,
    )(qf, kf, vf, dof, lse_rep, delta_rep)

    # dkv: grid over k-blocks, streaming q/do/lse/delta (innermost).
    def kv_fixed(b, j, i):
        return (b, j, 0)

    def q_stream2(b, j, i):
        if causal:
            # Skipped steps (q block wholly above this k block) prefetch
            # the first contributing q block instead.
            lo = (j * block_k) // block_q
            i = lax.select(_on_or_below_diag(i, j, block_q, block_k), i, lo)
        return (b, i, 0)

    qspec2 = pl.BlockSpec((1, block_q, head_dim), q_stream2)
    kspec2 = pl.BlockSpec((1, block_k, head_dim), kv_fixed)
    rowspec2 = pl.BlockSpec((1, block_q, _LANES), q_stream2)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=block_q,
            block_k=block_k, num_q_blocks=seq_q // block_q),
        out_shape=[jax.ShapeDtypeStruct(kf.shape, k.dtype),
                   jax.ShapeDtypeStruct(vf.shape, v.dtype)],
        grid=(bh, seq_k // block_k, seq_q // block_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        scratch_shapes=[pltpu.VMEM((block_k, head_dim), jnp.float32),
                        pltpu.VMEM((block_k, head_dim), jnp.float32)],
        compiler_params=_grid_params(3),
        interpret=interpret,
    )(qf, kf, vf, dof, lse_rep, delta_rep)

    return (_unfold(dq, batch, heads), _unfold(dk, batch, heads),
            _unfold(dv, batch, heads))


# -- public op with custom VJP ----------------------------------------------

def _plan(q, k, causal, block_q, block_k) -> tuple[int, int] | None:
    """Concrete (block_q, block_k) for these shapes, shrinking blocks for
    short sequences; None if the kernel cannot apply."""
    _, seq_q, _, head_dim = q.shape
    seq_k = k.shape[1]
    if head_dim % 128:
        return None
    if causal and seq_q != seq_k:
        # The kernel's causal mask is top-left aligned; a decode-style
        # rectangle (seq_q < seq_k over cached keys) needs the fallback's
        # bottom-right alignment (dense_attention's tril(k=sk-sq)).
        return None
    block_q = min(block_q, seq_q)
    block_k = min(block_k, seq_k)
    if seq_q % block_q or seq_k % block_k or block_k % _LANES:
        return None
    return block_q, block_k


def _resolve_plan(q, k, causal, block_q, block_k, which,
                  default_q, default_k):
    """Concrete (block_q, block_k) for one pass: explicit blocks win,
    then the per-device autotune entry (validated — a tuned pick that
    does not divide THESE shapes falls through rather than disabling
    the kernel), then the hardcoded defaults."""
    if block_q is None and block_k is None:
        tuned = _autotune.lookup_for_arrays(q, k, which, causal)
        if tuned is not None:
            plan = _plan(q, k, causal, *tuned)
            if plan is not None:
                return plan
    return _plan(q, k, causal, block_q or default_q, block_k or default_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool = False,
                    bwd_block_q: int | None = None,
                    bwd_block_k: int | None = None):
    """Fused attention, [B, S, H, D], K/V already at full head count
    (repeat grouped KV heads first — see repeat_kv).  Falls back to the
    XLA implementation off-TPU or for unaligned shapes.

    block_q/block_k None = the autotuned blocks for this device/shape
    (nos_tpu/ops/autotune.py) when an entry exists, else the
    hardware-tuned defaults — which differ between the forward
    (DEFAULT_BLOCK_*) and backward (DEFAULT_BWD_BLOCK_*) passes.
    Explicit block_q/block_k are honored verbatim in BOTH passes
    (sweeps depend on that) unless bwd_block_q/bwd_block_k pin the
    backward separately — the autotuner times backward candidates with
    the forward held fixed through exactly that override."""
    on_tpu = jax.default_backend() == "tpu"
    plan = _resolve_plan(q, k, causal, block_q, block_k, "fwd",
                         DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    if (on_tpu or interpret) and plan is not None:
        out, _ = _flash_forward(q, k, v, causal, *plan, interpret)
        return _unfold(out, q.shape[0], q.shape[2])
    return _xla_attention(q, k, v, causal)


def _fwd(q, k, v, causal, block_q, block_k, interpret,
         bwd_block_q, bwd_block_k):
    on_tpu = jax.default_backend() == "tpu"
    plan = _resolve_plan(q, k, causal, block_q, block_k, "fwd",
                         DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    if (on_tpu or interpret) and plan is not None:
        out, lse = _flash_forward(q, k, v, causal, *plan, interpret)
        out = _unfold(out, q.shape[0], q.shape[2])
        return out, (q, k, v, out, lse)
    return _xla_attention(q, k, v, causal), (q, k, v, None, None)


def _bwd(causal, block_q, block_k, interpret, bwd_block_q, bwd_block_k,
         res, g):
    q, k, v, o, lse = res
    if lse is not None:
        # backward block precedence: explicit bwd blocks > explicit
        # shared blocks > autotune "bwd" entry > backward defaults
        bq = bwd_block_q if bwd_block_q is not None else block_q
        bk = bwd_block_k if bwd_block_k is not None else block_k
        plan = _resolve_plan(q, k, causal, bq, bk, "bwd",
                             DEFAULT_BWD_BLOCK_Q, DEFAULT_BWD_BLOCK_K)
        if plan is None:    # bwd blocks unaligned for these shapes
            plan = _plan(q, k, causal, bq or DEFAULT_BLOCK_Q,
                         bk or DEFAULT_BLOCK_K)
        if plan is None:
            # the bwd-specific override itself cannot apply to these
            # shapes: drop it and reuse the forward's blocks, which the
            # forward pass just validated (lse is not None), so this
            # plan is guaranteed concrete
            plan = _resolve_plan(q, k, causal, block_q, block_k, "fwd",
                                 DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
        batch, seq_q, heads, head_dim = q.shape
        partial_bytes = (batch * heads * (k.shape[1] // plan[1])
                         * seq_q * head_dim * q.dtype.itemsize)
        use_fused = (_BWD_IMPL == "fused"
                     and partial_bytes <= FUSED_PARTIAL_BUDGET)
        impl = _flash_backward_fused if use_fused else _flash_backward
        return impl(q, k, v, o, lse, g, causal, *plan, interpret)
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Expand grouped KV heads to the full head count ([B, S, Hkv, D] ->
    [B, S, Hkv*n_rep, D])."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)
