"""Flash attention: fused pallas TPU kernels (forward + backward) + XLA fallback.

The forward kernel streams K/V blocks through VMEM with online-softmax
accumulation so the [S, S] score matrix never hits HBM (HBM bandwidth, not
FLOPs, bounds naive attention).  Grid is (batch*heads, q-blocks); the causal
variant skips K/V blocks entirely above the diagonal.  The forward also
emits the per-row logsumexp so the backward can reconstruct the softmax
without a second online pass.

The backward is two kernels (the standard TPU split, since TPU has no
atomics and pallas grids write disjoint output blocks):

- dq kernel: grid over q-blocks, scans K/V, accumulates dq.
- dkv kernel: grid over k-blocks, scans Q/dO, accumulates dk and dv.

Both recompute p = exp(s - lse) from the saved logsumexp (flash-attention-2
style), use ds = p * (dp - delta) with delta = rowsum(dO * O) computed once
in XLA, and keep fp32 accumulation on the MXU (preferred_element_type).
Written per /opt/skills/guides/pallas_guide.md: (block, 128)-aligned tiles,
broadcasted_iota position masks, fori_loop streaming.

Layout convention everywhere in nos_tpu: [batch, seq, heads, head_dim].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nos_tpu.parallel.ring import dense_attention

_NEG_INF = -1e30


def _xla_attention(q, k, v, causal):
    return dense_attention(q, k, v, causal=causal)


def _causal_mask(qi, kj, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return q_pos >= k_pos


def _fold(x):
    """[B, S, H, D] -> [B*H, S, D] (TPU block shapes constrain only the
    last two dims, which become (seq-block, head_dim))."""
    b, s, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(x, batch, heads):
    bh, s, d = x.shape
    return x.reshape(batch, heads, s, d).transpose(0, 2, 1, 3)


# -- forward ----------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k):
    qi = pl.program_id(1)
    seq_k = k_ref.shape[1]
    num_k_blocks = seq_k // block_k
    q = q_ref[0].astype(jnp.float32) * scale               # [bq, D]
    head_dim = q.shape[-1]

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

    def body(j, carry, masked):
        m, l, acc = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, kb.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if masked:
            mask = _causal_mask(qi, j, block_q, block_k)
            s = jnp.where(mask, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if masked:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * corr + jnp.dot(p, vb, preferred_element_type=jnp.float32)
        return m_new, l, acc

    carry = (m0, l0, acc0)
    if causal:
        # [0, full): wholly below the diagonal, mask-free; [full, hi):
        # straddles the diagonal; blocks above it are skipped entirely.
        full = (qi * block_q + 1) // block_k
        hi = jnp.minimum(num_k_blocks,
                         pl.cdiv((qi + 1) * block_q, block_k))
        carry = jax.lax.fori_loop(
            0, full, functools.partial(body, masked=False), carry)
        carry = jax.lax.fori_loop(
            full, hi, functools.partial(body, masked=True), carry)
    else:
        carry = jax.lax.fori_loop(
            0, num_k_blocks, functools.partial(body, masked=False), carry)
    m, l, acc = carry
    l = jnp.maximum(l, 1e-20)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l)                            # [bq, 1]


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    scale = head_dim ** -0.5

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    grid = (batch * heads, seq_q // block_q)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            # [BH, Sq, 1]: a trailing unit dim keeps the block's last two
            # dims TPU-legal ((block_q, 1) with 1 == array dim).
            jax.ShapeDtypeStruct((batch * heads, seq_q, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        cost_estimate=pl.CostEstimate(
            flops=4 * batch * heads * seq_q * seq_k * head_dim,
            bytes_accessed=2 * (q.size + k.size + v.size),
            transcendentals=batch * heads * seq_q * seq_k,
        ),
        interpret=interpret,
    )(qf, kf, vf)
    return out, lse


# -- backward ---------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    seq_k = k_ref.shape[1]
    num_k_blocks = seq_k // block_k
    q = q_ref[0].astype(jnp.float32)                       # [bq, D]
    do = do_ref[0].astype(jnp.float32)                     # [bq, D]
    lse = lse_ref[0]                                       # [bq, 1]
    delta = delta_ref[0]                                   # [bq, 1]

    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    def body(j, acc, masked):
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = scale * jnp.dot(q, kb.T, preferred_element_type=jnp.float32)
        if masked:
            mask = _causal_mask(qi, j, block_q, block_k)
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk]
        dp = jnp.dot(do, vb.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return acc + jnp.dot(ds, kb, preferred_element_type=jnp.float32)

    if causal:
        full = (qi * block_q + 1) // block_k
        hi = jnp.minimum(num_k_blocks,
                         pl.cdiv((qi + 1) * block_q, block_k))
        acc = jax.lax.fori_loop(
            0, full, functools.partial(body, masked=False), acc0)
        acc = jax.lax.fori_loop(
            full, hi, functools.partial(body, masked=True), acc)
    else:
        acc = jax.lax.fori_loop(
            0, num_k_blocks, functools.partial(body, masked=False), acc0)
    dq_ref[0] = (scale * acc).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q, block_k):
    kj = pl.program_id(1)
    seq_q = q_ref.shape[1]
    num_q_blocks = seq_q // block_q
    k = k_ref[0].astype(jnp.float32)                       # [bk, D]
    v = v_ref[0].astype(jnp.float32)                       # [bk, D]

    acc0 = (jnp.zeros((block_k, k.shape[-1]), jnp.float32),
            jnp.zeros((block_k, v.shape[-1]), jnp.float32))

    def body(i, carry, masked):
        dk_acc, dv_acc = carry
        qb = q_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(i * block_q, block_q), :]   # [bq, 1]
        delta = delta_ref[0, pl.ds(i * block_q, block_q), :]
        s = scale * jnp.dot(qb, k.T, preferred_element_type=jnp.float32)
        if masked:
            mask = _causal_mask(i, kj, block_q, block_k)
            s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse)                               # [bq, bk]
        dv_acc = dv_acc + jnp.dot(p.T, dob,
                                  preferred_element_type=jnp.float32)
        dp = jnp.dot(dob, v.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc = dk_acc + jnp.dot(ds.T, qb,
                                  preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    if causal:
        # [lo, full): straddles the diagonal, masked; [full, end): wholly
        # below it, mask-free.  Blocks above the diagonal are skipped.
        lo = (kj * block_k) // block_q
        full = pl.cdiv((kj + 1) * block_k - 1, block_q)
        carry = jax.lax.fori_loop(
            lo, full, functools.partial(body, masked=True), acc0)
        dk_acc, dv_acc = jax.lax.fori_loop(
            full, num_q_blocks, functools.partial(body, masked=False), carry)
    else:
        dk_acc, dv_acc = jax.lax.fori_loop(
            0, num_q_blocks, functools.partial(body, masked=False), acc0)
    dk_ref[0] = (scale * dk_acc).astype(dk_ref.dtype)
    dv_ref[0] = dv_acc.astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k, interpret):
    batch, seq_q, heads, head_dim = q.shape
    seq_k = k.shape[1]
    scale = head_dim ** -0.5

    qf, kf, vf = _fold(q), _fold(k), _fold(v)
    dof = _fold(g)
    # delta_i = sum_d dO_id * O_id — one fused elementwise+reduce, XLA-side.
    delta = jnp.sum(dof.astype(jnp.float32) * _fold(o).astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BH, Sq, 1]

    qspec = pl.BlockSpec((1, block_q, head_dim), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM)
    qfull = pl.BlockSpec((1, seq_q, head_dim), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM)
    kspec = pl.BlockSpec((1, block_k, head_dim), lambda b, j: (b, j, 0),
                         memory_space=pltpu.VMEM)
    kfull = pl.BlockSpec((1, seq_k, head_dim), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM)
    rowspec = pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    rowfull = pl.BlockSpec((1, seq_q, 1), lambda b, j: (b, 0, 0),
                           memory_space=pltpu.VMEM)

    bwd_flops = 10 * batch * heads * seq_q * seq_k * head_dim

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        grid=(batch * heads, seq_q // block_q),
        in_specs=[qspec, kfull, kfull, qspec, rowspec, rowspec],
        out_specs=qspec,
        cost_estimate=pl.CostEstimate(
            flops=bwd_flops // 2, bytes_accessed=3 * q.size,
            transcendentals=batch * heads * seq_q * seq_k),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        out_shape=[jax.ShapeDtypeStruct(kf.shape, k.dtype),
                   jax.ShapeDtypeStruct(vf.shape, v.dtype)],
        grid=(batch * heads, seq_k // block_k),
        in_specs=[qfull, kspec, kspec, qfull, rowfull, rowfull],
        out_specs=[kspec, kspec],
        cost_estimate=pl.CostEstimate(
            flops=bwd_flops // 2, bytes_accessed=3 * q.size,
            transcendentals=batch * heads * seq_q * seq_k),
        interpret=interpret,
    )(qf, kf, vf, dof, lse, delta)

    return (_unfold(dq, batch, heads), _unfold(dk, batch, heads),
            _unfold(dv, batch, heads))


# -- public op with custom VJP ----------------------------------------------

def _supported(q, k, block_q, block_k) -> bool:
    _, seq_q, _, head_dim = q.shape
    seq_k = k.shape[1]
    return (seq_q % block_q == 0 and seq_k % block_k == 0
            and head_dim % 128 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 256, block_k: int = 512,
                    interpret: bool = False):
    """Fused attention, [B, S, H, D], K/V already at full head count
    (repeat grouped KV heads first — see repeat_kv).  Falls back to the
    XLA implementation off-TPU or for unaligned shapes."""
    on_tpu = jax.default_backend() == "tpu"
    if (on_tpu or interpret) and _supported(q, k, block_q, block_k):
        out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
        return _unfold(out, q.shape[0], q.shape[2])
    return _xla_attention(q, k, v, causal)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    on_tpu = jax.default_backend() == "tpu"
    if (on_tpu or interpret) and _supported(q, k, block_q, block_k):
        out, lse = _flash_forward(q, k, v, causal, block_q, block_k,
                                  interpret)
        out = _unfold(out, q.shape[0], q.shape[2])
        return out, (q, k, v, out, lse)
    return _xla_attention(q, k, v, causal), (q, k, v, None, None)


def _bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    if lse is not None:
        return _flash_backward(q, k, v, o, lse, g, causal,
                               block_q, block_k, interpret)
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """Expand grouped KV heads to the full head count ([B, S, Hkv, D] ->
    [B, S, Hkv*n_rep, D])."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(
        k[:, :, :, None, :], (b, s, h, n_rep, d)
    ).reshape(b, s, h * n_rep, d)
