"""Roofline accounting: peaks, analytic model FLOPs, slope timing.

The single source of truth for "what fraction of the chip did we use":
bench_compute.py, scripts/mfu_explore.py, scripts/diag_batch16.py, the
autotuner and the cmd/train telemetry hook all judge MFU against THESE
peaks, THIS FLOP count and (for the benches) THIS timing method — two
of them disagreeing would make a regression gate unfalsifiable.  Peaks
are the public Cloud TPU bf16 specs.
"""

from __future__ import annotations

import time

#: Nominal bf16 peak FLOP/s per chip, matched by substring against the
#: jax ``device_kind`` string.  Order matters: more specific needles
#: ("v5e", "v5p") must precede the bare "v5" catch-all.
PEAK_TFLOPS = {"v6e": 918e12, "trillium": 918e12,
               "v5p": 459e12,
               "v5e": 197e12, "v5litepod": 197e12, "v5 lite": 197e12,
               "v5": 197e12,
               "v4": 275e12}
DEFAULT_PEAK = 197e12


def peak_for(device_kind: str) -> float:
    """Nominal bf16 peak FLOP/s for a jax device_kind string."""
    kind = device_kind.lower()
    return next((v for k, v in PEAK_TFLOPS.items() if k in kind),
                DEFAULT_PEAK)


def model_flops_per_step(cfg, batch: int, seq: int) -> float:
    """Analytic Llama train-step FLOPs (fwd+bwd, no remat credit): 6*T per
    matmul param + causal attention matmuls.  `cfg` is a LlamaConfig (duck
    typed so this module needs no jax/flax import)."""
    per_layer_mm = (
        cfg.hidden_size * cfg.num_heads * cfg.head_dim          # q
        + 2 * cfg.hidden_size * cfg.num_kv_heads * cfg.head_dim  # k, v
        + cfg.num_heads * cfg.head_dim * cfg.hidden_size        # o
        + 3 * cfg.hidden_size * cfg.intermediate_size           # mlp
    )
    n_mm = cfg.num_layers * per_layer_mm + cfg.vocab_size * cfg.hidden_size
    tokens = batch * seq
    matmul = 6 * n_mm * tokens
    # QK^T and PV: 2 matmuls x 2 FLOPs x B*H*S^2*D, causal halves it,
    # backward doubles it (fwd 1x + bwd 2x = 3x).
    attn = 3 * cfg.num_layers * 2 * batch * cfg.num_heads * seq * seq \
        * cfg.head_dim
    return float(matmul + attn)


def slope(fn_maker, n1: int = 20, n2: int = 80, reps: int = 5) -> float:
    """Per-iteration device time = (t[n2] - t[n1]) / (n2 - n1) over
    min-of-reps wall times: the chained-iteration slope method
    (bench_compute.py module docstring) — the tunnel RTT cancels in the
    difference, the min filters tunnel jitter.  `fn_maker(n)` returns a
    thunk running an n-iteration chain to completion; both chain
    lengths must share one compiled program (pass n as a traced
    scalar).  Shared by bench_compute (which re-exports it as `_slope`
    for the sweep scripts) and the flash block autotuner, so candidate
    rankings and bench numbers come from ONE methodology."""
    fa, fb = fn_maker(n1), fn_maker(n2)
    fa(), fb()  # compile + warm
    tsa, tsb = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        fa()
        tsa.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        fb()
        tsb.append(time.perf_counter() - t0)
    return (min(tsb) - min(tsa)) / (n2 - n1)
