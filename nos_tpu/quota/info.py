"""ElasticQuotaInfo: the per-quota usage ledger.

Re-derivation of reference
pkg/scheduler/plugins/capacityscheduling/elasticquotainfo.go:30-361 with
ResourceLists as plain dicts.  Comparison semantics preserved exactly:

- `cpu` and `memory` are compared unconditionally (they are first-class
  fields of the Go framework.Resource, defaulting to 0 — sumGreaterThan,
  elasticquotainfo.go:319-338).
- every other (scalar) resource is compared only when present in the limit
  being checked — a quota that doesn't mention `google.com/tpu` doesn't
  bound it.
"""

from __future__ import annotations

import math
from typing import Iterable, Mapping

from nos_tpu.kube.resources import (
    ResourceList, subtract_non_negative, sum_resources,
)

# Resources compared unconditionally against a limit (missing == 0).
_ALWAYS_ENFORCED = ("cpu", "memory")


def sum_greater_than(x1: Mapping[str, float], x2: Mapping[str, float],
                     y: Mapping[str, float]) -> bool:
    """True iff any resource of (x1+x2) that y enforces exceeds y.
    Reference elasticquotainfo.go:319-338."""
    for r in _ALWAYS_ENFORCED:
        if x1.get(r, 0.0) + x2.get(r, 0.0) > y.get(r, 0.0):
            return True
    for r in set(x1) | set(x2):
        if r in _ALWAYS_ENFORCED:
            continue
        if r in y and x1.get(r, 0.0) + x2.get(r, 0.0) > y[r]:
            return True
    return False


def greater_than(x: Mapping[str, float], y: Mapping[str, float]) -> bool:
    return sum_greater_than(x, {}, y)


def sum_less_than_equal(x1: Mapping[str, float], x2: Mapping[str, float],
                        y: Mapping[str, float]) -> bool:
    return not sum_greater_than(x1, x2, y)


class ElasticQuotaInfo:
    """Wraps one ElasticQuota or CompositeElasticQuota with usage tracking
    (reference elasticquotainfo.go:176-310)."""

    def __init__(self, resource_name: str, resource_namespace: str,
                 namespaces: Iterable[str], min: ResourceList,
                 max: ResourceList | None, calculator,
                 composite: bool = False) -> None:
        self.resource_name = resource_name
        self.resource_namespace = resource_namespace
        self.namespaces: set[str] = set(namespaces)
        self.min: ResourceList = dict(min)
        self.max: ResourceList = dict(max or {})
        self.max_enforced = bool(max)
        self.used: ResourceList = {}
        self.pods: set[str] = set()
        self.calculator = calculator
        self.composite = composite

    # -- usage bookkeeping --------------------------------------------------
    def add_pod_if_not_present(self, pod) -> None:
        key = pod.key
        if key in self.pods:
            return
        self.pods.add(key)
        self.used = sum_resources(self.used, self.calculator.compute_pod_request(pod))

    def delete_pod_if_present(self, pod) -> None:
        key = pod.key
        if key not in self.pods:
            return
        self.pods.discard(key)
        req = self.calculator.compute_pod_request(pod)
        self.used = {k: self.used.get(k, 0.0) - req.get(k, 0.0)
                     for k in set(self.used) | set(req)}

    # -- limit checks -------------------------------------------------------
    def used_over_min_with(self, pod_request: ResourceList) -> bool:
        return sum_greater_than(pod_request, self.used, self.min)

    def used_over_max_with(self, pod_request: ResourceList) -> bool:
        if self.max_enforced:
            return sum_greater_than(pod_request, self.used, self.max)
        return False

    def used_over_min(self) -> bool:
        return greater_than(self.used, self.min)

    def used_over(self, limit: ResourceList) -> bool:
        return greater_than(self.used, limit)

    def used_lte_with(self, limit: ResourceList, pod_request: ResourceList) -> bool:
        return sum_less_than_equal(pod_request, self.used, limit)

    def clone(self) -> "ElasticQuotaInfo":
        out = ElasticQuotaInfo(
            self.resource_name, self.resource_namespace, set(self.namespaces),
            dict(self.min), dict(self.max) if self.max_enforced else None,
            self.calculator, self.composite,
        )
        out.max_enforced = self.max_enforced
        out.used = dict(self.used)
        out.pods = set(self.pods)
        return out


class ElasticQuotaInfos(dict):
    """namespace -> ElasticQuotaInfo (reference elasticquotainfo.go:31-174).
    A CompositeElasticQuota registers the same info under every namespace it
    spans."""

    def clone(self) -> "ElasticQuotaInfos":
        out = ElasticQuotaInfos()
        seen: dict[int, ElasticQuotaInfo] = {}
        for ns, info in self.items():
            # Preserve identity sharing: composite quotas must stay one object.
            if id(info) not in seen:
                seen[id(info)] = info.clone()
            out[ns] = seen[id(info)]
        return out

    def add(self, info: ElasticQuotaInfo) -> None:
        for ns in info.namespaces:
            self[ns] = info

    def update_info(self, old: ElasticQuotaInfo, new: ElasticQuotaInfo) -> None:
        """Replace old with new, carrying forward observed usage.

        Usage is carried from `old` — the previous info of the *same quota
        object* — not from whatever info each namespace happened to map to
        (the reference's per-namespace carry, elasticquotainfo.go:51-66, is
        last-wins over map iteration and corrupts a CompositeElasticQuota's
        ledger when its namespace set grows to cover a plain ElasticQuota).
        Pods in newly-covered namespaces are picked up by the caller's
        recount (CapacityScheduling._recount); add_pod_if_not_present makes
        that idempotent."""
        new.pods = set(old.pods)
        new.used = dict(old.used)
        for ns in old.namespaces:
            if ns not in new.namespaces and self.get(ns) is old:
                del self[ns]
        for ns in new.namespaces:
            self[ns] = new

    def delete(self, info: ElasticQuotaInfo) -> None:
        for ns in info.namespaces:
            self.pop(ns, None)

    # -- aggregates ---------------------------------------------------------
    def _unique_infos(self) -> list[ElasticQuotaInfo]:
        seen: dict[int, ElasticQuotaInfo] = {}
        for info in self.values():
            seen[id(info)] = info
        return list(seen.values())

    def aggregated_min(self) -> ResourceList:
        total: ResourceList = {}
        for info in self._unique_infos():
            total = sum_resources(total, info.min)
        return total

    def aggregated_used(self) -> ResourceList:
        total: ResourceList = {}
        for info in self._unique_infos():
            total = sum_resources(total, info.used)
        return total

    def aggregated_used_over_min_with(self, pod_request: ResourceList) -> bool:
        return sum_greater_than(self.aggregated_used(), pod_request,
                                self.aggregated_min())

    def aggregated_overquotas(self) -> ResourceList:
        """Total quota usable over-min: sum of each quota's unused min
        (reference elasticquotainfo.go:121-152)."""
        total: ResourceList = {}
        for info in self._unique_infos():
            total = sum_resources(total, subtract_non_negative(info.min, info.used))
        return total

    def get_guaranteed_overquotas(self, namespace: str) -> ResourceList:
        """The share of aggregate unused min guaranteed to `namespace`'s
        quota, proportional to its min (reference elasticquotainfo.go:81-119).
        """
        info = self.get(namespace)
        if info is None:
            raise KeyError(f"no elastic quota covers namespace {namespace!r}")
        total_min = self.aggregated_min()
        over = self.aggregated_overquotas()
        result: ResourceList = {}
        for r, v in over.items():
            t = total_min.get(r, 0.0)
            pct = (info.min.get(r, 0.0) / t) if t > 0 else 0.0
            result[r] = float(math.floor(v * pct))
        return result
