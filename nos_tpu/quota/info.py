"""ElasticQuotaInfo: the per-quota usage ledger.

Re-derivation of reference
pkg/scheduler/plugins/capacityscheduling/elasticquotainfo.go:30-361 with
ResourceLists as plain dicts.

Enforcement semantics — one deliberate divergence from the reference: a
limit bounds ONLY the resources it names.  The reference's Go
framework.Resource compares cpu/memory unconditionally (sumGreaterThan,
elasticquotainfo.go:319-338), which makes any pod with a cpu request
permanently unschedulable under a quota denominated purely in
`nos.tpu/tpu-memory` — while its own reconciler labels the same pod
in-quota via quota.LessThanOrEqual (elasticquota.go:53), which checks only
named resources.  We use the reconciler's (coherent) semantics everywhere.
"""

from __future__ import annotations

import math
from typing import Iterable, Mapping

from nos_tpu.kube.resources import (
    ResourceList, subtract, subtract_non_negative, sum_resources,
)


def sum_greater_than(x1: Mapping[str, float], x2: Mapping[str, float],
                     y: Mapping[str, float]) -> bool:
    """True iff any resource of (x1+x2) that y names exceeds y."""
    return any(x1.get(r, 0.0) + x2.get(r, 0.0) > limit
               for r, limit in y.items())


def greater_than(x: Mapping[str, float], y: Mapping[str, float]) -> bool:
    return sum_greater_than(x, {}, y)


def sum_less_than_equal(x1: Mapping[str, float], x2: Mapping[str, float],
                        y: Mapping[str, float]) -> bool:
    return not sum_greater_than(x1, x2, y)


class ElasticQuotaInfo:
    """Wraps one ElasticQuota or CompositeElasticQuota with usage tracking
    (reference elasticquotainfo.go:176-310)."""

    def __init__(self, resource_name: str, resource_namespace: str,
                 namespaces: Iterable[str], min: ResourceList,
                 max: ResourceList | None, calculator,
                 composite: bool = False) -> None:
        self.resource_name = resource_name
        self.resource_namespace = resource_namespace
        self.namespaces: set[str] = set(namespaces)
        self.min: ResourceList = dict(min)
        self.max: ResourceList = dict(max or {})
        self.max_enforced = bool(max)
        self.used: ResourceList = {}
        # pod key ("ns/name") -> the request booked for it, so usage can be
        # reclaimed without the pod object (e.g. when a composite quota's
        # namespace set shrinks and the pod leaves the ledger's view).
        self.pods: dict[str, ResourceList] = {}
        self.calculator = calculator
        self.composite = composite

    # -- usage bookkeeping --------------------------------------------------
    def add_pod_if_not_present(self, pod) -> None:
        key = pod.key
        if key in self.pods:
            return
        req = self.calculator.compute_pod_request(pod)
        self.pods[key] = req
        self.used = sum_resources(self.used, req)

    def delete_pod_if_present(self, pod) -> None:
        self._release(pod.key)

    def _release(self, key: str) -> None:
        req = self.pods.pop(key, None)
        if req is not None:
            self.used = subtract(self.used, req)

    # -- limit checks -------------------------------------------------------
    def used_over_min_with(self, pod_request: ResourceList) -> bool:
        return sum_greater_than(pod_request, self.used, self.min)

    def used_over_max_with(self, pod_request: ResourceList) -> bool:
        if self.max_enforced:
            return sum_greater_than(pod_request, self.used, self.max)
        return False

    def used_over_min(self) -> bool:
        return greater_than(self.used, self.min)

    def used_over(self, limit: ResourceList) -> bool:
        return greater_than(self.used, limit)

    def used_lte_with(self, limit: ResourceList, pod_request: ResourceList) -> bool:
        return sum_less_than_equal(pod_request, self.used, limit)

    def clone(self) -> "ElasticQuotaInfo":
        out = ElasticQuotaInfo(
            self.resource_name, self.resource_namespace, set(self.namespaces),
            dict(self.min), dict(self.max) if self.max_enforced else None,
            self.calculator, self.composite,
        )
        out.max_enforced = self.max_enforced
        out.used = dict(self.used)
        out.pods = {k: dict(v) for k, v in self.pods.items()}
        return out


class ElasticQuotaInfos(dict):
    """namespace -> ElasticQuotaInfo (reference elasticquotainfo.go:31-174).
    A CompositeElasticQuota registers the same info under every namespace it
    spans."""

    def clone(self) -> "ElasticQuotaInfos":
        out = ElasticQuotaInfos()
        seen: dict[int, ElasticQuotaInfo] = {}
        for ns, info in self.items():
            # Preserve identity sharing: composite quotas must stay one object.
            if id(info) not in seen:
                seen[id(info)] = info.clone()
            out[ns] = seen[id(info)]
        return out

    def add(self, info: ElasticQuotaInfo) -> None:
        for ns in info.namespaces:
            self[ns] = info

    def update_info(self, old: ElasticQuotaInfo, new: ElasticQuotaInfo) -> None:
        """Replace old with new, carrying forward observed usage.

        Usage is carried from `old` — the previous info of the *same quota
        object* — not from whatever info each namespace happened to map to
        (the reference's per-namespace carry, elasticquotainfo.go:51-66, is
        last-wins over map iteration and corrupts a CompositeElasticQuota's
        ledger when its namespace set grows to cover a plain ElasticQuota).
        Pods whose namespace left the quota are released (their booked
        request is subtracted); pods in newly-covered namespaces are picked
        up by the caller's recount (CapacityScheduling._recount), which
        add_pod_if_not_present makes idempotent."""
        new.pods = {k: dict(v) for k, v in old.pods.items()}
        new.used = dict(old.used)
        for key in list(new.pods):
            ns = key.split("/", 1)[0]
            if ns not in new.namespaces:
                new._release(key)
        for ns in old.namespaces:
            if ns not in new.namespaces and self.get(ns) is old:
                del self[ns]
        for ns in new.namespaces:
            self[ns] = new

    def delete(self, info: ElasticQuotaInfo) -> None:
        for ns in info.namespaces:
            self.pop(ns, None)

    # -- aggregates ---------------------------------------------------------
    def _unique_infos(self) -> list[ElasticQuotaInfo]:
        seen: dict[int, ElasticQuotaInfo] = {}
        for info in self.values():
            seen[id(info)] = info
        return list(seen.values())

    def aggregated_min(self) -> ResourceList:
        total: ResourceList = {}
        for info in self._unique_infos():
            total = sum_resources(total, info.min)
        return total

    def aggregated_used(self) -> ResourceList:
        total: ResourceList = {}
        for info in self._unique_infos():
            total = sum_resources(total, info.used)
        return total

    def aggregated_used_over_min_with(self, pod_request: ResourceList) -> bool:
        return sum_greater_than(self.aggregated_used(), pod_request,
                                self.aggregated_min())

    def aggregated_overquotas(self) -> ResourceList:
        """Total quota usable over-min: sum of each quota's unused min
        (reference elasticquotainfo.go:121-152)."""
        total: ResourceList = {}
        for info in self._unique_infos():
            total = sum_resources(total, subtract_non_negative(info.min, info.used))
        return total

    def get_guaranteed_overquotas(self, namespace: str) -> ResourceList:
        """The share of aggregate unused min guaranteed to `namespace`'s
        quota, proportional to its min (reference elasticquotainfo.go:81-119).
        """
        info = self.get(namespace)
        if info is None:
            raise KeyError(f"no elastic quota covers namespace {namespace!r}")
        total_min = self.aggregated_min()
        over = self.aggregated_overquotas()
        result: ResourceList = {}
        for r, v in over.items():
            t = total_min.get(r, 0.0)
            pct = (info.min.get(r, 0.0) / t) if t > 0 else 0.0
            result[r] = float(math.floor(v * pct))
        return result
