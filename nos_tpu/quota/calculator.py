"""TPU resource calculator: pod requests in the quota currency.

The reference derives a synthetic `nos.nebuly.com/gpu-memory` scalar from GPU
requests so quotas can be denominated in one fungible unit across whole GPUs
and MIG profiles (pkg/gpu/util/resource.go:28-86).  The TPU analog derives
`nos.tpu/tpu-memory` (HBM gigabytes) from:

- whole chips (`google.com/tpu`): chips x hbm_gb_per_chip
- slice profiles (`nos.tpu/slice-<XxY[xZ]>`): shape.chips x hbm_gb_per_chip
- timeshare profiles (`nos.tpu/tpu-<N>gb`): N directly
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.kube.resources import ResourceList, pod_request
from nos_tpu.topology.profile import gb_from_resource, shape_from_resource


class TPUResourceCalculator:
    """Computes effective pod requests with the tpu-memory scalar added.

    `hbm_gb_per_chip` plays the role of the reference's
    `nvidiaGpuResourceMemoryGB` operator config (default 32 GB there;
    16 GB here = v5e chip HBM).
    """

    def __init__(self, hbm_gb_per_chip: int = 16) -> None:
        self.hbm_gb_per_chip = hbm_gb_per_chip

    def compute_pod_request(self, pod) -> ResourceList:
        req = pod_request(pod)
        req[C.RESOURCE_TPU_MEMORY] = float(self.compute_required_tpu_memory_gb(req))
        return req

    def compute_required_tpu_memory_gb(self, request: ResourceList) -> int:
        total = 0
        for resource, qty in request.items():
            if qty <= 0:
                continue
            if resource == C.RESOURCE_TPU:
                total += self.hbm_gb_per_chip * int(qty)
                continue
            shape = shape_from_resource(resource)
            if shape is not None:
                total += shape.chips * self.hbm_gb_per_chip * int(qty)
                continue
            gb = gb_from_resource(resource)
            if gb is not None:
                total += gb * int(qty)
        return total
