"""TPU resource calculator: pod requests in the quota currency.

The reference derives a synthetic `nos.nebuly.com/gpu-memory` scalar from GPU
requests so quotas can be denominated in one fungible unit across whole GPUs
and MIG profiles (pkg/gpu/util/resource.go:28-86).  The TPU analog derives
`nos.tpu/tpu-memory` (HBM gigabytes) from:

- whole chips (`google.com/tpu`): chips x hbm_gb_per_chip
- slice profiles (`nos.tpu/slice-<XxY[xZ]>`): shape.chips x hbm_gb_per_chip
- timeshare profiles (`nos.tpu/tpu-<N>gb`): N directly
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.kube.resources import ResourceList, pod_request
from nos_tpu.topology.profile import gb_from_resource, shape_from_resource


class TPUResourceCalculator:
    """Computes effective pod requests with the tpu-memory scalar added.

    `hbm_gb_per_chip` plays the role of the reference's
    `nvidiaGpuResourceMemoryGB` operator config (default 32 GB there;
    16 GB here = v5e chip HBM).

    `chips_per_host` (optional, 0 = off) enables host-shard accounting
    for multi-host slices: one unit of a multi-host slice resource is
    one HOST-SHARD of the instance (the partitioner advertises one
    shard per member host — partitioning/slicepart/group.py — and each
    gang member binds one), so a member is charged the chips it
    physically owns, `shape.chips / hosts`, not the whole slice.  With
    0, every unit is charged its full shape — each member of an
    N-host gang then books the slice N times, which overstates a
    gang-heavy namespace's usage N-fold against its quota.  Set it to
    the cluster generation's chips-per-host (8 for v4/v5e/v5p/v6e
    host blocks) unless generations are mixed.
    """

    def __init__(self, hbm_gb_per_chip: int = 16,
                 chips_per_host: int = 0) -> None:
        self.hbm_gb_per_chip = hbm_gb_per_chip
        self.chips_per_host = chips_per_host

    def _unit_chips(self, shape) -> int:
        """Chips charged for ONE unit of a slice resource."""
        if 0 < self.chips_per_host < shape.chips \
                and shape.chips % self.chips_per_host == 0:
            return self.chips_per_host
        return shape.chips

    def compute_pod_request(self, pod) -> ResourceList:
        req = pod_request(pod)
        req[C.RESOURCE_TPU_MEMORY] = float(self.compute_required_tpu_memory_gb(req))
        return req

    def compute_required_tpu_memory_gb(self, request: ResourceList) -> int:
        total = 0
        for resource, qty in request.items():
            if qty <= 0:
                continue
            if resource == C.RESOURCE_TPU:
                total += self.hbm_gb_per_chip * int(qty)
                continue
            shape = shape_from_resource(resource)
            if shape is not None:
                total += self._unit_chips(shape) * self.hbm_gb_per_chip \
                    * int(qty)
                continue
            gb = gb_from_resource(resource)
            if gb is not None:
                total += gb * int(qty)
        return total
