"""Elastic-quota bookkeeping: the TPU-memory currency and the quota ledger.

Analog of reference pkg/gpu/util/resource.go (ResourceCalculator) and
pkg/scheduler/plugins/capacityscheduling/elasticquotainfo.go.
"""

from .calculator import TPUResourceCalculator
from .info import ElasticQuotaInfo, ElasticQuotaInfos, greater_than, sum_greater_than

__all__ = [
    "TPUResourceCalculator",
    "ElasticQuotaInfo", "ElasticQuotaInfos",
    "greater_than", "sum_greater_than",
]
