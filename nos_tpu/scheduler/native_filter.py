"""Native prescreen for the Filter per-pod x node hot loop.

The Filter pipeline runs per (pod equivalence class, node) in both the
scheduler's cycle and the planner's what-if simulation; at fleet scale
(1024+ hosts) the Python pipeline — lock, plugin dispatch, Status
allocation — dominates the cycle even though almost every verdict is a
plain resource comparison.  `FitPrescreen` pushes exactly that
comparison into the C++ shim (tpu_shim.cc `nos_fit_batch`, next to the
packer) as a batch call that RELEASES the GIL, so concurrent plan
shards screening at once genuinely overlap.

Soundness is a superset contract, never a semantic fork:

- the native math replays `NodeResourcesFit.filter` bit-for-bit on the
  same doubles (request <= free per requested resource, then the
  chip-equivalent aggregate guard), so a native FAIL is exactly a
  NodeResourcesFit fail;
- a pipeline containing the exact in-tree `NodeResourcesFit` class
  fails whenever any plugin fails, so native-fail implies
  pipeline-fail: fail verdicts may be recorded without running the
  pipeline (`verdict_sound`);
- when NodeResourcesFit additionally runs FIRST in the chain, the
  pipeline's failure Status on such a node IS NodeResourcesFit's, so
  the exact rejection message can be reconstructed from the native
  miss mask (`message_exact`) — the scheduler's journal/explain output
  is byte-identical with and without the screen;
- native PASS verdicts decide nothing: those (class, node) pairs still
  run the full Python pipeline.

A subclassed or re-ordered plugin chain disables the corresponding
level automatically; an unavailable shim disables everything (every
screen call falls back to `None`, callers run the pure-Python path).
tests/test_native.py pins the native-vs-Python equivalence property.

Cost discipline: the planner calls `compile_classes` ONCE per plan —
the request matrix, chip vector and output buffers become reusable
ctypes arrays — so each candidate node pays one free-row fill plus one
GIL-free C call, not a fresh marshal of every class.
"""

from __future__ import annotations

import ctypes

from nos_tpu.device import native
from nos_tpu.kube.resources import ResourceList

from .framework import Framework, NodeInfo, NodeResourcesFit, _slice_chips


class CompiledClasses:
    """Plan-lifetime screen state: the class request matrix and scratch
    buffers, marshalled once.  NOT thread-safe — one instance per
    planning thread (each plan shard compiles its own)."""

    __slots__ = ("universe", "n", "n_res", "req_arr", "chips_arr",
                 "free_arr", "cap_arr", "used_arr", "out_arr", "any_chips")

    def __init__(self, universe: list[str],
                 classes: list[tuple[ResourceList, int]]) -> None:
        self.universe = universe
        self.n = len(classes)
        self.n_res = len(universe)
        req_flat = [
            float(request.get(name, 0.0))
            for request, _ in classes for name in universe]
        self.req_arr = (ctypes.c_double * max(1, len(req_flat)))(*req_flat)
        self.chips_arr = (ctypes.c_double * self.n)(
            *[float(chips) for _, chips in classes])
        self.any_chips = any(chips for _, chips in classes)
        self.free_arr = (ctypes.c_double * max(1, self.n_res))()
        self.cap_arr = (ctypes.c_double * 1)()
        self.used_arr = (ctypes.c_double * 1)()
        self.out_arr = (ctypes.c_uint8 * self.n)()


class FitPrescreen:
    """Batch resource-fit screen bound to one framework's filter chain."""

    def __init__(self, framework: Framework) -> None:
        chain = framework.filter_chain
        self.verdict_sound = any(
            type(p) is NodeResourcesFit for p in chain)
        self.message_exact = bool(chain) and \
            type(chain[0]) is NodeResourcesFit

    # -- planner path: one node x M compiled classes, verdicts only ---------
    def compile_classes(
        self, classes: list[tuple[ResourceList, int]],
    ) -> CompiledClasses | None:
        """Marshal the plan's equivalence classes once; None when the
        screen cannot run (unsound chain, shim missing, too many
        distinct resources)."""
        if not self.verdict_sound or not classes:
            return None
        if not native.fit_batch_available():
            return None
        universe = sorted({
            name for request, _ in classes
            for name, qty in request.items() if qty > 0})
        if len(universe) > native.FIT_MAX_RESOURCES:
            return None
        return CompiledClasses(universe, classes)

    def screen_compiled(self, node_info: NodeInfo,
                        compiled: CompiledClasses) -> list[bool] | None:
        """Verdict per compiled class against one node state; None =
        screen unavailable (caller runs the pipeline)."""
        free = node_info.free()
        for i, name in enumerate(compiled.universe):
            compiled.free_arr[i] = free.get(name, 0.0)
        if compiled.any_chips:
            compiled.cap_arr[0] = float(_slice_chips(node_info.allocatable))
            compiled.used_arr[0] = float(_slice_chips(node_info.requested))
        if not native.fit_batch_raw(
                compiled.free_arr, compiled.req_arr, compiled.cap_arr,
                compiled.used_arr, compiled.chips_arr, 1, compiled.n,
                compiled.n_res, compiled.out_arr):
            return None
        return [compiled.out_arr[j] == 1 for j in range(compiled.n)]

    # -- scheduler path: N nodes x one class, exact messages ----------------
    def screen_nodes(
        self, node_infos: list[NodeInfo], request: ResourceList,
        pod_chips: int,
        chip_cache: dict[str, tuple[int, int]] | None = None,
    ) -> list[str | None] | None:
        """Per-node rejection message for native fails (None entry =
        native pass, run the pipeline); None overall = unavailable.
        Messages are NodeResourcesFit's exact strings, prefixed the way
        the scheduler memoises them ("NodeResourcesFit: ...") — only
        valid under `message_exact`.  `chip_cache` (node name ->
        (cap, used) chip-equivalents) amortises the aggregate-guard
        scans across the classes of one cycle; the caller owns its
        invalidation (drop a node's entry whenever its requested set
        changes)."""
        if not self.message_exact or not node_infos:
            return None
        universe = sorted(
            name for name, qty in request.items() if qty > 0)
        if len(universe) > native.FIT_MAX_RESOURCES:
            return None
        req_flat = [float(request[name]) for name in universe]
        free_flat: list[float] = []
        chips: list[tuple[int, int]] = []
        for ni in node_infos:
            free = ni.free()
            free_flat.extend(free.get(name, 0.0) for name in universe)
            if not pod_chips:
                chips.append((0, 0))
                continue
            cached = chip_cache.get(ni.name) if chip_cache is not None \
                else None
            if cached is None:
                cached = (_slice_chips(ni.allocatable),
                          _slice_chips(ni.requested))
                if chip_cache is not None:
                    chip_cache[ni.name] = cached
            chips.append(cached)
        result = native.fit_batch(
            free_flat, req_flat,
            [float(c) for c, _ in chips], [float(u) for _, u in chips],
            [float(pod_chips)],
            len(node_infos), 1, len(universe))
        if result is None:
            return None
        verdicts, miss = result
        if miss is None:
            return None
        out: list[str | None] = []
        for i in range(len(node_infos)):
            if verdicts[i] == 1:
                out.append(None)
                continue
            mask = miss[i]
            if mask & ~native.FIT_MISS_CHIP_GUARD:
                missing = sorted(
                    universe[r] for r in range(len(universe))
                    if mask & (1 << r))
                out.append("NodeResourcesFit: insufficient "
                           + ", ".join(missing))
            else:
                cap, used = chips[i]
                out.append(
                    f"NodeResourcesFit: insufficient slice chips "
                    f"({used}+{pod_chips} over {cap}; "
                    f"geometry in flux)")
        return out
