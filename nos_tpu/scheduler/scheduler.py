"""The scheduling loop: the cmd/scheduler analog.

The reference recompiles the stock kube-scheduler with the CapacityScheduling
plugin registered (cmd/scheduler/scheduler.go:43-59).  Here the Scheduler
drives the same Framework used by the planner's simulation over the live
cluster view: PreFilter -> Filter (all nodes) -> score (least-requested on
TPU resources) -> Reserve -> bind; on no fit, PostFilter (preemption) then
mark the pod unschedulable so the partitioner notices it
(ExtraResourcesCouldHelpScheduling).
"""

from __future__ import annotations

import functools
import logging
import time
from collections import Counter
from typing import Any, Callable

from nos_tpu.api.constants import (
    ANNOT_DEFRAG_DRAIN as C_ANNOT_DEFRAG_DRAIN,
    ANNOT_DISPLACED as C_ANNOT_DISPLACED,
    ANNOT_GANG_LEASE as C_ANNOT_GANG_LEASE,
    LABEL_ACCELERATOR as C_LABEL_ACCELERATOR,
    LABEL_CHIP_COUNT as C_LABEL_CHIP_COUNT,
    LABEL_HOST_INDEX as C_LABEL_HOST_INDEX,
    LABEL_POD_GROUP as C_LABEL_POD_GROUP,
    LABEL_POD_ID as C_LABEL_POD_ID,
    LABEL_UNSCHEDULABLE_CLASS as C_LABEL_UNSCHEDULABLE_CLASS,
    RESOURCE_TPU,
    TIER_SERVING as C_TIER_SERVING,
    is_warm_spare_labels,
)
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD, NotFound
from nos_tpu.kube.objects import PENDING, RUNNING, Pod, fast_deepcopy
from nos_tpu.kube.resources import pod_request, sum_resources
from nos_tpu.scheduler.cache import SchedulerCache
from nos_tpu.scheduler.framework import (
    CycleState, Framework, NodeInfo, SharedLister, Status, UNSCHEDULABLE,
    _slice_chips, filter_equivalence_key,
)
from nos_tpu.scheduler.native_filter import FitPrescreen
from nos_tpu.scheduler.gang import (
    GANG_HOST_SET_KEY, GANG_POD_ID_KEY, gang_name, gang_slice_windows,
    get_pod_group, set_pod_group_status,
)
from nos_tpu.topology import DEFAULT_REGISTRY
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.obs import journal as J
from nos_tpu.obs.journal import MAX_JOURNAL_NODES, record as journal_record
from nos_tpu.obs.trace import bump as obs_bump, span as obs_span
from nos_tpu.utils.pod_util import (
    admission_rank, displacement, workload_class, workload_tier,
)
from nos_tpu.utils.guards import invalidated_by
from nos_tpu.utils.retry import retry_on_conflict

logger = logging.getLogger(__name__)

REGISTRY.describe("nos_tpu_drain_preemptions_total",
                  "Straggler pods evicted to complete a window drain")
# Batch-scale bucket layout: the default 1 ms - 60 s layout serves
# control-loop latencies, but batch/gang schedule latencies run minutes
# on a saturated fleet — the top buckets must resolve them or every
# queue-heavy class collapses into +Inf.
REGISTRY.describe("nos_tpu_schedule_latency_seconds",
                  "Queue-admission to bind latency per workload class "
                  "(gang = last member bound)",
                  buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                           0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                           120.0, 240.0, 480.0))
# Displacement stamp → re-bind latency: the node-loss recovery SLO's
# histogram (docs/scheduler.md, "Self-healing node-loss recovery").
# Same batch-scale top buckets as schedule latency — a stranded rebind
# runs minutes, and the whole point is seeing that tail.
REGISTRY.describe("nos_tpu_rebind_latency_seconds",
                  "Displacement stamp to re-bind latency per workload "
                  "class (gang = last member bound)",
                  buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 15.0,
                           30.0, 60.0, 120.0, 240.0, 480.0))
REGISTRY.describe("nos_tpu_schedule_pending_age_seconds",
                  "Oldest still-pending pod's age per workload class")
REGISTRY.describe("nos_tpu_schedule_pending_pods",
                  "Still-pending pods per workload class after a cycle")


def _gen_window_sizes(accel: str) -> tuple[int, ...]:
    try:
        gen = DEFAULT_REGISTRY.get(accel)
    except KeyError:
        return ()
    # memoised on the frozen Generation itself: a registry override
    # (load_overrides) installs a NEW Generation, so its sizes are
    # recomputed instead of served stale from an accel-name key
    return _window_sizes_of(gen)


@functools.lru_cache(maxsize=64)
def _window_sizes_of(gen: Any) -> tuple[int, ...]:
    return tuple(sorted({gen.hosts_for(s) for s in gen.multihost_shapes()}))


def _free_chip_equiv(ni: NodeInfo) -> float:
    from nos_tpu.topology.profile import free_chip_equivalents

    return free_chip_equivalents(ni.free())


def attribute_free_chips(
        free: float, hold: dict | None, reserved: bool, demand: bool,
        rejected: bool, quota_budget: float, gang_budget: float,
) -> tuple[str, float, float, float]:
    """Attribute ONE node's free chips to exactly one waterfall category
    (docs/observability.md, "The waterfall"): hold precedence first
    (quarantine > actuation > drain > provisioning — including defrag
    drains, so
    chip-seconds spent emptying a window for a re-carve land in `drain`
    and are never double-counted with `frag_stranded`), then the gang
    window lease, then this cycle's own verdicts, with the demand-capped
    quota/gang budgets consumed in node order.  Returns
    (category, chips taken, remaining quota budget, remaining gang
    budget); the caller books `free - take` as idle_no_demand.  Factored
    out of the cycle-end loop so the conservation property — every chip
    in exactly one bucket, whatever the hold/verdict combination — is
    directly testable (tests/test_defrag.py randomizes it)."""
    from nos_tpu.obs import ledger as L

    take = free
    if hold is not None and L.QUARANTINE in hold:
        cat = L.QUARANTINE
    elif hold is not None and L.ACTUATION in hold:
        cat = L.ACTUATION
    elif hold is not None and L.DRAIN in hold:
        cat = L.DRAIN
    elif hold is not None and L.PROVISIONING in hold:
        # a host the capacity plane is still landing (cloud create →
        # join → first report): its free chips are "cloud is slow",
        # never idle_no_demand or frag (nos_tpu/capacity/provisioner)
        cat = L.PROVISIONING
    elif reserved:
        cat = L.GANG_WAIT
    elif not demand:
        cat = L.IDLE_NO_DEMAND
    elif rejected:
        cat = L.FRAG_STRANDED
    elif quota_budget > 0.0:
        # pending demand rejected at the quota gates BEFORE any
        # geometry scan: the free chips the over-quota pod could
        # use — capped at the blocked demand itself, remainder
        # is idle (one small rejection must not paint the pool)
        cat = L.QUOTA_STRANDED
        take = min(free, quota_budget)
        quota_budget -= take
    elif gang_budget > 0.0:
        cat = L.GANG_WAIT
        take = min(free, gang_budget)
        gang_budget -= take
    else:
        cat = L.IDLE_NO_DEMAND
    return cat, take, quota_budget, gang_budget


def _annotation_progress(pod: Pod) -> float:
    """Default drain-preemption progress source: the workload-reported
    ANNOT_JOB_PROGRESS fraction (absent/garbage/non-finite = 0: nothing
    to lose).  ONE parsing, shared with the displaced-preemptor victim
    walk (utils/pod_util.job_progress)."""
    from nos_tpu.utils.pod_util import job_progress

    return job_progress(pod)


# the cycle lister is the source view behind the per-class scan cache,
# the per-node Filter/chips memos and the window-busy map; noslint N012
# proves every in-place booking through it emits _invalidate_scans.
# The window-busy map carries its own declaration: _mark_busy is BOTH
# its invalidation event and its only in-place writer (flipping a host
# busy after a bind), so N012 conviction-tests it exactly like the
# SchedulerCache indexes instead of trusting an ad-hoc per-cycle reset.
@invalidated_by("_invalidate_scans", "_cycle_lister_cache")
@invalidated_by("_mark_busy", "_busy_map_cache")
class Scheduler:
    def __init__(self, api: APIServer, framework: Framework,
                 name: str = "nos-tpu-scheduler",
                 drain_preempt_after_cycles: int | None = None,
                 drain_preempt_max_busy_fraction: float = 0.25,
                 drain_preempt_spare_progress: float = 0.75,
                 drain_preempt_progress_fn: Callable[
                     [Pod], float | None] | None = None,
                 preempt_budget_per_cycle: int = 2,
                 backfill_remaining_fn: Callable[
                     [Pod], float | None] | None = None,
                 backfill_duration_fn: Callable[
                     [Pod], float | None] | None = None,
                 elastic_grow_budget_per_cycle: int = 1,
                 displaced_age_cap_s: float = 300.0,
                 incremental: bool = True,
                 full_rescan_every: int = 512,
                 clock: Callable[[], float] = time.time,
                 hbm_gb_per_chip: float = 16.0) -> None:
        self._api = api
        self._framework = framework
        self.name = name
        # Schedule-latency clock: must share a time domain with pod
        # creation_timestamps (wall clock in production, the virtual
        # trace clock in sims/benches) — queue-admission→bind latency is
        # clock() - creation_timestamp.  Injectable per noslint N002.
        self._clock = clock
        # Drain preemption (opt-in): once a gang has held the window
        # lease this many scheduling cycles, the last stragglers on the
        # window (at most the given fraction of its chip capacity,
        # PDB-respecting, whole-gang amplified) are EVICTED so the drain
        # completes instead of waiting out their full durations.  The
        # honest cost lands on the victims: they requeue and re-run
        # (workloads checkpointing via cmd/train.py lose little).  None
        # disables (default — eviction of healthy pods is a policy choice
        # the operator must make).
        #
        # Victim selection is remaining-work-aware: stragglers are walked
        # least-progress-first, and any straggler whose reported progress
        # (ANNOT_JOB_PROGRESS, or `drain_preempt_progress_fn(pod)` when
        # injected — the simulator passes its job table; production jobs
        # annotate on checkpoint) has reached `drain_preempt_spare_progress`
        # is never evicted: a nearly-done job drains the window for free by
        # finishing, and evicting it wastes its whole run.
        self._drain_after = drain_preempt_after_cycles
        self._drain_fraction = drain_preempt_max_busy_fraction
        self._drain_spare_progress = drain_preempt_spare_progress
        self._progress_fn = (drain_preempt_progress_fn
                             or _annotation_progress)
        self._drain_cycles = 0
        self._drain_gang: tuple[str, str] | None = None
        # Preemption budget: at most this many PostFilter (preemption)
        # searches per scheduling cycle.  kube-scheduler pops one pod per
        # cycle, so it never runs more than one preemption between state
        # refreshes; this loop schedules EVERY pending pod per cycle, and
        # running a full victim search for each unschedulable pod both
        # multiplies the cycle cost ~10x at v5e-256 scale and lets
        # same-cycle preemptors fight over the same space.  Unserved pods
        # simply retry next cycle (one tick later).
        self._preempt_budget_per_cycle = preempt_budget_per_cycle
        self._preempt_budget = self._preempt_budget_per_cycle
        # Duration-aware backfill on the drain window (opt-in, both fns
        # required): a single may bind onto a reserved host ONLY if its
        # expected duration fits inside the window's drain ETA (max
        # remaining time of the stragglers already there) — short jobs
        # keep the draining window busy for free, anything longer would
        # push the stuck gang's bind out and is excluded outright.
        # `backfill_remaining_fn(pod)` estimates a RUNNING pod's
        # remaining seconds (None = unknown); `backfill_duration_fn(pod)`
        # a PENDING pod's total expected seconds (None = unknown, which
        # excludes it — don't gamble the window on an unbounded job).
        # Production sources these from duration/deadline annotations;
        # the simulator injects its job table.  Without the fns, the
        # score-key's soft avoidance (reserved hosts last) is unchanged.
        self._backfill_remaining_fn = backfill_remaining_fn
        self._backfill_duration_fn = backfill_duration_fn
        self._window_eta: float | None = None
        self._quota_hol: dict[str, int] = {}
        # The capacity plugin, if registered (fixed at construction):
        # quota HOL and gang evictability consult its ledger/calculator.
        self._capacity = next(
            (p for p in framework.plugins
             if hasattr(p, "elastic_quota_infos")), None)
        # Gang window lease: each cycle, the oldest stuck multi-host gang
        # reserves its currently most-drained candidate window (re-picked
        # every cycle — completions are stochastic, so tracking whichever
        # window is closest to empty beats pinning one; measured on the
        # v5e-256 trace).  Singles avoid the reserved hosts whenever any
        # alternative fits, and the lease is published on the nodes so
        # the partitioner drains the same window.
        self._lease: tuple[tuple[str, str], frozenset[str]] | None = None
        self._reserved_hosts: frozenset[str] = frozenset()
        self._lease_healed = False   # one startup sweep clears stale leases
        # Per-cycle snapshot + assume cache (kube-scheduler's snapshot
        # model): the cluster view is built once per cycle, pods bound
        # THIS cycle are assumed into it in place, and it is invalidated
        # after any eviction (preemption) so freed capacity is seen.
        # Rebuilding it for every pending pod dominated the cycle cost
        # at v5e-256 scale (one full deepcopy of the store per pod).
        self._cycle_lister_cache: SharedLister | None = None
        # Incremental cluster view (scheduler/cache.py): watch-driven
        # node/pod indexes with per-node generation invalidation, so
        # snapshot() rebuilds only the nodes events touched instead of
        # re-listing (and deep-copying) the whole store per cycle.
        # Substrates without a watch bus fall back to the full scan.
        self._cache = SchedulerCache(api) if hasattr(api, "watch") else None
        # Incremental decision plane (ISSUE 18): a clean cycle KEEPS the
        # previous cycle's snapshot and derived indexes (class scans,
        # filter memos, busy map) and applies only the watch-dirty node
        # set; every `full_rescan_every` cycles — or whenever the cache
        # level-triggers total invalidation — a full rescan re-levels
        # every index (the PR 1 level-triggered lesson).  incremental
        # =False recovers the per-cycle rebuild; nosdiff certifies the
        # decision journals byte-identical between the two modes.  The
        # default period (512) keeps the backstop's full re-scan below
        # 1% of cycles so it amortizes out of the steady-state p99 at
        # 16k hosts while still bounding how long a hypothetically
        # missed invalidation could linger.
        self._incremental = incremental and self._cache is not None
        self._full_rescan_every = max(1, full_rescan_every)
        self._cycles_since_rescan = 0
        # Per-cycle pod-equivalence Filter memo: node name -> equivalence
        # key -> (verdict, why).  Identical profile-requests skip
        # re-running the whole Filter pipeline per node; entries die with
        # the node's assume booking and with the cycle snapshot.
        self._filter_cache: dict[str, dict] = {}
        # Native batch fit screen (scheduler/native_filter.py): definite
        # NodeResourcesFit fails are memoised from ONE GIL-releasing C
        # call over all unseen nodes instead of a Python pipeline run
        # each.  message_exact required — the memo carries the exact
        # rejection strings the journal/explain output relies on.
        _screen = FitPrescreen(framework)
        self._prescreen = _screen if _screen.message_exact else None
        # chip-equivalent (cap, used) per node for the screen's
        # aggregate guard — cycle-scoped, dropped per node on assume
        self._chips_cache: dict[str, tuple[int, int]] = {}
        # equivalence classes already screened this cycle: later pods of
        # the class skip even the is-anything-unseen scan (an assumed
        # node's dropped memo entry just falls back to the pipeline)
        self._screened_classes: set = set()
        # Per-class scan cache — the persistent cross-cycle feasibility
        # index: [feasible NodeInfos by name, per-node rejections,
        # memoised rejection attrs, stale node names] for one
        # equivalence class.  The per-pod x node loop is the fleet's
        # steady-state cycle cost, and every pod of a class sees the
        # identical verdict set — so the fleet pays one scan per class
        # per state, not per pod.  When node state moves (assume,
        # preemption, watch-dirty nodes) the touched nodes are marked
        # stale in every index and re-screened lazily on the index's
        # next use (_refresh_scan) — O(dirty), never a full rebuild;
        # incremental mode carries the indexes across cycles, full mode
        # drops them with the cycle snapshot.  Disabled while
        # duration-aware backfill is on (its verdicts are per-pod, not
        # per-class).
        self._class_scan_cache: dict[tuple, Any] = {}
        # Per-cycle window-busy map for _score_key's fragmentation
        # penalty: building it per scoring decision was O(pods x nodes)
        # per cycle at fleet scale.  Lives and dies with the cycle
        # snapshot; assume() marks the bound host busy in place.
        self._busy_map_cache: dict[tuple[str, int], bool] | None = None
        # Marshalled (sorted-key) form of the busy map for the native
        # score argmin (device/native.nos_score_batch) — derived from
        # the busy dict, keyed on its identity, and dropped whenever
        # _mark_busy mutates it in place.
        self._busy_arrays_cache: tuple | None = None
        # True while run_cycle drives the entry points: the cycle
        # snapshot is shared across its pods.  Direct schedule_one/
        # schedule_gang calls (public entry points) drop it on exit so
        # external mutations between calls are seen (ADVICE round 5).
        self._in_cycle = False
        # Chip-second waste attribution (obs/ledger.py): the cycle's
        # OWN rejection verdicts, collected as they are made, feed the
        # cycle-end waterfall — frag_stranded is derived from what the
        # Filter pipeline actually said, never from a re-scan.
        # per-class rejection maps from this cycle's no-fit verdicts (a
        # node some class FIT binds the pod and never lands here).
        # Identity-deduplicated references into the class scan cache's
        # own rejection dicts: noting a class is O(1), and on clean
        # incremental cycles the SAME dict objects recur — the waste
        # skeleton memo keys on that to skip the O(nodes) waterfall.
        self._waste_rejection_maps: list[dict[str, str]] = []
        # cycle-end waterfall skeleton memo: (key, rejection maps,
        # per-pool template) — valid while the view epoch, holds,
        # budgets and rejection maps all stand still (see _observe_waste)
        self._waste_skel: tuple | None = None
        # pending class -> rejection node-count (frag culprit evidence)
        self._waste_frag_counts: dict[str, int] = {}
        # pending class -> frag-blocked chip demand this cycle, and the
        # persistent per-class stranded chip-second integral the frag
        # culprit ranking keys on: when several classes strand the same
        # pool, the one that has waited with the most blocked chips the
        # longest is the culprit — NOT whichever rejection is newest.
        self._waste_frag_chips: dict[str, float] = {}
        self._frag_class_chip_seconds: dict[str, float] = {}
        self._last_waste_t: float | None = None
        # Elastic grow pass (scheduler/elastic.py): at most this many
        # replica clones created per cycle across all dp-elastic gangs.
        # Gated entirely on the workloads' own annotations — a cluster
        # with no elastic gangs sees identical decisions at any budget.
        self._elastic_grow_budget = elastic_grow_budget_per_cycle
        # pending class -> chip demand blocked by quota (PreFilter
        # quota rejections + head-of-line deferrals); Σ bounds the
        # quota_stranded bucket — stranding cannot exceed the demand
        self._waste_quota_blocked: dict[str, float] = {}
        # stuck gang -> its members' chip demand; Σ bounds the
        # gang_wait attributed OUTSIDE the leased window
        self._waste_pending_gangs: dict[str, float] = {}
        # hosts whose free chips were bought by drain preemption this
        # lease period (DRAIN holds, cleared when the lease resolves)
        self._drain_hold_hosts: frozenset[str] = frozenset()
        # timeshare-GB -> chips conversion for productive accounting
        self._hbm_gb_per_chip = hbm_gb_per_chip
        # Displaced head-of-line (docs/scheduler.md): a pod stamped
        # ANNOT_DISPLACED ranks in its own admission tier between
        # serving and batch until the stamp is older than this cap —
        # the anti-starvation bound that stops an unplaceable displaced
        # pod from camping the head of the queue (<= 0: no expiry).
        self._displaced_age_cap_s = displaced_age_cap_s
        # displaced kill causes for the cycle's waste evidence:
        # stranding class / stuck gang -> its displacement cause, so
        # the frag/gang_wait culprit join can name the node-loss victim
        self._waste_displaced: dict[str, str] = {}

    def close(self) -> None:
        """Detach the incremental cache's watch subscriptions.  A
        replaced Scheduler on a long-lived APIServer must not keep
        paying two synchronous callbacks (plus per-watcher deep copies)
        on every write, nor be kept alive by the watcher list."""
        if self._cache is not None:
            self._cache.close()

    # -- cluster view -------------------------------------------------------
    def snapshot(self) -> SharedLister:
        if self._cache is not None:
            return self._cache.snapshot()
        # full-scan fallback for substrates without a watch bus
        infos: dict[str, NodeInfo] = {}
        for node in self._api.list(KIND_NODE):
            infos[node.metadata.name] = NodeInfo(node=node)
        for pod in self._api.list(KIND_POD):
            if pod.spec.node_name and pod.spec.node_name in infos \
                    and pod.status.phase in (PENDING, RUNNING):
                infos[pod.spec.node_name].add_pod(pod)
        return SharedLister(infos.values())

    # -- one scheduling cycle ----------------------------------------------
    def _cycle_lister(self) -> SharedLister:
        if self._cycle_lister_cache is None:
            self._cycle_lister_cache = self.snapshot()
            self._filter_cache = {}
            self._chips_cache = {}
            self._screened_classes = set()
            self._class_scan_cache = {}
            self._busy_map_cache = None
            self._busy_arrays_cache = None
        return self._cycle_lister_cache

    def _begin_cycle_view(self) -> None:
        """Install the cycle's cluster view.  Incremental mode drains
        the watch-dirty node set and applies it to the PERSISTENT
        snapshot and derived indexes — a clean cycle touches nothing,
        a dirty one re-screens exactly the dirtied nodes.  Every
        `full_rescan_every` cycles, or when the cache level-triggers
        (`drain_dirty()` returning None), everything is dropped and
        rebuilt from scratch — the correctness backstop.  Full mode
        (incremental off) takes the drop-everything path each call,
        recovering the per-cycle rebuild exactly."""
        if not self._incremental:
            self._drop_cycle_snapshot()
            return
        assert self._cache is not None
        self._cycles_since_rescan += 1
        dirty = self._cache.drain_dirty()
        if dirty is None \
                or self._cycles_since_rescan >= self._full_rescan_every:
            self._cycles_since_rescan = 0
            if dirty is not None:
                # periodic backstop: level-trigger the cache's own
                # views too, then swallow the resulting None drain
                self._cache.invalidate_all()
                self._cache.drain_dirty()
            obs_bump("sched_full_rescans")
            self._drop_cycle_snapshot()
            return
        if dirty:
            # busy map FIRST: applying dirt routes through _mark_busy,
            # but a dirtied node may have just become EMPTY — the map
            # is rebuilt lazily from the fresh view instead of being
            # patched pessimistically busy
            self._busy_map_cache = None
            self._busy_arrays_cache = None
            for name in sorted(dirty):
                self._invalidate_scans(name)
            # the native prescreen memo-seeds per class; dirtied nodes
            # are unseen again, so the per-cycle screened set resets
            self._screened_classes = set()
        self._cycle_lister_cache = self.snapshot()

    def schedule_one(self, pod: Pod) -> str | None:
        """Try to place one pod; returns the node name or None."""
        if not self._in_cycle:
            self._begin_cycle_view()
        try:
            return self._schedule_one(pod)
        finally:
            if not self._in_cycle and not self._incremental:
                self._drop_cycle_snapshot()

    def _drop_cycle_snapshot(self) -> None:
        """Full-rebuild hygiene: drop the snapshot and every derived
        index, so the next `_cycle_lister()` rebuilds from live state.
        Full (non-incremental) mode runs this per cycle and per public
        entry-point call — external mutations between calls must be
        seen (ADVICE round 5); incremental mode runs it only on the
        full-rescan backstop, trusting the watch-dirty set otherwise."""
        self._cycle_lister_cache = None
        self._filter_cache = {}
        self._busy_map_cache = None
        self._busy_arrays_cache = None
        self._chips_cache = {}
        self._screened_classes = set()
        self._class_scan_cache = {}

    def _seed_filter_memo_native(self, pod: Pod, equiv: tuple,
                                 lister: SharedLister) -> None:
        """Seed the per-cycle Filter memo with the native batch screen's
        definite fails for this pod's equivalence class (superset
        contract: native fail => the pipeline fails with exactly the
        memoised message — see native_filter.py).  Native passes decide
        nothing; those nodes still run the real pipeline."""
        # Snapshot the screen ONCE: self._prescreen can be dropped at
        # runtime (the shim-less latch below, a test, or an operator
        # toggle) between the caller's None check and the dereference —
        # the old assert turned that benign disable into a crashed
        # cycle.  The local keeps this call self-consistent; the next
        # call sees the latch and falls back to the pure pipeline.
        prescreen = self._prescreen
        if prescreen is None or equiv in self._screened_classes:
            return
        from nos_tpu.device import native
        if not native.fit_batch_available(build=False):
            # shim-less deployment: latch the screen OFF before paying
            # any per-node marshalling — the pure-Python pipeline path
            # must not get slower for lack of a .so (decided once, at
            # the first scheduling cycle)
            self._prescreen = None
            return
        self._screened_classes.add(equiv)
        unseen = [ni for ni in lister.list()
                  if equiv not in self._filter_cache.get(ni.name, ())]
        if not unseen:
            return
        req = pod_request(pod)
        msgs = prescreen.screen_nodes(unseen, req, _slice_chips(req),
                                      chip_cache=self._chips_cache)
        if msgs is None:
            return
        seeded = 0
        for ni, why in zip(unseen, msgs):
            if why is not None:
                self._filter_cache.setdefault(ni.name, {})[equiv] = \
                    (False, why)
                seeded += 1
        if seeded:
            obs_bump("prescreen_fails", seeded)

    def _preempt_then_retry(self, state: CycleState, pod: Pod,
                            lister: SharedLister) -> tuple[bool, str | None]:
        """PostFilter, then — on success — ONE immediate re-placement
        attempt.  On the in-memory substrate evictions are synchronous
        deletes, so the victims' capacity is genuinely free right now;
        without the retry the preemptor leaves the cycle merely
        *nominated* and lower-tier pods later in the SAME cycle bind
        into the space it just cleared (the PodNominator race — a
        serving replica could preempt every cycle forever while batch
        fillers ate each freed unit).  Against a real apiserver victims
        terminate gracefully, the retry finds no fit, and behavior
        falls back to plain nomination.  Returns (handled, node):
        handled=False means no preemption happened and the caller
        proceeds to its unschedulable path."""
        nominated, post = self._post_filter_budgeted(state, pod, lister)
        if not (post.is_success and nominated):
            return False, None
        placed = self._schedule_one(pod, allow_preempt=False)
        if placed is None:
            self._nominate(pod, nominated)
        return True, placed

    def _schedule_one(self, pod: Pod,
                      allow_preempt: bool = True) -> str | None:
        if allow_preempt:
            # the post-preemption retry is the SAME scheduling attempt:
            # it must not double the trace counter or re-journal
            obs_bump("schedule_one")
        lister = self._cycle_lister()
        state = CycleState()
        status = self._framework.run_pre_filter_plugins(state, pod, lister)
        if not status.is_success:
            if not allow_preempt:
                return None     # post-preemption retry: caller nominates
            if status.reason == "quota":
                self._record_quota_hol(pod)
                self._note_quota_blocked(pod)
            # An unschedulable PreFilter verdict still gets a preemption
            # attempt, exactly like kube-scheduler: quota rejections are
            # resolved by evicting over-quota borrowers (reference
            # capacity_scheduling.go:323-341).
            if status.code == UNSCHEDULABLE:
                handled, placed = self._preempt_then_retry(
                    state, pod, lister)
                if handled:
                    return placed
            self._mark_unschedulable(pod, status)
            return None
        equiv = self._filter_equiv_key(pod)
        if equiv is not None and self._prescreen is not None:
            self._seed_filter_memo_native(pod, equiv, lister)
        # Per-class scan cache: every pod of one equivalence class sees
        # the identical per-node verdicts against unchanged state, so
        # the fleet-wide loop runs once per class, not once per pod.
        # Duration-aware backfill makes verdicts per-pod: bypass then.
        cacheable = equiv is not None and (
            self._backfill_duration_fn is None
            or not self._reserved_hosts)
        scan = self._class_scan_cache.get(equiv) if cacheable else None
        if scan is None:
            feasible: dict[str, NodeInfo] = {}
            rejections: dict[str, str] = {}
            for ni in lister.list():
                # ni.name is a two-hop property and this loop runs per
                # pod x node over the whole fleet: read it once
                name = ni.name
                if not self._backfill_allows(pod, ni, name):
                    rejections[name] = \
                        "Backfill: job would outlive the drain window"
                    continue
                ok, why = self._filter_passes(state, pod, ni, equiv, name)
                if ok:
                    feasible[name] = ni
                else:
                    rejections[name] = why
            scan = [feasible, rejections, None, set()]
            if cacheable:
                self._class_scan_cache[equiv] = scan
        elif scan[3]:
            # persistent index with stale members: re-screen exactly
            # the nodes whose state moved since the verdicts were cut
            self._refresh_scan(scan, state, pod, equiv, lister)
        feasible, rejections = scan[0], scan[1]
        if not feasible:
            if not allow_preempt:
                return None     # post-preemption retry: caller nominates
            handled, placed = self._preempt_then_retry(state, pod, lister)
            if handled:
                return placed
            if scan[2] is None:
                scan[2] = self._node_reason_attrs(rejections)
            self._note_no_fit(pod, rejections)
            self._mark_unschedulable(
                pod, Status.unschedulable("no fit"),
                node_attrs=scan[2])
            return None
        chosen = self._choose_node(pod, feasible, lister)
        status = self._framework.run_reserve_plugins(state, pod, chosen.name)
        if not status.is_success:
            self._framework.run_unreserve_plugins(state, pod, chosen.name)
            self._mark_unschedulable(pod, status)
            return None
        if not self._bind(pod, chosen.name):
            # The pod vanished mid-cycle: nothing was placed, and the
            # assume would poison the incremental cache with phantom
            # capacity (no write happened, so no event invalidates it).
            # Roll back the reservation: the ledger booked this pod
            # AFTER its DELETED event fired, so nothing else ever will.
            self._framework.run_unreserve_plugins(state, pod, chosen.name)
            return None
        self._assume_bound(pod, chosen.name)
        self._observe_schedule_latency([pod])
        self._observe_rebind([pod])
        return chosen.name

    def _filter_equiv_key(self, pod: Pod) -> tuple | None:
        """Per-cycle Filter equivalence class (the shared
        framework.filter_equivalence_key).  Gang members are never
        cached here: pins in cycle state change the TopologyFilter
        verdict, and they go through schedule_gang's cloned domains
        anyway.  None disables caching for this pod."""
        if gang_name(pod):
            return None
        return filter_equivalence_key(pod)

    def _filter_passes(self, state: CycleState, pod: Pod, ni: NodeInfo,
                       equiv: tuple | None,
                       name: str | None = None) -> tuple[bool, str]:
        """(verdict, why): why is "plugin: message" on rejection, "" on
        success — the journal's per-node provenance, carried through the
        memo so cache hits keep their reason.  `name` lets fleet-scale
        loops pass the already-read node name (ni.name is a two-hop
        property)."""
        if equiv is None:
            return self._filter_verdict(state, pod, ni)
        per_node = self._filter_cache.setdefault(
            name if name is not None else ni.name, {})
        verdict = per_node.get(equiv)
        if verdict is None:
            verdict = self._filter_verdict(state, pod, ni)
            per_node[equiv] = verdict
        return verdict

    def _filter_verdict(self, state: CycleState, pod: Pod,
                        ni: NodeInfo) -> tuple[bool, str]:
        st = self._framework.run_filter_plugins(state, pod, ni)
        if st.is_success:
            return True, ""
        return False, f"{st.plugin or 'Filter'}: {st.message}"

    def _assume_bound(self, pod: Pod, node_name: str) -> None:
        """Book a just-bound pod into the cycle snapshot so later pods
        this cycle see its capacity consumed (the assume cache)."""
        assumed = fast_deepcopy(pod)
        assumed.spec.node_name = node_name
        if self._cache is not None:
            # also book into the incremental cache: on an async watch
            # substrate the bind's pod event can lag a node event whose
            # rebuild would otherwise resurrect the pre-bind view
            self._cache.assume(assumed)
        lister = self._cycle_lister_cache
        if lister is not None:
            ni = lister.get(node_name)
            if ni is not None:
                ni.add_pod(assumed)
        self._invalidate_scans(node_name)

    def _refresh_scan(self, scan: list, state: CycleState, pod: Pod,
                      equiv: tuple, lister: SharedLister) -> None:
        """Bring a persistent class index up to date by re-screening
        ONLY its stale nodes (marked by _invalidate_scans) against the
        current view — O(dirty), never a fleet rescan.  Verdicts for
        untouched nodes are carried verbatim (their _filter_cache memos
        would replay the identical (verdict, why) anyway), so the index
        is byte-equal to a from-scratch scan; nodes that left the fleet
        simply drop out.  The memoised rejection attrs die with any
        refresh — they summarise the rejection map's content."""
        feasible, rejections, stale = scan[0], scan[1], scan[3]
        for name in sorted(stale):
            feasible.pop(name, None)
            rejections.pop(name, None)
            ni = lister.get(name)
            if ni is None:
                continue        # node left the fleet
            if not self._backfill_allows(pod, ni, name):
                rejections[name] = \
                    "Backfill: job would outlive the drain window"
                continue
            ok, why = self._filter_passes(state, pod, ni, equiv, name)
            if ok:
                feasible[name] = ni
            else:
                rejections[name] = why
        stale.clear()
        scan[2] = None

    def _invalidate_scans(self, node_name: str) -> None:
        """The declared invalidation event (@invalidated_by) for the
        derived decision caches: the node's capacity changed, so its
        memoised Filter verdicts die, it goes stale in every class's
        persistent feasibility index (re-screened lazily on the index's
        next use — never a full rebuild), and the window-busy map entry
        flips busy."""
        self._filter_cache.pop(node_name, None)
        self._chips_cache.pop(node_name, None)
        for scan in self._class_scan_cache.values():
            scan[3].add(node_name)
        self._mark_busy(node_name)

    @staticmethod
    def _window_key(labels: dict) -> tuple[str, int] | None:
        """(pod-id, host-index) of a node's labels, or None when it has
        no pod-id / an unparsable index — ONE parsing for the busy-map
        builder, the in-place busy marker, and the score penalty, so
        they can never disagree on the key encoding."""
        pid = labels.get(C_LABEL_POD_ID, "")
        if not pid:
            return None
        try:
            return pid, int(labels.get(C_LABEL_HOST_INDEX, "0"))
        except ValueError:
            return None

    def _mark_busy(self, node_name: str) -> None:
        """Keep the cycle's window-busy map truthful after a bind: the
        host now has a pod, so whole-free-window penalties involving it
        must stop firing this cycle."""
        if self._busy_map_cache is None or self._cycle_lister_cache is None:
            return
        ni = self._cycle_lister_cache.get(node_name)
        if ni is None:
            return
        key = self._window_key(ni.node.metadata.labels)
        if key is not None:
            self._busy_map_cache[key] = True
            # the marshalled (sorted) form is derived from the dict's
            # content but keyed on its identity: an in-place flip must
            # drop it explicitly
            self._busy_arrays_cache = None

    def run_cycle(self) -> int:
        """Schedule all pending, not-yet-bound pods for this scheduler;
        returns number of pods bound.  Pods sharing a `nos.tpu/pod-group`
        label are admitted all-or-nothing (gang scheduling)."""
        self._in_cycle = True
        try:
            with obs_span("scheduler.run_cycle") as sp:
                bound = self._run_cycle()
                if sp is not None:
                    sp.set("bound", bound)
                return bound
        finally:
            self._in_cycle = False

    def _run_cycle(self) -> int:
        bound = 0
        self._preempt_budget = self._preempt_budget_per_cycle
        self._window_eta = None     # re-estimated per cycle
        self._quota_hol: dict[str, int] = {}
        # install the cycle's cluster view: incremental mode carries
        # the previous view + indexes and applies the watch-dirty set;
        # full mode drops everything for a per-cycle rebuild
        self._begin_cycle_view()
        self._waste_rejection_maps = []
        self._waste_frag_counts = {}
        self._waste_frag_chips = {}
        self._waste_quota_blocked = {}
        self._waste_pending_gangs = {}
        self._waste_displaced = {}
        pods = [
            p for p in self._pending_pods()
            if p.spec.scheduler_name == self.name
        ]
        # Tiered admission queue (docs/serving.md + docs/scheduler.md):
        # serving pods are picked FIRST every cycle — before any batch
        # gang, whatever its PriorityClass — then DISPLACED victims of
        # node loss / drain migration (their own tier, with an
        # anti-starvation age cap), then batch, then best-effort;
        # priority and FIFO order break ties within a tier.  This is
        # also what routes the per-cycle preemption budget under
        # contention: serving spends it first, displaced rebinds next.
        now = self._clock()
        cap = self._displaced_age_cap_s
        pods.sort(key=lambda p: (admission_rank(p, now, cap),
                                 -p.spec.priority,
                                 p.metadata.creation_timestamp, p.key))
        # Release the window lease once its gang is no longer waiting;
        # a still-stuck gang re-earns (and may move) it this cycle.
        pending_gangs = {(p.metadata.namespace, gang_name(p))
                         for p in pods if gang_name(p)}
        if self._lease is not None and self._lease[0] not in pending_gangs:
            self._lease = None
            self._sync_lease_annotations(frozenset())
            self._clear_drain_holds()
        elif not self._lease_healed and self._lease is None:
            # Startup: a predecessor may have died holding a lease whose
            # annotations would otherwise skew partitioning forever.
            self._sync_lease_annotations(frozenset())
        self._lease_healed = True
        self._reserved_hosts = (self._lease[1] if self._lease is not None
                                else frozenset())
        self._window_eta = None     # follows _reserved_hosts, always
        self._maybe_drain_preempt()
        gangs: dict[tuple[str, str], list[Pod]] = {}
        for pod in pods:
            g = gang_name(pod)
            if g:
                gangs.setdefault((pod.metadata.namespace, g), []).append(pod)
        seen_gangs: set[tuple[str, str]] = set()
        for pod in pods:
            if self._quota_hol_defers(pod):
                continue
            g = gang_name(pod)
            if not g:
                if self.schedule_one(pod) is not None:
                    bound += 1
                continue
            key = (pod.metadata.namespace, g)
            if key not in seen_gangs:
                seen_gangs.add(key)
                bound += self.schedule_gang(gangs[key])
        pending_counts = self._publish_pending_gauges()
        # waste waterfall BEFORE the snapshot drops: attribution reads
        # the post-bind cycle view plus this cycle's rejection verdicts
        self._observe_waste(pending_counts)
        # elastic grow pass LAST: clones created here are next cycle's
        # demand and must not perturb this cycle's waste attribution
        # or pending gauges (scheduler/elastic.py).  Gated on the
        # watch-maintained gang index when available: maybe_grow scans
        # the whole pod store but can only ever act on pod-group
        # labeled (elastic) gangs, so a gang-free fleet skips the scan
        # outright — the same decisions, none of the O(pods) walk.
        if self._elastic_grow_budget > 0 and (
                self._cache is None or self._cache.has_gang_pods()):
            from nos_tpu.scheduler.elastic import maybe_grow

            maybe_grow(self._api, self._framework, self._cycle_lister(),
                       budget=self._elastic_grow_budget,
                       clock=self._clock)
        if not self._incremental:
            # full mode drops the cycle snapshot on exit so direct
            # entry-point calls see fresh state (they rebuild lazily);
            # incremental mode KEEPS the view — entry points re-level
            # it through _begin_cycle_view's dirty drain instead
            self._cycle_lister_cache = None
            self._busy_map_cache = None
            self._busy_arrays_cache = None
        return bound

    # -- quota head-of-line -------------------------------------------------
    # A quota-rejected pod is waiting for LEDGER headroom in its
    # namespace's share; once it is rejected this cycle, lower-priority
    # pods of the same namespace must not bind into the headroom that
    # frees up (first-come ledger allocation would starve a big gang
    # forever: every chunk of freed quota is eaten by a small single
    # before the gang's full requirement accumulates — the ledger-level
    # twin of the physical window-lease problem).  Scope is one cycle;
    # the rejection re-records each cycle while the claimant waits.

    def _record_quota_hol(self, pod: Pod,
                          total_request: dict | None = None) -> None:
        ns = pod.metadata.namespace
        # Unsatisfiability guard: a claimant whose request ALONE can
        # never pass the quota gates — it exceeds its namespace max, or
        # the cluster's aggregated min (rejected even at zero usage) —
        # will never schedule no matter what is evicted, so letting it
        # hold the head-of-line would starve the whole namespace until
        # someone deletes it.  Such a claimant records nothing.
        cap = self._capacity
        if cap is not None:
            req = total_request if total_request is not None \
                else cap.calculator.compute_pod_request(pod)
            info = cap.elastic_quota_infos.get(ns)
            over_own_max = (info is not None and info.max_enforced
                            and any(req.get(r, 0.0) > limit
                                    for r, limit in info.max.items()))
            agg_min = cap.elastic_quota_infos.aggregated_min()
            over_agg_min = any(req.get(r, 0.0) > limit
                               for r, limit in agg_min.items())
            if over_own_max or over_agg_min:
                logger.warning(
                    "quota HOL: claimant %s requests more than %s can "
                    "ever grant (namespace max or aggregated min) — "
                    "never schedulable, not blocking the namespace",
                    pod.key, ns)
                return
        self._quota_hol[ns] = max(self._quota_hol.get(ns, 0),
                                  pod.spec.priority)
        journal_record(J.QUOTA_HOL_CLAIM, pod.key, namespace=ns,
                       priority=pod.spec.priority)

    def _quota_hol_defers(self, pod: Pod) -> bool:
        blocker = self._quota_hol.get(pod.metadata.namespace)
        if blocker is None or pod.spec.priority >= blocker:
            return False
        if workload_tier(pod) == C_TIER_SERVING:
            # The serving tier never queues behind a batch gang's ledger
            # claim: its latency SLO is milliseconds, the claimant's
            # wait is minutes.  A serving pod that genuinely lacks
            # headroom is rejected by PreFilter itself; the HOL rule
            # exists to stop SMALL BATCH pods from eating a gang's
            # accumulating quota, not to starve the protected tier.
            return False
        self._note_quota_blocked(pod)
        self._mark_unschedulable(pod, Status.unschedulable(
            f"waiting behind a higher-priority quota claim in namespace "
            f"{pod.metadata.namespace}", reason="quota-hol"))
        return True

    def _pending_pods(self) -> list[Pod]:
        """The unbound PENDING pods — from the incremental cache's
        watch-maintained index when one exists (no store scan, no deep
        copies), the API's phase listing otherwise.  Callers filter on
        scheduler_name themselves and treat the pods as read-only;
        every downstream ordering re-sorts on a strict total key, so
        the two sources' iteration orders are interchangeable."""
        if self._cache is not None:
            return self._cache.pending_pods()
        return [p for p in self._api.pods_by_phase(PENDING)
                if not p.spec.node_name]

    def schedule_gang(self, members: list[Pod]) -> int:
        """All-or-nothing placement of a pod group: simulate every member
        on a shared snapshot (each consumes capacity the next one sees,
        and the first placement pins the gang's physical TPU pod); bind
        only if all fit, else mark all unschedulable so the partitioner
        sees the gang's full demand."""
        if not self._in_cycle:
            self._begin_cycle_view()
        try:
            with obs_span("scheduler.schedule_gang",
                          gang=f"{members[0].metadata.namespace}"
                               f"/{gang_name(members[0])}",
                          members=len(members)):
                return self._schedule_gang(members)
        finally:
            if not self._in_cycle and not self._incremental:
                self._drop_cycle_snapshot()

    def _gang_journal(self, members: list[Pod], admitted: bool,
                      message: str, bound: int = 0) -> None:
        first = members[0]
        subject = f"{first.metadata.namespace}/{gang_name(first)}"
        journal_record(
            J.GANG_ADMITTED if admitted else J.GANG_REJECTED, subject,
            message=message, bound=bound,
            members=[p.key for p in members[:MAX_JOURNAL_NODES]],
            members_total=len(members))

    def _schedule_gang(self, members: list[Pod]) -> int:
        first = members[0]
        gang = gang_name(first)
        pg = get_pod_group(self._api, gang, first.metadata.namespace)
        min_member = pg.spec.min_member if pg else len(members)
        # Count every live member — already-running mates count toward the
        # gang, so a recreated worker of a partially-running gang schedules
        # instead of deadlocking on "waiting for members".
        alive = len(self._api.list(
            KIND_POD, namespace=first.metadata.namespace,
            label_selector={C_LABEL_POD_GROUP: gang},
            filter_fn=lambda p: p.status.phase in (PENDING, RUNNING)))
        if alive < min_member:
            self._note_stuck_gang(members)
            self._gang_journal(
                members, False,
                f"pod group waiting for members ({alive}/{min_member})")
            for pod in members:
                self._mark_unschedulable(pod, Status.unschedulable(
                    f"pod group waiting for members "
                    f"({alive}/{min_member})"))
            return 0

        # Candidates: for a gang consuming one multi-host slice, the
        # aligned host windows matching the partitioner's shard layout;
        # otherwise whole ICI domains, best-fit first (least free capacity
        # that still might hold the gang — keeps large pods free for large
        # gangs).  "" = hosts with no pod-id label.
        windows = gang_slice_windows(self._api, members)
        base = self._cycle_lister()
        if windows:
            # hosts=None: a sub-host-generation domain — pin the pod id
            # only (gang_slice_windows' per-generation classification).
            # Ordered so windows that avoid the drain lease are tried
            # before ones that would refill it (resetting a stuck bigger
            # gang's drain clock).
            candidate_pins = [
                {GANG_POD_ID_KEY: pid, GANG_HOST_SET_KEY: hosts}
                if hosts is not None else {GANG_POD_ID_KEY: pid}
                for pid, hosts in self._order_gang_windows(windows)
            ]
        else:
            free_by_pod: dict[str, float] = {}
            for ni in base.list():
                pid = ni.node.metadata.labels.get(C_LABEL_POD_ID, "")
                free_by_pod[pid] = free_by_pod.get(pid, 0.0) + max(
                    0.0, ni.free().get(RESOURCE_TPU, 0.0))
            # Pin even the "" candidate: a gang trying unlabeled hosts must
            # use ONLY unlabeled hosts, never span labeled ICI domains.
            candidate_pins = [
                {GANG_POD_ID_KEY: pid}
                for pid in sorted(free_by_pod,
                                  key=lambda p: (free_by_pod[p], p))
            ]

        placements: list[tuple[Pod, NodeInfo]] = []
        state = CycleState()
        for pins in candidate_pins:
            placements, state, _, _ = self._attempt_gang(pins, base, members)
            if len(placements) == len(members):
                break

        if len(placements) != len(members):
            # A gang claiming its guaranteed quota min must not starve
            # behind over-quota borrowers: give it the same preemption
            # attempt single pods get (schedule_one's PostFilter path).
            # The feasibility gate picks the candidate domain where the
            # gang COULD fit once evictable pods are gone; the attempt is
            # re-run there so PostFilter serves the stuck member with its
            # gang-mates' bookings in cycle state — victim selection sees
            # the whole gang's claim on the domain where eviction actually
            # helps.  Victims are evicted whole-gang (evict_gang); the
            # gang binds on a later cycle once the space exists.
            preempted = False
            if self._preempt_budget > 0:
                feasible_pins = self._gang_feasible_after_evictions(
                    members, candidate_pins, base)
            else:
                feasible_pins = None        # budget spent: retry next cycle
            if feasible_pins is not None:
                _, st, domain, stuck = self._attempt_gang(
                    feasible_pins, base, members)
                if stuck is not None:
                    nominated, post = self._post_filter_budgeted(
                        st, stuck, SharedLister(domain))
                    # Deliberately NOT nominating: a nominated pod stops
                    # matching extra_resources_could_help_scheduling,
                    # which would hide this member from the partitioner
                    # and split the gang's demand.  The evictions are the
                    # useful effect.
                    preempted = post.is_success and bool(nominated)
            msg = "gang does not fit as a whole"
            if preempted:
                msg += " (evicted over-quota victims, retrying)"
            self._note_stuck_gang(members)
            self._gang_journal(members, False, msg)
            self._reserve_gang_window(
                (first.metadata.namespace, gang), windows, base)
            for pod in members:
                self._mark_unschedulable(pod, Status.unschedulable(msg))
            return 0
        for pod, ni in placements:
            st = self._framework.run_reserve_plugins(state, pod, ni.name)
            if not st.is_success:
                # roll back the whole gang
                for p2, n2 in placements:
                    self._framework.run_unreserve_plugins(state, p2, n2.name)
                self._gang_journal(
                    members, False,
                    f"reserve failed for {pod.key}: {st.message}")
                for p2 in members:
                    self._mark_unschedulable(p2, st)
                return 0
        bound_members = 0
        for pod, ni in placements:
            if self._bind(pod, ni.name):
                self._assume_bound(pod, ni.name)
                bound_members += 1
            else:
                # vanished member: un-book its reservation (its DELETED
                # event fired before reserve booked it — see schedule_one)
                self._framework.run_unreserve_plugins(state, pod, ni.name)
        if pg is not None:
            # `alive` counts running mates plus the members just bound —
            # the true scheduled size, not just this cycle's batch;
            # members that vanished mid-cycle bound nothing and are not
            # reported (a deleted pod already dropped out of `alive`'s
            # next listing)
            set_pod_group_status(
                self._api, pg, "Scheduled",
                alive - (len(placements) - bound_members))
        if bound_members == len(members):
            # gang latency = last member bound, measured from the
            # EARLIEST admission (the gang waited as one unit)
            self._observe_schedule_latency(members)
            self._observe_rebind(members)
        self._gang_journal(members, True, "gang admitted",
                           bound=bound_members)
        logger.info("gang %s: bound %d pods",
                    gang_name(first), bound_members)
        return bound_members

    def _backfill_allows(self, pod: Pod, ni: NodeInfo,
                         name: str | None = None) -> bool:
        """Duration-aware drain-window backfill (__init__); True outside
        the reserved window or when the feature is off."""
        if (name if name is not None else ni.name) \
                not in self._reserved_hosts \
                or self._backfill_duration_fn is None \
                or self._backfill_remaining_fn is None:
            return True
        duration = self._backfill_duration_fn(pod)
        if duration is None:
            return False        # unbounded job: never gamble the window
        return duration <= self._window_drain_eta()

    def _window_drain_eta(self) -> float:
        """Max estimated remaining seconds among pods running on the
        reserved window (cached per cycle).  Unknown remaining => +inf:
        the window will not drain on its own soon anyway (drain
        preemption is the lever there), so backfill costs nothing."""
        if self._window_eta is not None:
            return self._window_eta
        eta = 0.0
        for p in self._api.list(KIND_POD):
            if p.spec.node_name in self._reserved_hosts \
                    and p.status.phase in (PENDING, RUNNING):
                rem = self._backfill_remaining_fn(p)
                if rem is None:
                    eta = float("inf")
                    break
                eta = max(eta, rem)
        self._window_eta = eta
        return eta

    def _post_filter_budgeted(self, state: CycleState, pod: Pod,
                              lister: SharedLister) -> tuple[str, Status]:
        """PostFilter under the per-cycle preemption budget (__init__):
        once spent, further unschedulable pods just wait for next cycle."""
        if self._preempt_budget <= 0:
            return "", Status.unschedulable(
                "preemption budget for this cycle spent")
        self._preempt_budget -= 1
        # The restart-cost victim walk judges "displaced" with the
        # admission queue's freshness rule (pod_util.is_displaced_fresh)
        # — hand it the same clock + age cap the queue sort used.
        from nos_tpu.scheduler.capacityscheduling import (
            DISPLACED_CONTEXT_KEY, VIEW_EPOCH_CONTEXT_KEY,
        )

        state[DISPLACED_CONTEXT_KEY] = (
            self._clock(), self._displaced_age_cap_s)
        if self._cache is not None \
                and lister is self._cycle_lister_cache:
            # the fleet-wide view epoch certifies the lister's state to
            # the victim prescreen's cross-cycle mask cache; gang
            # what-if domains pass a cloned sub-lister the epoch says
            # nothing about, so they get no key (and no mask reuse)
            state[VIEW_EPOCH_CONTEXT_KEY] = self._cache.view_epoch()
        nominated, status = self._framework.run_post_filter_plugins(
            state, pod, lister)
        if status.is_success:
            # victims were evicted: the cycle snapshot is stale
            self._cycle_lister_cache = None
            self._busy_map_cache = None
            self._busy_arrays_cache = None
        return nominated, status

    def _maybe_drain_preempt(self) -> None:
        """Evict the last stragglers off a long-held drain window (see
        __init__).  Runs once per lease period: after an eviction the
        counter goes into cooldown so surviving (PDB-protected) pods are
        not hammered every cycle."""
        if self._drain_after is None:
            return
        gang = self._lease[0] if self._lease is not None else None
        if gang != self._drain_gang:
            self._drain_gang, self._drain_cycles = gang, 0
            return
        if gang is None:
            return
        self._drain_cycles += 1
        if self._drain_cycles < self._drain_after:
            return

        from nos_tpu.scheduler.gang import evict_gang
        from nos_tpu.topology.profile import free_chip_equivalents

        hosts = self._reserved_hosts
        # Serving-tier stragglers are never drain-evicted: the tier
        # contract (docs/serving.md) is that NO mechanism preempts a
        # serving pod for batch progress — the autoscaler shrinks
        # replicas when load drops, which drains the window honestly.
        stragglers = [
            p for p in self._api.list(KIND_POD)
            if p.spec.node_name in hosts
            and p.status.phase in (PENDING, RUNNING)
            and (p.metadata.namespace, gang_name(p)) != gang
            and workload_tier(p) != C_TIER_SERVING]
        if not stragglers:
            return
        capacity = 0.0
        for node in self._api.list(KIND_NODE):
            if node.metadata.name in hosts:
                try:
                    capacity += float(node.metadata.labels.get(
                        C_LABEL_CHIP_COUNT, "0"))
                except ValueError:
                    pass
        busy = sum(free_chip_equivalents(pod_request(p))
                   for p in stragglers)
        if capacity <= 0 or busy > self._drain_fraction * capacity:
            return      # not the final stretch: keep waiting

        # PDB respect: budget-charge each candidate's whole eviction set
        # (evict_gang amplifies to every gang mate)
        from nos_tpu.api.pdb import (
            KIND_POD_DISRUPTION_BUDGET, refresh_pdb_status,
        )

        pdbs = [refresh_pdb_status(self._api, pdb)
                for pdb in self._api.list(KIND_POD_DISRUPTION_BUDGET)]
        allowed = [pdb.status.disruptions_allowed for pdb in pdbs]
        evicted = 0
        doomed_keys: set[str] = set()
        # Least progress first; near-done stragglers are spared outright
        # (they free the window by finishing — evicting one wastes its
        # whole run for seconds of drain time).  Progress is GANG-level
        # (max over members): eviction is whole-gang amplified, so a
        # member with an unannotated mate must not sneak its nearly-done
        # gang past the spare filter.
        prog_cache: dict[tuple[str, str], float] = {}

        def progress(p: Pod) -> float:
            g = gang_name(p)
            if not g:
                return self._progress_fn(p)
            key = (p.metadata.namespace, g)
            if key not in prog_cache:
                mates = self._api.list(
                    KIND_POD, namespace=p.metadata.namespace,
                    label_selector={C_LABEL_POD_GROUP: g})
                prog_cache[key] = max(
                    [self._progress_fn(m) for m in mates] or [0.0])
            return prog_cache[key]

        stragglers = sorted(
            (p for p in stragglers
             if progress(p) < self._drain_spare_progress),
            key=progress)
        shrunk_gangs: dict[tuple[str, str], int] = {}
        for pod in stragglers:
            if pod.key in doomed_keys:
                continue
            g = gang_name(pod)
            members = [pod] if not g else self._api.list(
                KIND_POD, namespace=pod.metadata.namespace,
                label_selector={C_LABEL_POD_GROUP: g})
            # Shrink-before-evict (scheduler/elastic.py): an elastic dp
            # straggler loses only its WINDOW-RESIDENT members, within
            # the gang's min bound — evicting a 60-replica sponge whole
            # to clear a 2-host window is exactly the waste the
            # malleable-gang contract exists to avoid.
            shrink = False
            if g:
                from nos_tpu.utils.pod_util import elastic_replica_bounds

                bounds = elastic_replica_bounds(pod)
                if bounds is not None:
                    live_members = [
                        m for m in members
                        if m.status.phase in (PENDING, RUNNING)]
                    headroom = max(0, len(live_members) - bounds[0])
                    on_window = [
                        m for m in live_members
                        if m.spec.node_name in hosts
                        and m.key not in doomed_keys]
                    members = on_window[:headroom]
                    if not members:
                        continue    # at min: nothing to shrink
                    shrink = True
            needed: dict[int, int] = {}
            for m in members:
                if m.status.phase != RUNNING or m.key in doomed_keys:
                    continue
                for i, pdb in enumerate(pdbs):
                    if pdb.matches(m):
                        needed[i] = needed.get(i, 0) + 1
            if any(allowed[i] < n for i, n in needed.items()):
                continue        # a budget lacks allowance: reprieve
            for i, n in needed.items():
                allowed[i] -= n
            doomed_keys.update(m.key for m in members)
            if shrink:
                for m in members:
                    try:
                        self._api.delete(KIND_POD, m.metadata.name,
                                         m.metadata.namespace)
                        evicted += 1
                    except NotFound:
                        pass
                shrunk_gangs[(pod.metadata.namespace, g)] = \
                    shrunk_gangs.get((pod.metadata.namespace, g), 0) \
                    + len(members)
            else:
                evicted += len(evict_gang(self._api, pod))
        if shrunk_gangs:
            from nos_tpu.scheduler.elastic import record_shrink

            for (ns, g), n in sorted(shrunk_gangs.items()):
                record_shrink(self._api, ns, g, n)
        if evicted:
            # the freed chips were BOUGHT by eviction: until the leased
            # window resolves, their idle time is `drain` waste, not
            # natural gang-assembly wait (obs/ledger.py)
            from nos_tpu.obs.ledger import DRAIN, get_ledger

            ledger = get_ledger()
            for host in hosts:
                ledger.set_hold(host, DRAIN, owner=self.name,
                                gang=f"{gang[0]}/{gang[1]}",
                                evicted=evicted)
            self._drain_hold_hosts = frozenset(hosts)
            REGISTRY.inc("nos_tpu_drain_preemptions_total",
                         labels={"gang": f"{gang[0]}/{gang[1]}"},
                         value=evicted)
            logger.info(
                "drain preemption for gang %s/%s: evicted %d straggler "
                "pod(s) off %s after %d cycles", gang[0], gang[1],
                evicted, sorted(hosts), self._drain_cycles)
        # Cooldown either way: the counter restarts, so survivors (spared
        # or PDB-reprieved) get another full drain_preempt_after_cycles
        # before the next attempt — attempts fire every N cycles, as the
        # config documents (the first attempt lands ~2 cycles later than
        # N: one cycle to adopt the lease, one to arm the counter).
        self._drain_cycles = 0

    def _order_gang_windows(self, windows: list) -> list:
        """Order candidate windows so the FIRST one that fits is also the
        best citizen: windows overlapping the drain lease OR a window a
        defrag proposal is emptying come last (a smaller gang binding
        into either would reset the larger drain's clock — for defrag,
        refill the very hosts whose residents were just migrated off),
        original adjacency order otherwise.  Fragmentation-aware
        ordering (prefer breaking already-busy super-windows) was
        measured as well and LOST on the v5e-256 trace (seed-0
        utilization -5 points) — see scripts/diag_gang.py for the
        experiment harness."""
        avoid = set(self._reserved_hosts)
        lister = self._cycle_lister_cache
        if lister is not None:
            for ni in lister.list():
                if ni.node.metadata.annotations.get(
                        C_ANNOT_DEFRAG_DRAIN):
                    avoid.add(ni.name)

        def key(item: tuple) -> int:
            _, hosts = item
            if hosts is None:
                return 0
            return len(frozenset(hosts) & avoid)

        return sorted(windows, key=key)

    def _attempt_gang(self, pins: dict, base: SharedLister,
                      members: list[Pod]) -> tuple[
                          list[tuple[Pod, str]], CycleState, Any,
                          Pod | None]:
        """Simulate placing the whole gang in one pinned domain over
        clones of the base snapshot.  Returns (placements, state, domain,
        stuck): placements is complete on success; `stuck` is the first
        member that found no fit (None on success), with the placed
        mates' capacity on the domain clones and their quota bookings in
        `state` — exactly the context PostFilter preemption needs."""
        domain = [ni.clone() for ni in base.list()
                  if self._pins_match(ni, pins)]
        lister = SharedLister(domain)
        state = CycleState(pins)
        placements: list[tuple[Pod, NodeInfo]] = []
        for pod in members:
            status = self._framework.run_pre_filter_plugins(
                state, pod, lister)
            feasible = []
            if status.is_success:
                feasible = [
                    ni for ni in domain
                    if self._framework.run_filter_plugins(
                        state, pod, ni).is_success
                ]
            if not feasible:
                if status.reason == "quota":
                    # the gang is waiting on LEDGER headroom: lower-
                    # priority same-namespace pods defer (quota HOL).
                    # The unsatisfiability guard judges the WHOLE
                    # gang's request, not one member's.
                    total = self._gang_total_request(members)
                    self._record_quota_hol(pod, total_request=total)
                return [], state, domain, pod
            chosen = min(feasible, key=self._score_key(pod))
            chosen.add_pod(pod)  # next member sees reduced capacity
            self._framework.run_pre_filter_extension_add_pod(
                state, pod, pod, chosen)  # book quota usage for mates
            placements.append((pod, chosen))
        return placements, state, domain, None

    def _gang_total_request(self, members: list[Pod]) -> dict | None:
        """Aggregate quota request of a gang, in the capacity plugin's
        currency; None when no capacity plugin is registered."""
        if self._capacity is None:
            return None
        total: dict = {}
        for m in members:
            total = sum_resources(
                total, self._capacity.calculator.compute_pod_request(m))
        return total

    @staticmethod
    def _pins_match(ni: NodeInfo, pins: dict) -> bool:
        pid = pins.get(GANG_POD_ID_KEY)
        if pid is not None and \
                ni.node.metadata.labels.get(C_LABEL_POD_ID, "") != pid:
            return False
        hosts = pins.get(GANG_HOST_SET_KEY)
        return hosts is None or ni.name in hosts

    def _gang_feasible_after_evictions(
            self, members: list[Pod], candidate_pins: list[dict],
            base: SharedLister) -> dict | None:
        """Would the gang fit some candidate domain if every *evictable*
        pod were gone?  Returns the first such domain's pins (where the
        subsequent preemption attempt should run — eviction only helps
        there), or None.  Guards gang preemption: a gang that is
        topology-infeasible (e.g. needs a 4-host window no domain has, or
        windows fragmented by non-evictable in-quota pods) must not evict
        a fresh over-quota victim gang every cycle to no effect.

        Evictability mirrors _select_victims_on_node's branch structure
        (capacityscheduling.py): a quota-less preemptor takes lower-
        priority quota-less victims; a preemptor over its min takes
        same-namespace lower-priority or cross-namespace over-quota
        victims; a preemptor within min takes cross-namespace over-quota
        victims only.  Quota prefilters are skipped — eviction is exactly
        what relaxes them; only filter-capable plugins (resources,
        topology) gate here."""
        from nos_tpu.utils.pod_util import is_over_quota

        if not any(hasattr(p, "post_filter")
                   for p in self._framework.plugins):
            return None  # nothing could perform an eviction anyway
        first = members[0]
        cap = self._capacity
        infos = cap.elastic_quota_infos if cap is not None else None
        preemptor_info = (infos.get(first.metadata.namespace)
                          if infos is not None else None)
        more_than_min = False
        if preemptor_info is not None:
            # Aggregate gang demand: victim selection runs with the placed
            # mates booked into the quota snapshot, so its over-min test
            # effectively sees the whole gang's claim — a single member's
            # request would misclassify same-namespace victims.

            total_req: dict = {}
            for m in members:
                total_req = sum_resources(
                    total_req, cap.calculator.compute_pod_request(m))
            more_than_min = preemptor_info.used_over_min_with(total_req)

        def directly_evictable(p: Pod) -> bool:
            if workload_tier(p) == C_TIER_SERVING \
                    and not is_over_quota(p):
                # mirrors _select_victims_on_node: in-quota serving is
                # never a victim (over-quota serving borrowers stay
                # reclaimable — the quota guarantee outranks the tier
                # shield), so a domain only "opens up" here if it opens
                # without touching protected serving pods
                return False
            if preemptor_info is None:
                # classic priority preemption among quota-less pods
                if infos is not None \
                        and infos.get(p.metadata.namespace) is not None:
                    return False
                return p.spec.priority < first.spec.priority
            if p.metadata.namespace == first.metadata.namespace:
                return more_than_min \
                    and p.spec.priority < first.spec.priority
            return is_over_quota(p)

        # Gang amplification: evicting any member evicts the whole gang
        # (evict_gang), so every gang-mate of an evictable pod is gone too.
        doomed_gangs = {
            (p.metadata.namespace, gang_name(p))
            for ni in base.list() for p in ni.pods
            if gang_name(p) and directly_evictable(p)
        }

        def evictable(p: Pod) -> bool:
            if directly_evictable(p):
                return True
            g = gang_name(p)
            return bool(g) and (p.metadata.namespace, g) in doomed_gangs

        fw = Framework([p for p in self._framework.plugins
                        if hasattr(p, "filter")])
        for pins in candidate_pins:
            domain = []
            for ni in base.list():
                if not self._pins_match(ni, pins):
                    continue
                optimistic = NodeInfo(node=ni.node)
                for p in ni.pods:
                    if not evictable(p):
                        optimistic.add_pod(p)
                domain.append(optimistic)
            lister = SharedLister(domain)
            state = CycleState(pins)
            placed = 0
            for pod in members:
                fw.run_pre_filter_plugins(state, pod, lister)
                feasible = [
                    ni for ni in domain
                    if fw.run_filter_plugins(state, pod, ni).is_success
                ]
                if not feasible:
                    break
                chosen = min(feasible, key=self._score_key(pod))
                chosen.add_pod(pod)
                placed += 1
            if placed == len(members):
                return pins
        return None

    # -- internals ----------------------------------------------------------
    def _reserve_gang_window(self, gang_key: tuple[str, str],
                             windows: list,
                             base: SharedLister) -> None:
        """A stuck multi-host gang leases its most drained candidate
        window (max free chip-equivalents = least left to wait for),
        re-evaluated every cycle so the lease follows whichever window is
        currently closest to empty.  One lease cluster-wide, oldest stuck
        gang first (processing order).  Advisory: singles shed the
        reservation whenever any other host fits (_score_key), so it
        costs nothing when the cluster has room."""
        if self._lease is not None and self._lease[0] != gang_key:
            return          # another (older) gang holds this cycle's lease
        if not windows:
            return
        free_by_name = {ni.name: _free_chip_equiv(ni) for ni in base.list()}
        best: tuple[float, frozenset[str]] | None = None
        for _, hosts in windows:
            if not hosts:
                continue
            drained = sum(free_by_name.get(h, 0.0) for h in hosts)
            if best is None or drained > best[0]:
                best = (drained, frozenset(hosts))
        if best is not None:
            if best[1] != self._reserved_hosts:
                # the lease moved: drain holds belong to the old window
                self._clear_drain_holds()
            self._lease = (gang_key, best[1])
            self._reserved_hosts = best[1]
            self._window_eta = None     # new window: stale ETA must die
            self._sync_lease_annotations(best[1], gang_key)
            logger.debug("gang %s leased window %s",
                         gang_key, sorted(best[1]))

    def _sync_lease_annotations(self, hosts: frozenset[str],
                                gang_key: tuple[str, str] | None = None
                                ) -> None:
        """Publish the lease on the member nodes (ANNOT_GANG_LEASE) so the
        partitioner drains the SAME window; clear it everywhere else.
        Scanning all nodes also heals stale leases after a scheduler
        restart."""
        value = f"{gang_key[0]}/{gang_key[1]}" if gang_key else ""
        for node in self._api.list(KIND_NODE):
            has = node.metadata.annotations.get(C_ANNOT_GANG_LEASE, "")
            want = value if node.metadata.name in hosts else ""
            if has == want:
                continue

            def mutate(n: Any) -> None:
                if want:
                    n.metadata.annotations[C_ANNOT_GANG_LEASE] = want
                else:
                    n.metadata.annotations.pop(C_ANNOT_GANG_LEASE, None)
            try:
                retry_on_conflict(self._api, KIND_NODE, node.metadata.name,
                                  mutate, component="scheduler-gang-lease")
            except Exception:  # noqa: BLE001 — advisory; next cycle's
                # full-node scan heals a half-synced lease, so nothing on
                # this path (exhausted retries, vanished node, a raising
                # watcher re-thrown through the write) may abort the cycle
                logger.debug("lease annotation patch failed for %s",
                             node.metadata.name)

    def _cycle_busy_map(self, lister: SharedLister) -> dict:
        """The window-busy map, cached for the cycle when the given
        lister IS the cycle snapshot (mutations route through
        _mark_busy/_busy_map_cache invalidation); rebuilt fresh for any
        other lister (direct entry points, gang what-if domains)."""
        if lister is not self._cycle_lister_cache:
            return self._window_busy_map(lister)
        if self._busy_map_cache is None:
            self._busy_map_cache = self._window_busy_map(lister)
        return self._busy_map_cache

    def _window_busy_map(self, lister: SharedLister) -> dict:
        """(pod_id, host_index) -> has-pods, for fragmentation-aware
        scoring.  Built once per scoring decision from the cycle's
        lister view.  The label parse is inherently Python (dict
        lookups on metadata); the fold's native form is the sorted
        busy ARRAYS the native scorer consumes (_busy_score_arrays /
        nos_window_busy), derived from this map on demand."""
        busy: dict[tuple[str, int], bool] = {}
        for ni in lister.list():
            key = self._window_key(ni.node.metadata.labels)
            if key is None:
                continue
            busy[key] = busy.get(key, False) or bool(ni.pods)
        return busy

    def _busy_score_arrays(self, busy: dict) -> tuple | None:
        """The window-busy map marshalled for the native scorer: pod-id
        -> dense gid (in sorted pod-id order), plus (gid, host-index,
        busy) triples sorted lexicographically so nos_score_batch can
        binary-search window membership.  Sorted+folded natively
        (nos_window_busy, GIL-released) when the shim is loaded, in
        Python otherwise — identical output either way.  Cached per
        busy-dict IDENTITY: _mark_busy's in-place flip rebinds the
        cache to None, and a new dict (fresh cycle, eviction) misses
        on identity."""
        cached = self._busy_arrays_cache
        if cached is not None and cached[0] is busy:
            return cached[1]
        import ctypes

        from nos_tpu.device import native

        pids = sorted({pid for pid, _ in busy})
        gid_of = {pid: g for g, pid in enumerate(pids)}
        n = len(busy)
        gid_a = (ctypes.c_longlong * max(1, n))()
        idx_a = (ctypes.c_longlong * max(1, n))()
        val_a = (ctypes.c_uint8 * max(1, n))()
        i = 0
        for (pid, idx), val in busy.items():
            gid_a[i] = gid_of[pid]
            idx_a[i] = idx
            val_a[i] = 1 if val else 0
            i += 1
        if not native.window_busy_sort(gid_a, idx_a, val_a, n):
            # Python fallback: same sorted fold (keys are unique in a
            # dict, so the fold is a pure lexicographic sort)
            triples = sorted(
                (gid_of[pid], idx, 1 if val else 0)
                for (pid, idx), val in busy.items())
            for i, (g, idx, val) in enumerate(triples):
                gid_a[i], idx_a[i], val_a[i] = g, idx, val
        arrays = (gid_of, gid_a, idx_a, val_a, n)
        self._busy_arrays_cache = (busy, arrays)
        return arrays

    def _choose_node(self, pod: Pod, feasible: dict[str, NodeInfo],
                     lister: SharedLister | None) -> NodeInfo:
        """Argmin of the scoring order over the feasible set — one
        GIL-released native call (nos_score_batch) when the shim is
        loaded, the Python _score_key min otherwise.  The native
        comparator replays the exact (avoided, headroom,
        window-penalty, host-index, name-rank) tuple ordering on the
        same IEEE doubles, and the name rank is the node's position in
        the sorted candidate names — the same strict total order as
        comparing the strings — so both paths pick the identical node
        (tests/test_native.py pins the equivalence)."""
        nis = list(feasible.values())
        if len(nis) == 1:
            return nis[0]
        if lister is not None:
            chosen = self._native_choose(pod, nis, lister)
            if chosen is not None:
                return chosen
        return min(nis, key=self._score_key(pod, lister))

    def _native_choose(self, pod: Pod, nis: list[NodeInfo],
                       lister: SharedLister) -> NodeInfo | None:
        """Marshal the candidates for nos_score_batch; None falls back
        to the Python argmin (shim unavailable, or inputs the native
        comparator cannot replay bit-identically — a negative host
        index trips C trunc-division vs Python floor-division)."""
        import ctypes

        from nos_tpu.device import native

        if not native.fit_batch_available(build=False):
            return None
        busy = self._cycle_busy_map(lister)
        arrays = self._busy_score_arrays(busy)
        if arrays is None:
            return None
        gid_of, busy_gid, busy_idx, busy_val, m = arrays
        rank_of = {name: r
                   for r, name in enumerate(sorted(ni.name for ni in nis))}
        req = pod_request(pod)
        n = len(nis)
        avoided = (ctypes.c_uint8 * n)()
        headroom = (ctypes.c_double * n)()
        gids = (ctypes.c_longlong * n)()
        widx = (ctypes.c_longlong * n)()
        hidx = (ctypes.c_longlong * n)()
        rank = (ctypes.c_longlong * n)()
        wsizes: list[int] = []
        woff = (ctypes.c_longlong * (n + 1))()
        for i, ni in enumerate(nis):
            labels = ni.node.metadata.labels
            free = ni.free()
            headroom[i] = sum(free.get(r, 0.0) for r in req)
            try:
                hidx[i] = int(labels.get(C_LABEL_HOST_INDEX, "0"))
            except ValueError:
                hidx[i] = 0
            avoided[i] = 1 if (
                ni.name in self._reserved_hosts
                or bool(ni.node.metadata.annotations.get(
                    C_ANNOT_DEFRAG_DRAIN))) else 0
            rank[i] = rank_of[ni.name]
            gids[i] = -1
            wkey = self._window_key(labels) if m else None
            if wkey is not None:
                pid, idx = wkey
                if idx < 0:
                    return None
                g = gid_of.get(pid)
                # a pod-id absent from the busy map fails every
                # membership test => penalty 0: gid stays -1
                if g is not None:
                    gids[i] = g
                    widx[i] = idx
                    wsizes.extend(self._window_sizes(ni))
            woff[i + 1] = len(wsizes)
        ws_arr = (ctypes.c_longlong * max(1, len(wsizes)))(*wsizes)
        out = native.score_batch(avoided, headroom, gids, widx, hidx,
                                 rank, ws_arr, woff, busy_gid, busy_idx,
                                 busy_val, n, m)
        if out is None:
            return None
        return nis[out]

    @staticmethod
    def _window_sizes(ni: NodeInfo) -> tuple[int, ...]:
        """Multi-host window sizes (in hosts) for this node's generation."""
        return _gen_window_sizes(
            ni.node.metadata.labels.get(C_LABEL_ACCELERATOR, ""))

    def _score_key(self, pod: Pod,
                   lister: SharedLister | None = None
                   ) -> Callable[[NodeInfo], tuple]:
        """Least-requested on the pod's own resources: packs TPU profiles
        tightly (utilization).  Equal-headroom ties prefer hosts whose
        aligned multi-host windows are already broken — placing a
        single-host job in a wholly-free window would strand it for gangs
        (fragmentation; the window convention is topology/windows.py).
        Final ties break on numeric host index, not name — filling hosts
        in physical order keeps high-index aligned windows contiguous
        (lexicographic order would put host-10 before host-2 and fragment
        every window)."""
        req = pod_request(pod)
        busy = self._cycle_busy_map(lister) if lister is not None else {}

        def window_penalty(ni: NodeInfo) -> int:
            if not busy:
                return 0
            wkey = self._window_key(ni.node.metadata.labels)
            if wkey is None:
                return 0
            pid, idx = wkey
            pen = 0
            for size in self._window_sizes(ni):
                start = (idx // size) * size
                window = [(pid, i) for i in range(start, start + size)]
                whole = all(w in busy and not busy[w] for w in window)
                if whole:
                    pen += size  # breaking a whole free window of `size`
            return pen

        def key(ni: NodeInfo) -> tuple:
            free = ni.free()
            headroom = sum(free.get(r, 0.0) for r in req)
            try:
                idx = int(ni.node.metadata.labels.get(
                    C_LABEL_HOST_INDEX, "0"))
            except ValueError:
                idx = 0
            # Reserved-window avoidance dominates: a stuck gang's chosen
            # window must drain, so singles go anywhere else that fits.
            # Hosts a defrag proposal is emptying (ANNOT_DEFRAG_DRAIN)
            # are avoided the same way — the migration bought that
            # window for the fragmentation-blocked class, and refilling
            # it with the very pods just moved off would undo the move.
            avoided = (ni.name in self._reserved_hosts
                       or bool(ni.node.metadata.annotations.get(
                           C_ANNOT_DEFRAG_DRAIN)))
            return (avoided, headroom,
                    window_penalty(ni), idx, ni.name)

        return key

    def _patch_pod(self, pod: Pod, mutate: Callable[[Any], None]) -> bool:
        """A pod can vanish between this cycle's LIST and the patch —
        deleted by a user, a controller, or this very cycle's drain
        preemption (whole-gang amplification can doom a pod that is
        still in the stale pending list).  A gone pod needs no status:
        swallow NotFound instead of killing the scheduling cycle.
        Returns False exactly on that vanished-pod path."""
        try:
            retry_on_conflict(self._api, KIND_POD, pod.metadata.name,
                              mutate, pod.metadata.namespace,
                              component="scheduler")
        except NotFound:
            logger.debug("scheduler: pod %s vanished mid-cycle", pod.key)
            return False
        return True

    def _observe_schedule_latency(self, pods: list[Pod]) -> None:
        """Record queue-admission→bind latency into the per-class SLO
        histogram.  One observation per scheduling unit: a single pod
        observes itself; a gang is passed whole once its LAST member
        bound (the gang's latency is the straggler's).  Pods without a
        creation timestamp (tests, hand-made objects) observe nothing —
        a fabricated zero admission time would poison the p99."""
        ts = min(p.metadata.creation_timestamp for p in pods)
        if ts <= 0.0:
            return
        latency = self._clock() - ts
        if latency < 0.0:
            return      # clock domains disagree: no honest sample exists
        REGISTRY.observe("nos_tpu_schedule_latency_seconds", latency,
                         labels={"class": workload_class(pods[0])})

    def _observe_rebind(self, pods: list[Pod]) -> None:
        """A displaced scheduling unit just re-bound: observe
        displacement-stamp→bind latency into the rebind histogram and
        journal JOB_REBOUND.  Gangs observe once, from the EARLIEST
        member stamp (the job was down from the first kill) — members
        bound in earlier cycles had their stamp cleared at bind, so the
        min runs over whatever stamps this cycle still carries.  Called
        BEFORE _bind's annotation clear lands in the caller's pod
        objects (they are this cycle's stale copies)."""
        stamps = [d for d in (displacement(p) for p in pods)
                  if d is not None]
        if not stamps:
            return
        cause, ts = min(stamps, key=lambda d: d[1])
        if ts <= 0.0:
            return      # fabricated stamp: no honest sample exists
        latency = self._clock() - ts
        if latency < 0.0:
            return      # clock domains disagree
        REGISTRY.observe("nos_tpu_rebind_latency_seconds", latency,
                         labels={"class": workload_class(pods[0])})
        first = pods[0]
        g = gang_name(first)
        subject = (f"{first.metadata.namespace}/{g}" if g else first.key)
        # members_total (the COUNT convention — a `members` attr is
        # reserved for pod-key lists, which explain's membership match
        # iterates)
        journal_record(J.JOB_REBOUND, subject, cause=cause,
                       latency_s=round(latency, 3),
                       members_total=len(pods),
                       **{"class": workload_class(first)})

    # -- chip-second waste attribution (obs/ledger.py) ----------------------
    def _clear_drain_holds(self) -> None:
        if not self._drain_hold_hosts:
            return
        from nos_tpu.obs.ledger import DRAIN, get_ledger

        ledger = get_ledger()
        for host in self._drain_hold_hosts:
            ledger.clear_hold(host, DRAIN, owner=self.name)
        self._drain_hold_hosts = frozenset()

    def _note_quota_blocked(self, pod: Pod) -> None:
        """A pod rejected by the quota gates (PreFilter quota verdict or
        head-of-line deferral): its class's demand is quota-blocked this
        cycle — free chips it could physically use read quota_stranded,
        not idle."""
        from nos_tpu.kube.resources import pod_request as _pod_request
        from nos_tpu.obs.ledger import pod_chip_equiv

        cls = workload_class(pod)
        shard = float(getattr(getattr(self._capacity, "calculator", None),
                              "chips_per_host", 0) or 0) or 8.0
        chips = pod_chip_equiv(_pod_request(pod), shard,
                               self._hbm_gb_per_chip)
        self._waste_quota_blocked[cls] = max(
            self._waste_quota_blocked.get(cls, 0.0), chips)

    def _note_no_fit(self, pod: Pod, rejections: dict[str, str]) -> None:
        """The Filter pipeline rejected this pending pod on every node:
        those verdicts ARE the frag_stranded derivation — a node every
        pending class rejected holds free chips no pending demand can
        use (idempotent per class; the class scan cache replays the
        identical verdict set for class-mates)."""
        from nos_tpu.kube.resources import pod_request as _pod_request
        from nos_tpu.obs.ledger import pod_chip_equiv

        # identity-deduplicated reference, not a set union: class-mates
        # hand in the SAME cached rejection dict, so noting a class is
        # O(1) — and on clean incremental cycles the same objects recur,
        # which the cycle-end waterfall's skeleton memo keys on
        maps = self._waste_rejection_maps
        if not any(m is rejections for m in maps):
            maps.append(rejections)
        cls = workload_class(pod)
        disp = displacement(pod)
        if disp is not None:
            # the stranded class is a node-loss/migration victim: the
            # waste evidence must name the kill cause, so `obs waste`
            # can say "this frag is a displaced gang failing to rebind"
            self._waste_displaced.setdefault(cls, disp[0])
        self._waste_frag_counts[cls] = max(
            self._waste_frag_counts.get(cls, 0), len(rejections))
        shard = float(getattr(getattr(self._capacity, "calculator", None),
                              "chips_per_host", 0) or 0) or 8.0
        chips = pod_chip_equiv(_pod_request(pod), shard,
                               self._hbm_gb_per_chip)
        self._waste_frag_chips[cls] = max(
            self._waste_frag_chips.get(cls, 0.0), chips)

    def _note_stuck_gang(self, members: list[Pod]) -> None:
        """A gang that failed admission this cycle: remember it with its
        members' chip demand — the cap on gang_wait attributed outside
        the leased window (free chips far beyond what the gang could
        consume are idle, not gang wait)."""
        from nos_tpu.kube.resources import pod_request as _pod_request
        from nos_tpu.obs.ledger import pod_chip_equiv

        first = members[0]
        key = f"{first.metadata.namespace}/{gang_name(first)}"
        shard = float(getattr(getattr(self._capacity, "calculator", None),
                              "chips_per_host", 0) or 0) or 8.0
        chips = sum(pod_chip_equiv(_pod_request(m), shard,
                                   self._hbm_gb_per_chip)
                    for m in members)
        self._waste_pending_gangs[key] = max(
            self._waste_pending_gangs.get(key, 0.0), chips)
        disp = next((d for d in (displacement(m) for m in members)
                     if d is not None), None)
        if disp is not None:
            self._waste_displaced.setdefault(key, disp[0])

    def _observe_waste(self, pending_by_class: dict[str, int]) -> None:
        """Cycle end: attribute every chip in the cycle snapshot to ONE
        waterfall category and hand the per-pool breakdown to the
        chip-second ledger.  Free chips on a node are attributed with
        this precedence (docs/observability.md, "The waterfall"):
        quarantine > actuation > drain holds (owning subsystems stamp
        those), then the gang window lease (gang_wait), then this
        cycle's own verdicts — rejected-by-every-scanned-class reads
        frag_stranded; quota-blocked (and off-lease gang) demand reads
        quota_stranded/gang_wait, each CAPPED at the demand's own chip
        size (stranding cannot exceed what the blocked pods could
        consume — one 8-chip quota rejection must not paint a
        1000-chip pool) — and idle_no_demand absorbs the rest.
        Conservation (Σ == capacity) is structural: each chip lands in
        exactly one bucket."""
        from nos_tpu.obs import ledger as L
        from nos_tpu.obs.ledger import get_ledger, pod_chip_equiv

        lister = self._cycle_lister()
        holds = get_ledger().holds()
        demand = bool(pending_by_class) or bool(self._waste_pending_gangs)
        # fallback budgets (module docstring): free chips attributed to
        # blocked-demand categories are bounded by the demand itself
        quota_budget = sum(self._waste_quota_blocked.values())
        gang_budget = sum(self._waste_pending_gangs.values())
        # Per-class stranded chip-second integral: every cycle a class
        # stays frag-blocked, its blocked demand accrues over the cycle
        # interval — the culprit ranking (several classes stranding one
        # pool) keys on this, NOT on rejection recency.
        now = self._clock()
        dt = (max(0.0, now - self._last_waste_t)
              if self._last_waste_t is not None else 0.0)
        self._last_waste_t = now
        for cls, chips in self._waste_frag_chips.items():
            self._frag_class_chip_seconds[cls] = \
                self._frag_class_chip_seconds.get(cls, 0.0) + chips * dt
        frag_ev: dict[str, object] | None = None
        if self._waste_frag_counts:
            ranked = sorted(
                self._waste_frag_counts,
                key=lambda c: (-self._frag_class_chip_seconds.get(c, 0.0),
                               -self._waste_frag_counts[c], c))
            top = ranked[0]
            frag_ev = {
                "class": top,
                "rejected_nodes": self._waste_frag_counts[top],
                "classes": [
                    {"class": c,
                     "stranded_chip_seconds": round(
                         self._frag_class_chip_seconds.get(c, 0.0), 1),
                     "blocked_chips": round(
                         self._waste_frag_chips.get(c, 0.0), 2)}
                    for c in ranked[:3]],
            }
            if top in self._waste_displaced:
                # the stranding class is a displaced victim: name the
                # kill cause so displaced-wait is distinguishable from
                # ordinary fragmentation in the waterfall evidence
                frag_ev["displaced_cause"] = self._waste_displaced[top]
        quota_ev: dict[str, object] | None = None
        if self._waste_quota_blocked:
            top_q = max(self._waste_quota_blocked.items(),
                        key=lambda kv: kv[1])
            quota_ev = {"class": top_q[0],
                        "blocked_chips": round(top_q[1], 2)}
        gang_ev: dict[str, object] | None = None
        if self._lease is not None:
            gang_ev = {"gang": f"{self._lease[0][0]}/{self._lease[0][1]}"}
        elif self._waste_pending_gangs:
            top_g = max(self._waste_pending_gangs.items(),
                        key=lambda kv: kv[1])
            gang_ev = {"gang": top_g[0]}
        if gang_ev is not None:
            cause = self._waste_displaced.get(str(gang_ev["gang"]))
            if cause is not None:
                gang_ev["displaced_cause"] = cause

        # Skeleton memo: the attribution loop below is O(nodes) and a
        # pure function of (node states, holds, reserved set, demand,
        # budgets, rejection membership).  The view epoch certifies the
        # node states; everything else is compared directly — rejection
        # membership by map IDENTITY (clean incremental cycles replay
        # the same cached rejection dicts, whose content cannot move
        # without an epoch bump).  On a hit, only the evidence dicts
        # are re-resolved (the frag culprit's chip-second integral
        # accrues every cycle) and the per-pool template is replayed.
        rej_maps = tuple(self._waste_rejection_maps)
        epoch = (self._cache.view_epoch()
                 if self._cache is not None
                 and lister is self._cycle_lister_cache else None)
        skel_key = None
        if epoch is not None:
            skel_key = (epoch, demand, quota_budget, gang_budget,
                        self._reserved_hosts, holds)
            prev = self._waste_skel
            if prev is not None and prev[0] == skel_key \
                    and len(prev[1]) == len(rej_maps) \
                    and all(a is b for a, b in zip(prev[1], rej_maps)):
                replay: dict[str, dict[str, object]] = {}
                for pool, (pcap, pcats, evcats) in prev[2].items():
                    rentry: dict[str, object] = {
                        "capacity": pcap, "categories": dict(pcats),
                        "evidence": {}}
                    rev: dict[str, dict[str, object]] = \
                        rentry["evidence"]  # type: ignore[assignment]
                    for cat, src in evcats.items():
                        live = (gang_ev if src == "gang" else
                                frag_ev if src == "frag" else
                                quota_ev if src == "quota" else src)
                        if live:
                            rev[cat] = dict(live)
                    replay[pool] = rentry
                get_ledger().observe(replay)
                return

        pools: dict[str, dict[str, object]] = {}
        for ni in lister.list():
            labels = ni.node.metadata.labels
            if is_warm_spare_labels(labels):
                # a warm spare is deliberately-held reserve, not fleet
                # capacity: outside the waterfall until promoted (its
                # SpareGuard rejections must not read frag_stranded)
                continue
            try:
                cap = float(labels.get(C_LABEL_CHIP_COUNT, "0") or 0.0)
            except ValueError:
                cap = 0.0
            if cap <= 0.0:
                continue        # not a TPU host: outside the ledger
            pool = labels.get(C_LABEL_POD_ID, "") or "-"
            entry = pools.setdefault(
                pool, {"capacity": 0.0, "categories": {}, "evidence": {}})
            entry["capacity"] = float(entry["capacity"]) + cap  # type: ignore[arg-type]
            cats: dict[str, float] = entry["categories"]  # type: ignore[assignment]
            used = min(cap, pod_chip_equiv(ni.requested, cap,
                                           self._hbm_gb_per_chip))
            free = cap - used
            if used > 0.0:
                cats[L.PRODUCTIVE] = cats.get(L.PRODUCTIVE, 0.0) + used
            if free <= 0.0:
                continue
            name = ni.name
            hold = holds.get(name)
            cat, take, quota_budget, gang_budget = attribute_free_chips(
                free, hold, name in self._reserved_hosts, demand,
                any(name in m for m in rej_maps),
                quota_budget, gang_budget)
            evidence: dict[str, object] | None = None
            if cat == L.QUARANTINE:
                evidence = {"node": name, **(hold or {})[L.QUARANTINE]}
            elif cat == L.ACTUATION:
                evidence = {"node": name, **(hold or {})[L.ACTUATION]}
            elif cat == L.DRAIN:
                evidence = {"node": name, **(hold or {})[L.DRAIN]}
            elif cat == L.PROVISIONING:
                evidence = {"node": name, **(hold or {})[L.PROVISIONING]}
            elif cat == L.GANG_WAIT:
                evidence = gang_ev
            elif cat == L.FRAG_STRANDED:
                evidence = frag_ev
            elif cat == L.QUOTA_STRANDED:
                evidence = quota_ev
            cats[cat] = cats.get(cat, 0.0) + take
            if take < free:
                cats[L.IDLE_NO_DEMAND] = \
                    cats.get(L.IDLE_NO_DEMAND, 0.0) + (free - take)
            if evidence:
                ev: dict[str, dict[str, object]] = entry["evidence"]  # type: ignore[assignment]
                ev.setdefault(cat, dict(evidence))
        if skel_key is not None:
            # record the template for the next clean cycle: shared
            # evidence sources symbolically (re-resolved at replay —
            # their content accrues), per-node hold evidence literally
            skel: dict[str, tuple] = {}
            for pool, entry in pools.items():
                pcats: dict[str, float] = entry["categories"]  # type: ignore[assignment]
                pev: dict[str, dict[str, object]] = entry["evidence"]  # type: ignore[assignment]
                skel[pool] = (
                    entry["capacity"], dict(pcats),
                    {cat: ("gang" if cat == L.GANG_WAIT else
                           "frag" if cat == L.FRAG_STRANDED else
                           "quota" if cat == L.QUOTA_STRANDED else
                           dict(ev_d))
                     for cat, ev_d in pev.items()})
            self._waste_skel = (skel_key, rej_maps, skel)
        else:
            self._waste_skel = None
        get_ledger().observe(pools)

    def _publish_pending_gauges(self) -> dict[str, int]:
        """Per-class pending-pod gauges after a cycle: how many pods of
        each workload class are still waiting and the oldest one's age —
        the scoreboard's pending-by-class column and the SLO engine's
        leading breach indicator.  BOTH gauges are recomputed from live
        queue membership at observe time, and the reset set comes from
        the REGISTRY'S OWN series list rather than an in-memory
        "classes I last published" note: that note goes stale across a
        scheduler replacement/restart (the registry is process-global,
        the note was per-instance) and across a publish skipped by a
        raising cycle — either way a class that momentarily emptied
        could keep reporting its last (stale, maximal) age as a live
        backlog forever.  Classes with no pending pod read 0.  Returns
        the per-class pending counts — the waste waterfall's
        is-there-demand signal (_observe_waste)."""
        now = self._clock()
        count: dict[str, int] = {}
        oldest: dict[str, float] = {}
        for p in self._pending_pods():
            if p.spec.node_name or p.spec.scheduler_name != self.name:
                continue
            cls = workload_class(p)
            count[cls] = count.get(cls, 0) + 1
            ts = p.metadata.creation_timestamp
            if 0.0 < ts <= now:
                oldest[cls] = max(oldest.get(cls, 0.0), now - ts)
        published = set(REGISTRY.gauge_label_values(
            "nos_tpu_schedule_pending_pods", "class"))
        published.update(REGISTRY.gauge_label_values(
            "nos_tpu_schedule_pending_age_seconds", "class"))
        for cls in published - set(count):
            REGISTRY.set("nos_tpu_schedule_pending_pods", 0.0,
                         labels={"class": cls})
            REGISTRY.set("nos_tpu_schedule_pending_age_seconds", 0.0,
                         labels={"class": cls})
        for cls, n in count.items():
            REGISTRY.set("nos_tpu_schedule_pending_pods", float(n),
                         labels={"class": cls})
            REGISTRY.set("nos_tpu_schedule_pending_age_seconds",
                         oldest.get(cls, 0.0), labels={"class": cls})
        return count

    def _bind(self, pod: Pod, node_name: str) -> bool:
        # Binding only (the /binding subresource against a real substrate).
        # phase=Running is the KUBELET's claim, not the scheduler's — the
        # node agents make it for the in-memory substrate
        # (controllers/kubelet.py); asserting it here would inflate PDB
        # current_healthy and gang liveness before containers exist.
        #
        # Returns whether the bind landed: a vanished pod produced no
        # write, hence no watch event and no generation bump — assuming
        # it into the cycle snapshot would permanently pollute the
        # incremental cache's NodeInfo with phantom capacity (the old
        # full-rebuild snapshot self-healed; the cache must not).
        def mutate(p: Pod) -> None:
            p.spec.node_name = node_name
            p.status.conditions = [
                c for c in p.status.conditions if c.type != "PodScheduled"
            ]
            # a bound pod is no longer unschedulable: the class label
            # dies with the condition it refines
            p.metadata.labels.pop(C_LABEL_UNSCHEDULABLE_CLASS, None)
            # the displaced claim is consumed by this bind: a LATER
            # requeue (quota preemption, drain) is a fresh event and
            # must not inherit the head-of-line boost
            p.metadata.annotations.pop(C_ANNOT_DISPLACED, None)
        if not self._patch_pod(pod, mutate):
            return False
        journal_record(J.POD_BOUND, pod.key, node=node_name)
        logger.debug("scheduler: bound %s -> %s", pod.key, node_name)
        return True

    def _nominate(self, pod: Pod, node_name: str) -> None:
        def mutate(p: Pod) -> None:
            p.status.nominated_node_name = node_name
        self._patch_pod(pod, mutate)
        journal_record(J.POD_NOMINATED, pod.key, node=node_name)

    @staticmethod
    def _node_reason_attrs(node_reasons: dict[str, str]) -> dict:
        """Journal attrs for a per-node rejection map: per-node verdicts
        capped (MAX_JOURNAL_NODES), per-reason counts complete.  Reason
        strings embed per-node numbers (e.g. "used+req over cap"), so a
        heterogeneous cluster can mint one distinct reason per node —
        cap them too (top-N by node count) and carry the complete total
        separately.  Computed once per equivalence class per cycle (the
        class scan cache memoises the result: at fleet scale sorting
        1024 rejections per pending pod was measurable)."""
        if not node_reasons:
            return {}
        return {
            "nodes": dict(sorted(
                node_reasons.items())[:MAX_JOURNAL_NODES]),
            "reason_counts": dict(Counter(
                node_reasons.values()).most_common(MAX_JOURNAL_NODES)),
            "nodes_total": len(node_reasons),
        }

    @staticmethod
    def _already_marked(pod: Pod, status: Status) -> bool:
        """Whether the pod already carries EXACTLY the unschedulable
        condition + class label mark_unschedulable would write.  A
        resident never-fitting pod is re-rejected every cycle; without
        this guard each rejection pays an API patch (deepcopy + watch
        fan-out) to rewrite an identical status — at fleet scale that
        write, not the decision, dominates the steady cycle.  The
        predicate reads only store-derived pod state, so the
        incremental and full-rescan paths skip identically."""
        from nos_tpu.api.constants import LABEL_UNSCHEDULABLE_CLASS

        if pod.metadata.labels.get(LABEL_UNSCHEDULABLE_CLASS) \
                != (status.reason or None):
            return False
        for c in pod.status.conditions:
            if c.type == "PodScheduled":
                return (c.status == "False"
                        and c.reason == "Unschedulable"
                        and c.message == status.message)
        return False

    def _mark_unschedulable(self, pod: Pod, status: Status,
                            node_reasons: dict[str, str] | None = None,
                            node_attrs: dict | None = None) -> None:
        def mutate(p: Pod) -> None:
            p.mark_unschedulable(status.message, status.reason)
        if not self._already_marked(pod, status):
            self._patch_pod(pod, mutate)
        # the journal's "why is this pod pending" substrate; `class`
        # joins rejections to SLO breach records (obs slo names the
        # breaching class's rejecting plugin through it)
        attrs: dict = {"reason": status.reason, "message": status.message,
                       "class": workload_class(pod)}
        if status.plugin:
            attrs["plugin"] = status.plugin
        if node_attrs is None and node_reasons:
            node_attrs = self._node_reason_attrs(node_reasons)
        if node_attrs:
            attrs.update(node_attrs)
        g = gang_name(pod)
        if g:
            attrs["gang"] = f"{pod.metadata.namespace}/{g}"
        journal_record(J.POD_REJECTED, pod.key, **attrs)
