"""Malleable gangs: the control-plane side of the dp-elasticity contract.

A gang whose members carry ``nos.tpu/elastic: "dp"`` plus replica
bounds (api/constants.py) trades a fixed world size for utilization:

- **grow** — a scheduler cycle-end pass (`maybe_grow`, called from
  Scheduler.run_cycle) clones one extra member for each fully-running
  elastic gang below its max whose pinned ICI domain still fits the
  member, up to a per-cycle budget.  The clone rides the normal queue
  next cycle, so admission, quota and topology all apply unchanged.
- **shrink** — capacityscheduling's victim walk treats members of a
  gang above its min as *shrinkable*: the cheapest preemption rung
  (walked before even best-effort eviction) whose eviction does NOT
  amplify to the whole gang — the job loses one dp replica, not its
  run.  Eligibility branches are untouched; only amplification and
  walk order change, so victim_prescreen's superset contract holds.

Both directions stamp ``nos.tpu/dp-resize`` (the new member count) on
every surviving member; cmd/train.py reads it back at each checkpoint
and exits cleanly for a restart with the new mesh (the job-progress
hook's sibling — resize costs one checkpoint restart, never lost work).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable

from nos_tpu.api import constants as C
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import APIServer, KIND_POD, NotFound
from nos_tpu.kube.objects import PENDING, Pod, RUNNING, fast_deepcopy, new_uid
from nos_tpu.obs import journal as J
from nos_tpu.obs.journal import record as journal_record
from nos_tpu.utils.pod_util import elastic_replica_bounds
from nos_tpu.utils.retry import retry_on_conflict

logger = logging.getLogger(__name__)

REGISTRY.describe("nos_tpu_gang_resize_total",
                  "Elastic gang dp resizes by direction (grow/shrink)")


def live_gang_members(api: APIServer, namespace: str,
                      gang: str) -> list[Pod]:
    return api.list(
        KIND_POD, namespace=namespace,
        label_selector={C.LABEL_POD_GROUP: gang},
        filter_fn=lambda p: p.status.phase in (PENDING, RUNNING))


def shrink_headroom(members: list[Pod]) -> int:
    """How many members the gang may lose before hitting its declared
    min (0 = rigid or already at the floor).  Bounds come from any
    member — the contract rides on every pod identically."""
    if not members:
        return 0
    bounds = elastic_replica_bounds(members[0])
    if bounds is None:
        return 0
    return max(0, len(members) - bounds[0])


def stamp_resize(api: APIServer, members: list[Pod],
                 new_count: int) -> None:
    """Publish the post-resize dp replica count on every surviving
    member (ANNOT_DP_RESIZE) — the signal cmd/train.py's checkpoint
    hook reads to restart with the new mesh.  Advisory: a failed stamp
    only delays the workload's re-mesh by one resync."""
    value = str(new_count)

    def mutate(p: Pod) -> None:
        p.metadata.annotations[C.ANNOT_DP_RESIZE] = value

    for member in members:
        try:
            retry_on_conflict(api, KIND_POD, member.metadata.name, mutate,
                              member.metadata.namespace,
                              component="elastic-resize")
        except NotFound:
            continue            # the evicted member itself
        except Exception:  # noqa: BLE001 — advisory annotation
            logger.debug("dp-resize stamp failed for %s", member.key)


def record_shrink(api: APIServer, namespace: str, gang: str,
                  evicted: int, **attrs: object) -> None:
    """Post-shrink bookkeeping shared by every shrink call site
    (capacityscheduling's victim walk, drain preemption, the
    defragmenter): stamp the survivors' dp-resize annotation, bump the
    resize counter, journal GANG_RESIZED.  A gang with NO survivors was
    not shrunk — it died whole (evict_gang) — so nothing is recorded;
    a phantom 'shrink to 0 replicas' would mislead every obs join."""
    survivors = live_gang_members(api, namespace, gang)
    if not survivors:
        return
    stamp_resize(api, survivors, len(survivors))
    REGISTRY.inc("nos_tpu_gang_resize_total", float(evicted),
                 labels={"direction": "shrink"})
    journal_record(J.GANG_RESIZED, f"{namespace}/{gang}",
                   direction="shrink", evicted=evicted,
                   replicas=len(survivors), **attrs)


def clone_member_for_grow(template: Pod, name: str,
                          created: float) -> Pod:
    """A fresh pending replica cloned from a live member: same request,
    labels and elasticity contract; identity, binding and status reset
    so it rides the normal admission queue."""
    pod = fast_deepcopy(template)
    pod.metadata.name = name
    pod.metadata.uid = new_uid()
    pod.metadata.creation_timestamp = created
    pod.metadata.resource_version = 0
    pod.metadata.labels.pop(C.LABEL_UNSCHEDULABLE_CLASS, None)
    pod.metadata.annotations.pop(C.ANNOT_JOB_PROGRESS, None)
    pod.metadata.annotations.pop(C.ANNOT_DP_RESIZE, None)
    # a grown replica is NEW work: it must not inherit a template's
    # displaced head-of-line claim (or a displaced elastic gang would
    # mint queue-jumping clones until its max)
    pod.metadata.annotations.pop(C.ANNOT_DISPLACED, None)
    pod.metadata.annotations.pop(C.ANNOT_MIGRATE, None)
    pod.spec.node_name = ""
    pod.status.phase = PENDING
    pod.status.conditions = []
    pod.status.nominated_node_name = ""
    return pod


def maybe_grow(api: APIServer, framework: Any, lister: Any,
               budget: int = 1,
               clock: Callable[[], float] = time.time) -> int:
    """The cycle-end grow pass: for each fully-RUNNING elastic gang
    below max-replicas, verify one more member fits its pinned ICI
    domain (the real PreFilter+Filter pipeline against the post-bind
    cycle view) and create the clone.  Returns members created.

    Gangs with any pending member are skipped — a gang still
    assembling (or whose previous grow has not bound yet) must finish
    before growing again, which also rate-limits growth to one member
    per gang per bind."""
    if budget <= 0:
        return 0
    from nos_tpu.scheduler.framework import CycleState
    from nos_tpu.scheduler.gang import GANG_POD_ID_KEY

    gangs: dict[tuple[str, str], list[Pod]] = {}
    blocked: set[tuple[str, str]] = set()
    for pod in api.list(KIND_POD):
        gang = pod.metadata.labels.get(C.LABEL_POD_GROUP, "")
        if not gang:
            continue
        key = (pod.metadata.namespace, gang)
        if pod.status.phase == PENDING:
            blocked.add(key)
        elif pod.status.phase == RUNNING and pod.spec.node_name:
            gangs.setdefault(key, []).append(pod)
    created = 0
    for key in sorted(gangs):
        if created >= budget:
            break
        if key in blocked:
            continue
        members = gangs[key]
        bounds = elastic_replica_bounds(members[0])
        if bounds is None or len(members) >= bounds[1]:
            continue
        template = min(members, key=lambda p: p.metadata.name)
        ni = lister.get(template.spec.node_name)
        pin = (ni.node.metadata.labels.get(C.LABEL_POD_ID, "")
               if ni is not None else "")
        state = CycleState({GANG_POD_ID_KEY: pin})
        probe = clone_member_for_grow(
            template, f"{template.metadata.name}-probe", clock())
        if not framework.run_pre_filter_plugins(
                state, probe, lister).is_success:
            continue
        feasible = [n for n in lister.list()
                    if framework.run_filter_plugins(
                        state, probe, n).is_success]
        if not feasible:
            continue
        ns, gang = key
        name = _grow_name(api, ns, gang, members)
        pod = clone_member_for_grow(template, name, clock())
        try:
            api.create(KIND_POD, pod)
        except Exception:  # noqa: BLE001 — name collision/admission:
            # nothing created, the gang retries next cycle
            logger.debug("elastic grow create failed for %s/%s", ns, gang)
            continue
        created += 1
        new_count = len(members) + 1
        stamp_resize(api, members, new_count)
        REGISTRY.inc("nos_tpu_gang_resize_total",
                     labels={"direction": "grow"})
        journal_record(J.GANG_RESIZED, f"{ns}/{gang}",
                       direction="grow", replicas=new_count,
                       member=pod.key)
        logger.info("elastic gang %s/%s grew to %d replicas (%s)",
                    ns, gang, new_count, name)
    return created


def _grow_name(api: APIServer, namespace: str, gang: str,
               members: list[Pod]) -> str:
    """A fresh member name: "<gang>-e<N>" with the first unused N —
    deterministic and collision-checked against the live store."""
    taken = {p.metadata.name for p in api.list(
        KIND_POD, namespace=namespace,
        label_selector={C.LABEL_POD_GROUP: gang})}
    n = len(members)
    while f"{gang}-e{n}" in taken:
        n += 1
    return f"{gang}-e{n}"
