"""CapacityScheduling: elastic-quota enforcement + over-quota preemption.

Re-derivation of reference
pkg/scheduler/plugins/capacityscheduling/capacity_scheduling.go for the
nos_tpu scheduler framework, with quota currency `nos.tpu/tpu-memory`
(see nos_tpu/quota/calculator.py).

Plugin points (reference capacity_scheduling.go:92-95):
- PreFilter (:190-278): snapshot quota ledger into cycle state; account
  nominated pods; reject if used+req > max, or aggregate used+req > aggregate
  min.
- AddPod/RemovePod extensions (:286-321): keep the cycle-state snapshot
  coherent during preemption what-ifs.
- PostFilter (:323-341): preemption — over-quota-aware victim selection with
  guaranteed-overquota fair sharing (SelectVictimsOnNode :468-675).
- Reserve/Unreserve (:343-369): book usage on the live ledger.

One deliberate divergence from the reference: quota aggregates
(aggregated min/used/overquotas) count each CompositeElasticQuota once,
not once per spanned namespace (the reference's map-range aggregation
counts a CEQ's min N times for N namespaces — elasticquotainfo.go:154-174).
"""

from __future__ import annotations

import logging
from typing import Any

from nos_tpu.api import constants as C
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import (
    APIServer, KIND_COMPOSITE_ELASTIC_QUOTA, KIND_ELASTIC_QUOTA, KIND_POD,
    NotFound,
)
from nos_tpu.kube.objects import PENDING, RUNNING, Pod
from nos_tpu.kube.resources import (
    ResourceList, fits, pod_request, sum_resources,
)
from nos_tpu.obs import journal as J
from nos_tpu.obs.journal import MAX_JOURNAL_NODES, record as journal_record
from nos_tpu.quota import ElasticQuotaInfo, ElasticQuotaInfos, TPUResourceCalculator
from nos_tpu.scheduler.framework import (
    CycleState, Framework, NodeInfo, SharedLister, Status, _slice_chips,
)
from nos_tpu.utils.pod_util import (
    elastic_replica_bounds, is_displaced_fresh, is_over_quota,
    job_progress, tier_rank, workload_tier,
)

logger = logging.getLogger(__name__)

REGISTRY.describe("nos_tpu_preemptions_total",
                  "Over-quota preemption decisions executed")
REGISTRY.describe("nos_tpu_preemption_victims_total",
                  "Pods evicted by over-quota preemption")

PRE_FILTER_STATE_KEY = "PreFilterCapacityScheduling"
ELASTIC_QUOTA_SNAPSHOT_KEY = "ElasticQuotaSnapshot"
# (now, displaced_age_cap_s) the scheduler stashes before PostFilter so
# the restart-cost victim walk judges "displaced" with the SAME
# freshness rule as the admission queue (pod_util.is_displaced_fresh);
# absent (plugin driven directly) the stamp never expires.
DISPLACED_CONTEXT_KEY = "DisplacedPreemptorContext"
# Fleet view epoch the scheduler stamps before PostFilter when (and only
# when) the cycle runs against the real watch-cache lister: equal epochs
# certify the node set and every allocatable are unchanged, which keys
# the persistent victim-prescreen mask (ISSUE 18).  Gang what-if domains
# never carry it, so their synthetic listers cannot poison the cache.
VIEW_EPOCH_CONTEXT_KEY = "SchedulerViewEpoch"


class PreFilterState:
    """Reference capacity_scheduling.go:61-73."""

    def __init__(self, pod_req: ResourceList,
                 nominated_in_eq_with_req: ResourceList | None = None,
                 nominated_with_req: ResourceList | None = None) -> None:
        self.pod_req = pod_req
        # podReq + requests of nominated pods in the same quota with
        # priority >= preemptor.
        self.nominated_in_eq_with_req = nominated_in_eq_with_req or dict(pod_req)
        # podReq + requests of nominated pods across all quotas (same-quota
        # higher-priority ones, plus other-quota ones whose quota is
        # within min).
        self.nominated_with_req = nominated_with_req or dict(pod_req)


def victim_prescreen(preemptor: Pod, pv: Pod,
                     snapshot: ElasticQuotaInfos) -> bool:
    """Could `pv` EVER be selected as a victim for `preemptor` by
    `_select_victims_on_node`'s walk?  Used as the performance pre-screen
    that skips victim-less nodes before paying the what-if clones.

    CONTRACT: this predicate must remain a SUPERSET of the walk's
    selection branches — it may pass pods the walk later refuses (it
    ignores the guaranteed-overquota arithmetic and the preemptor's
    over-min state, both of which only ever *narrow* selection), but it
    must never refuse a pod the walk could select, or nodes holding
    valid victims are silently skipped.  Any change to the walk's
    branch structure (e.g. relaxing the over-quota label requirement on
    cross-namespace victims) must be mirrored here;
    tests/test_obs.py::TestVictimPrescreen asserts the superset property
    over the branch grid.

    The branches, mirroring the walk (reference :516-596):
    (a) quota-less preemptor: quota-less lower-priority victims only;
    (b) governed preemptor, same namespace: lower-priority victims;
    (c) governed preemptor, cross-namespace: governed victims carrying
        the over-quota label.
    """
    preemptor_governed = snapshot.get(preemptor.metadata.namespace) \
        is not None
    governed = snapshot.get(pv.metadata.namespace) is not None
    if not preemptor_governed:
        return not governed \
            and pv.spec.priority < preemptor.spec.priority
    if not governed:
        return False
    if pv.metadata.namespace == preemptor.metadata.namespace:
        return pv.spec.priority < preemptor.spec.priority
    return is_over_quota(pv)


def _spec_unchanged(old: ElasticQuotaInfo, new: ElasticQuotaInfo) -> bool:
    """True when nothing the ledger cares about changed — skips the
    O(pods) recount on the status-only updates reconcilers emit."""
    return (old.namespaces == new.namespaces and old.min == new.min
            and old.max == new.max and old.max_enforced == new.max_enforced)


def info_from_quota(obj: Any, calculator: TPUResourceCalculator,
                    composite: bool = False) -> ElasticQuotaInfo:
    """Build the ledger entry for an ElasticQuota/CompositeElasticQuota
    (the informer's mapping, reference informer.go:139-260)."""
    return ElasticQuotaInfo(
        resource_name=obj.metadata.name,
        resource_namespace=obj.metadata.namespace,
        namespaces=obj.namespaces,
        min=obj.spec.min,
        max=obj.spec.max or None,
        calculator=calculator,
        composite=composite,
    )


class CapacityScheduling:
    """The plugin.  Construct, then `attach(api)` to sync the ledger from
    the API server (the informer analog); inside planner simulations it can
    run detached with an empty ledger, exactly as the embedded framework in
    reference cmd/gpupartitioner/gpupartitioner.go:294-318 starts empty."""

    name = "CapacityScheduling"

    def __init__(self, calculator: TPUResourceCalculator | None = None) -> None:
        self.calculator = calculator or TPUResourceCalculator()
        self.elastic_quota_infos = ElasticQuotaInfos()
        self._api: APIServer | None = None
        self._framework: Framework | None = None
        # Optional observer called as on_preempt(preemptor, victims) just
        # before each eviction — how the utilization bench audits that
        # every cross-namespace victim carried the over-quota label
        # (falsifiable fairness invariant).  None = no observer.
        self.on_preempt = None
        self._nominated_rv: int | None = None
        self._nominated_cache: list[Pod] = []
        # request-signature -> (view epoch, empty-node fit mask); see
        # _victim_screen.  Bounded: cleared wholesale past 512 classes.
        self._victim_mask_cache: dict[
            tuple, tuple[int, frozenset[str]]] = {}

    # ------------------------------------------------------------------
    # Wiring
    # ------------------------------------------------------------------
    def set_framework(self, fw: Framework) -> None:
        """Handle used to re-run Filter during preemption what-ifs
        (RunFilterPluginsWithNominatedPods, reference :610,639)."""
        self._framework = fw

    def attach(self, api: APIServer) -> None:
        """Subscribe to EQ/CEQ and Pod events (the informer handlers,
        reference capacity_scheduling.go:131-172)."""
        self._api = api
        api.watch(KIND_ELASTIC_QUOTA, self._on_eq_event)
        api.watch(KIND_COMPOSITE_ELASTIC_QUOTA, self._on_ceq_event)
        api.watch(KIND_POD, self._on_pod_event)

    def _on_eq_event(self, event: str, eq: Any) -> None:
        # A namespace covered by a composite quota is shadowed by it
        # (reference informer.go:139-260).
        ns = eq.metadata.namespace
        existing = self.elastic_quota_infos.get(ns)
        if event == "DELETED":
            if existing is not None and not existing.composite \
                    and existing.resource_name == eq.metadata.name:
                self.elastic_quota_infos.delete(existing)
            return
        if existing is not None and existing.composite:
            return
        new = info_from_quota(eq, self.calculator)
        if existing is not None and _spec_unchanged(existing, new):
            return  # status-only update (e.g. the reconciler's used patch)
        if existing is not None:
            self.elastic_quota_infos.update_info(existing, new)
        else:
            self.elastic_quota_infos.add(new)
        self._recount(new)

    def _on_ceq_event(self, event: str, ceq: Any) -> None:
        new = info_from_quota(ceq, self.calculator, composite=True)
        existing = None
        for info in self.elastic_quota_infos.values():
            if info.composite and info.resource_name == ceq.metadata.name \
                    and info.resource_namespace == ceq.metadata.namespace:
                existing = info
                break
        if event == "DELETED":
            if existing is not None:
                self.elastic_quota_infos.delete(existing)
            return
        if existing is not None and _spec_unchanged(existing, new):
            return
        if existing is not None:
            self.elastic_quota_infos.update_info(existing, new)
        else:
            # Composite shadows any plain EQ on its namespaces.
            for ns in new.namespaces:
                shadowed = self.elastic_quota_infos.get(ns)
                if shadowed is not None:
                    self.elastic_quota_infos.delete(shadowed)
            self.elastic_quota_infos.add(new)
        self._recount(new)

    def _recount(self, info: ElasticQuotaInfo) -> None:
        """Seed usage from already-assigned pods when a quota appears."""
        if self._api is None:
            return
        for pod in self._api.list(KIND_POD):
            if pod.metadata.namespace in info.namespaces \
                    and pod.spec.node_name \
                    and pod.status.phase in (PENDING, RUNNING):
                info.add_pod_if_not_present(pod)

    def _on_pod_event(self, event: str, pod: Pod) -> None:
        info = self.elastic_quota_infos.get(pod.metadata.namespace)
        if info is None:
            return
        assigned = bool(pod.spec.node_name)
        if event == "DELETED" or pod.status.phase not in (PENDING, RUNNING):
            info.delete_pod_if_present(pod)
        elif assigned:
            info.add_pod_if_not_present(pod)

    # ------------------------------------------------------------------
    # PreFilter
    # ------------------------------------------------------------------
    def pre_filter(self, state: CycleState, pod: Pod,
                   nodes: SharedLister) -> Status:
        # Reuse an existing cycle-state snapshot rather than re-cloning:
        # gang scheduling runs PreFilter once per member against ONE state,
        # booking each placed member via the AddPod extension, so later
        # members' max/aggregate checks see their gang-mates' usage.
        snapshot = state.get(ELASTIC_QUOTA_SNAPSHOT_KEY)
        if snapshot is None:
            snapshot = self.elastic_quota_infos.clone()
            state[ELASTIC_QUOTA_SNAPSHOT_KEY] = snapshot
        pod_req = self.calculator.compute_pod_request(pod)

        eq = snapshot.get(pod.metadata.namespace)
        if eq is None:
            state[PRE_FILTER_STATE_KEY] = PreFilterState(pod_req)
            return Status.ok()

        nominated_in_eq: ResourceList = {}
        nominated_all: ResourceList = {}
        for np in self._nominated_pods():
            if np.metadata.uid == pod.metadata.uid:
                continue
            ns = np.metadata.namespace
            info = self.elastic_quota_infos.get(ns)
            if info is None:
                continue
            req = self.calculator.compute_pod_request(np)
            if ns == pod.metadata.namespace \
                    and np.spec.priority >= pod.spec.priority:
                nominated_in_eq = sum_resources(nominated_in_eq, req)
                nominated_all = sum_resources(nominated_all, req)
            elif ns != pod.metadata.namespace and not info.used_over_min():
                nominated_all = sum_resources(nominated_all, req)

        pfs = PreFilterState(
            pod_req,
            sum_resources(nominated_in_eq, pod_req),
            sum_resources(nominated_all, pod_req),
        )
        state[PRE_FILTER_STATE_KEY] = pfs

        if eq.used_over_max_with(pfs.nominated_in_eq_with_req):
            return Status.unschedulable(
                f"quota {eq.resource_namespace}/{eq.resource_name} "
                f"used more than max", reason="quota"
            )
        if snapshot.aggregated_used_over_min_with(pfs.nominated_with_req):
            return Status.unschedulable("total quota used is more than min",
                                       reason="quota")
        return Status.ok()

    def _nominated_pods(self) -> list[Pod]:
        if self._api is None:
            return []
        # rv-memoized: PreFilter runs for every pod of every cycle and
        # nominated pods are rare — re-listing (and deep-copying) the
        # whole pod store each time dominated the cycle cost at v5e-256
        # scale.  The global mutation counter invalidates exactly when
        # anything changed; substrates without it (REST) list every time.
        rv = getattr(self._api, "resource_version", None)
        if rv is not None and rv == self._nominated_rv:
            return self._nominated_cache
        pods = self._api.list(
            KIND_POD,
            filter_fn=lambda p: (p.status.nominated_node_name
                                 and not p.spec.node_name
                                 and p.status.phase == PENDING),
        )
        if rv is not None:
            self._nominated_rv = rv
            self._nominated_cache = pods
        return pods

    # ------------------------------------------------------------------
    # PreFilter extensions (preemption what-if coherence)
    # ------------------------------------------------------------------
    def add_pod(self, state: CycleState, pod_to_schedule: Pod,
                pod_to_add: Pod, node_info: NodeInfo) -> Status:
        snapshot: ElasticQuotaInfos | None = state.get(ELASTIC_QUOTA_SNAPSHOT_KEY)
        if snapshot is None:
            return Status.error("no ElasticQuotaSnapshot in cycle state")
        info = snapshot.get(pod_to_add.metadata.namespace)
        if info is not None:
            info.add_pod_if_not_present(pod_to_add)
        return Status.ok()

    def remove_pod(self, state: CycleState, pod_to_schedule: Pod,
                   pod_to_remove: Pod, node_info: NodeInfo) -> Status:
        snapshot: ElasticQuotaInfos | None = state.get(ELASTIC_QUOTA_SNAPSHOT_KEY)
        if snapshot is None:
            return Status.error("no ElasticQuotaSnapshot in cycle state")
        info = snapshot.get(pod_to_remove.metadata.namespace)
        if info is not None:
            info.delete_pod_if_present(pod_to_remove)
        return Status.ok()

    # ------------------------------------------------------------------
    # Reserve / Unreserve
    # ------------------------------------------------------------------
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        info = self.elastic_quota_infos.get(pod.metadata.namespace)
        if info is not None:
            info.add_pod_if_not_present(pod)
        return Status.ok()

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        info = self.elastic_quota_infos.get(pod.metadata.namespace)
        if info is not None:
            info.delete_pod_if_present(pod)

    # ------------------------------------------------------------------
    # PostFilter: preemption
    # ------------------------------------------------------------------
    def post_filter(self, state: CycleState, pod: Pod,
                    nodes: SharedLister) -> tuple[str, Status]:
        if pod.spec.preemption_policy == "Never":
            return "", Status.unschedulable(
                "not eligible due to preemptionPolicy=Never"
            )
        if PRE_FILTER_STATE_KEY not in state:
            return "", Status.unschedulable("PreFilter was not run")

        # Persistent victim prescreen (ISSUE 18): skip nodes that could
        # not hold the preemptor even fully drained.  The walk's final
        # re-check (`run_filter_plugins` with all victims removed) is
        # unconditional and NodeResourcesFit is monotone in occupancy,
        # so those nodes can never yield a candidate — skipping them is
        # journal-identical.  An empty mask short-circuits the whole
        # PostFilter with the exact journal line the empty-candidates
        # path below would emit.
        mask = self._victim_screen(state, pod, nodes)
        if mask is not None and not mask:
            journal_record(J.PREEMPTION_NONE, pod.key,
                           message="preemption found no candidates")
            return "", Status.unschedulable("preemption found no candidates")

        # PDB statuses are O(namespace pods) to refresh — compute once per
        # PostFilter, not once per candidate node.
        from nos_tpu.api.pdb import (
            KIND_POD_DISRUPTION_BUDGET, refresh_pdb_status,
        )

        pdbs: list = []
        if self._api is not None:
            pdbs = [refresh_pdb_status(self._api, pdb)
                    for pdb in self._api.list(KIND_POD_DISRUPTION_BUDGET)]
        # Gang membership is O(namespace pods) to list — resolve each gang
        # once per PostFilter and share the cache across candidate nodes.
        gang_cache: dict[tuple[str, str], list[Pod]] = {}

        candidates: list[tuple[str, list[Pod], int, set[str]]] = []
        for ni in nodes.list():
            if mask is not None and ni.name not in mask:
                continue
            shrink_uids: set[str] = set()
            victims, num_violating, st = self._select_victims_on_node(
                state, pod, ni, pdbs, gang_cache, shrink_out=shrink_uids)
            if st.is_success and victims:
                # Score and account the TRUE eviction set: gang eviction
                # amplifies cluster-wide, not just on this node — except
                # for elastic SHRINK victims, which die alone by contract.
                full = self._expand_eviction(victims, gang_cache,
                                             shrink_uids)
                candidates.append((ni.name, full, num_violating,
                                   shrink_uids))
        if not candidates:
            journal_record(J.PREEMPTION_NONE, pod.key,
                           message="preemption found no candidates")
            return "", Status.unschedulable("preemption found no candidates")

        best = min(candidates, key=lambda c: self._candidate_key(c[:3]))
        node_name, victims, _, shrink_uids = best
        if self.on_preempt is not None:
            self.on_preempt(pod, victims)
        self._evict_all(victims, shrink_uids)

        REGISTRY.inc("nos_tpu_preemptions_total")
        REGISTRY.inc("nos_tpu_preemption_victims_total", len(victims))
        journal_record(J.PREEMPTION, pod.key, node=node_name,
                       victims=[v.key for v in victims[:MAX_JOURNAL_NODES]],
                       victim_count=len(victims))
        logger.info("preempting %d pod(s) on %s for %s",
                    len(victims), node_name, pod.key)
        return node_name, Status.ok()

    def _victim_screen(self, state: CycleState, pod: Pod,
                       nodes: SharedLister) -> frozenset[str] | None:
        """Names of nodes where `pod` would fit on an EMPTY node — the
        persistent cross-cycle prescreen for the preemption walk.

        Soundness: `_select_victims_on_node` only succeeds after an
        unconditional `run_filter_plugins` re-check with every candidate
        victim removed; the non-victim residue keeps requested >= 0, so
        free <= allocatable and used chips >= 0 — NodeResourcesFit
        failing at zero occupancy implies it fails at any occupancy.  A
        node outside this mask can therefore never produce a candidate,
        and the walk itself journals nothing, so skipping it leaves the
        decision journal byte-identical.

        The mask is a pure function of (request signature, fleet node
        allocatables), cached per signature under the view epoch that
        the scheduler stamps into cycle state only for the real cycle
        lister.  Returns None (screen nothing) when no epoch is present
        — detached plugin use and gang what-if domains take the full
        walk unchanged."""
        epoch = state.get(VIEW_EPOCH_CONTEXT_KEY)
        if epoch is None:
            return None
        req = pod_request(pod)
        sig = tuple(sorted((k, v) for k, v in req.items() if v > 0))
        cached = self._victim_mask_cache.get(sig)
        if cached is not None and cached[0] == epoch:
            return cached[1]
        pod_chips = _slice_chips(req)
        nis = nodes.list()
        # chip capacities are only consulted when the request carries
        # slice chips; the profile parse is the costly part, skip it
        caps = [_slice_chips(ni.allocatable) if pod_chips else 0
                for ni in nis]
        from nos_tpu.device import native
        passing = native.victim_prescreen(
            [[ni.allocatable.get(k, 0.0) for k, _ in sig] for ni in nis],
            [v for _, v in sig], caps, pod_chips)
        if passing is None:
            passing = [fits(req, ni.allocatable)
                       and (pod_chips == 0 or pod_chips <= caps[i])
                       for i, ni in enumerate(nis)]
        mask = frozenset(
            ni.name for ni, ok in zip(nis, passing) if ok)
        if len(self._victim_mask_cache) > 512:
            self._victim_mask_cache.clear()
        self._victim_mask_cache[sig] = (epoch, mask)
        return mask

    def _expand_eviction(self, victims: list[Pod],
                         gang_cache: dict | None = None,
                         shrink_uids: set[str] | None = None) -> list[Pod]:
        """Deduplicated cluster-wide eviction set for a victim list: every
        gang-mate of a victim dies with it (evict_gang) — except shrink
        victims, which leave their gang running one replica smaller."""
        out: list[Pod] = []
        seen: set[str] = set()
        for v in victims:
            for m in self._eviction_set(v, gang_cache, shrink_uids):
                if m.metadata.uid not in seen:
                    seen.add(m.metadata.uid)
                    out.append(m)
        return out

    @staticmethod
    def _candidate_key(
            cand: tuple[str, list[Pod], int]) -> tuple[int, int, int, int, str]:
        """Node choice mirrors upstream pickOneNodeForPreemption: fewest PDB
        violations, lowest max victim priority, lowest priority sum, fewest
        victims, then name."""
        name, victims, num_violating = cand
        priorities = [v.spec.priority for v in victims]
        return (num_violating, max(priorities), sum(priorities),
                len(victims), name)

    def _evict_all(self, victims: list[Pod],
                   shrink_uids: set[str] | None = None) -> None:
        """Evict each gang once: the victim list is already gang-expanded
        (_expand_eviction), and evict_gang deletes every member of a
        victim's group, so per-member calls would re-list and re-delete
        each gang N times.  Shrink victims (elastic dp members above
        their min) are deleted ALONE and the surviving members get the
        dp-resize stamp — the cheaper rung that loses one replica, not
        the run."""
        if self._api is None:
            return
        from nos_tpu.scheduler.elastic import record_shrink
        from nos_tpu.scheduler.gang import evict_gang, gang_name
        evicted_gangs: set[tuple[str, str]] = set()
        shrunk: dict[tuple[str, str], int] = {}
        for v in victims:
            gang = gang_name(v)
            if shrink_uids and v.metadata.uid in shrink_uids and gang:
                key = (v.metadata.namespace, gang)
                try:
                    self._api.delete(KIND_POD, v.metadata.name,
                                     v.metadata.namespace)
                except NotFound:
                    pass
                shrunk[key] = shrunk.get(key, 0) + 1
                continue
            if gang:
                key = (v.metadata.namespace, gang)
                if key in evicted_gangs:
                    continue
                evicted_gangs.add(key)
            evict_gang(self._api, v)
        for (ns, gang), n in sorted(shrunk.items()):
            # a gang BOTH shrunk and whole-evicted in one walk died
            # whole; record_shrink's no-survivors guard keeps the
            # phantom "shrink to 0" out of the journal/metric
            record_shrink(self._api, ns, gang, n)

    def _select_victims_on_node(
            self, state: CycleState, pod: Pod, node_info: NodeInfo,
            pdbs: list | None = None,
            gang_cache: dict | None = None,
            shrink_out: set[str] | None = None
    ) -> tuple[list[Pod], int, Status]:
        """Reference SelectVictimsOnNode (capacity_scheduling.go:468-675),
        run against clones so failed candidates leave no trace.

        Shrink-before-evict (scheduler/elastic.py): members of an
        elastic dp gang above its declared min are the CHEAPEST rung of
        the walk — ordered before even best-effort eviction, and their
        eviction does not amplify to the gang.  Eligibility branches
        are untouched (shrink changes order and amplification only), so
        victim_prescreen's superset contract is preserved.  Selected
        shrink victims' uids are reported through `shrink_out`; at most
        (live members - min) members of one gang shrink per walk, the
        rest fall back to normal whole-gang eviction."""
        base_snapshot: ElasticQuotaInfos = state[ELASTIC_QUOTA_SNAPSHOT_KEY]
        pfs: PreFilterState = state[PRE_FILTER_STATE_KEY]

        # Cheap screen before the what-if clones (victim_prescreen, the
        # shared predicate): a node hosting no possible victim is skipped
        # without paying the snapshot/NodeInfo clone (the preemption
        # storm at v5e-256 scale is dominated by victim-less nodes).
        if not any(victim_prescreen(pod, pv, base_snapshot)
                   for pv in node_info.pods):
            return [], 0, Status.unschedulable("no victims found")

        # Candidate-local what-if copies.
        snapshot = base_snapshot.clone()
        ni = node_info.clone()
        wstate = CycleState(state)
        wstate[ELASTIC_QUOTA_SNAPSHOT_KEY] = snapshot

        pod_req = pfs.pod_req
        nominated_in_eq = pfs.nominated_in_eq_with_req
        nominated_all = pfs.nominated_with_req
        preemptor_info = snapshot.get(pod.metadata.namespace)

        def remove(p: Pod) -> None:
            ni.remove_pod(p)
            self.remove_pod(wstate, pod, p, ni)

        def add(p: Pod) -> None:
            ni.add_pod(p)
            self.add_pod(wstate, pod, p, ni)

        potential: list[Pod] = []
        # Tier-aware victim ordering (docs/serving.md): IN-QUOTA
        # serving pods are never victims — the tier's latency promise
        # would be worthless if an over-quota borrow could reclaim a
        # live inference replica.  A serving pod whose namespace is
        # itself borrowing beyond its min (over-quota label) stays
        # reclaimable like any other borrower: the quota guarantee
        # outranks the tier shield, or a self-applied tier label would
        # capture a lender's min forever (the band-fits-in-min posture
        # in docs/serving.md is what keeps real replicas in-quota).
        # Among the preemptible pods the walk takes shrinkable elastic
        # members first (the cheapest rung: one dp replica, not a run),
        # then best-effort scavengers before batch before (over-quota)
        # serving, then lowest priority first (reference sorts
        # ascending :516).  Excluding in-quota serving only NARROWS
        # selection, so victim_prescreen's superset contract is
        # untouched.
        shrink_left: dict[tuple[str, str], int] = {}

        def _shrink_headroom(pv: Pod) -> int:
            from nos_tpu.scheduler.gang import gang_name as _gname

            g = _gname(pv)
            if not g or elastic_replica_bounds(pv) is None:
                return 0
            key = (pv.metadata.namespace, g)
            if key not in shrink_left:
                from nos_tpu.scheduler.elastic import shrink_headroom
                members = self._eviction_set(pv, gang_cache)
                shrink_left[key] = shrink_headroom(
                    [m for m in members
                     if m.status.phase in (PENDING, RUNNING)])
            return shrink_left[key]

        def _take_shrink(pv: Pod) -> bool:
            """Consume one unit of the victim's gang shrink budget."""
            if _shrink_headroom(pv) <= 0:
                return False
            shrink_left[(pv.metadata.namespace,
                         gang_name(pv))] -= 1
            if shrink_out is not None:
                shrink_out.add(pv.metadata.uid)
            return True

        from nos_tpu.scheduler.gang import gang_name

        # Restart-cost-aware walk for a DISPLACED preemptor
        # (docs/scheduler.md): when the pod making room is itself a
        # node-loss/migration victim, equal-tier equal-priority victims
        # are walked least-job-progress first — evicting a fresh job
        # loses nothing, evicting a nearly-done one wastes its whole
        # run, and the displaced gang already lost one run.  Gated on
        # the preemptor's displacement stamp so every non-displaced
        # walk stays byte-identical; eligibility branches are untouched
        # (order only), preserving victim_prescreen's superset
        # contract.  "Displaced" is the admission queue's definition
        # (is_displaced_fresh): a stamp past the age cap lost its
        # head-of-line slot, so it must not keep the altered victim
        # order either, and a serving preemptor never had the slot.
        disp_now, disp_cap = state.get(DISPLACED_CONTEXT_KEY,
                                       (0.0, 0.0))
        displaced_preemptor = is_displaced_fresh(pod, disp_now,
                                                 disp_cap)

        def _restart_cost(p: Pod) -> float:
            return job_progress(p) if displaced_preemptor else 0.0

        node_pods = sorted(
            (p for p in ni.pods
             if workload_tier(p) != C.TIER_SERVING
             or is_over_quota(p)),
            key=lambda p: (0 if _shrink_headroom(p) > 0 else 1,
                           -tier_rank(p), p.spec.priority,
                           _restart_cost(p),
                           -p.metadata.creation_timestamp))
        def select(pv: Pod) -> None:
            """Take `pv` as a potential victim, consuming its gang's
            shrink budget when available (the uid lands in shrink_out
            so eviction will not gang-amplify it)."""
            _take_shrink(pv)
            potential.append(pv)
            remove(pv)

        if preemptor_info is not None:
            more_than_min = preemptor_info.used_over_min_with(nominated_in_eq)
            for pv in node_pods:
                pv_info = snapshot.get(pv.metadata.namespace)
                if pv_info is None:
                    continue
                if more_than_min:
                    # Preemptor would run over-quota: same-namespace
                    # lower-priority victims...
                    if pv.metadata.namespace == pod.metadata.namespace:
                        if pv.spec.priority < pod.spec.priority:
                            select(pv)
                        continue
                    # ...or cross-namespace over-quota pods, but only while
                    # the preemptor stays within min + its guaranteed share
                    # of the aggregate unused min, and the victim's quota
                    # exceeds its own guaranteed share (:547-564).
                    if not is_over_quota(pv):
                        continue
                    g = snapshot.get_guaranteed_overquotas(pod.metadata.namespace)
                    min_plus_g = sum_resources(g, preemptor_info.min)
                    if preemptor_info.used_lte_with(min_plus_g, nominated_in_eq):
                        pv_g = snapshot.get_guaranteed_overquotas(
                            pv.metadata.namespace)
                        pv_min_plus_g = sum_resources(pv_g, pv_info.min)
                        if pv_info.used_over(pv_min_plus_g):
                            select(pv)
                else:
                    # Preemptor within min: its guaranteed quota is borrowed
                    # elsewhere — only cross-namespace over-quota-labelled
                    # pods from borrowing quotas are eligible (:566-581).
                    if pv.metadata.namespace != pod.metadata.namespace \
                            and pv_info.used_over_min() and is_over_quota(pv):
                        select(pv)
        else:
            # Preemptor not governed by any quota: classic priority
            # preemption among quota-less pods (:583-596).
            for pv in node_pods:
                if snapshot.get(pv.metadata.namespace) is not None:
                    continue
                if pv.spec.priority < pod.spec.priority:
                    select(pv)

        if not potential:
            return [], 0, Status.unschedulable("no victims found")

        fw = self._framework
        if fw is None:
            return [], 0, Status.error("framework handle not set")
        if not fw.run_filter_plugins(wstate, pod, ni).is_success:
            return [], 0, Status.unschedulable(
                "pod does not fit even after removing all candidates")
        if preemptor_info is not None:
            if preemptor_info.used_over_max_with(pod_req):
                return [], 0, Status.unschedulable("max quota exceeded")
            if snapshot.aggregated_used_over_min_with(pod_req):
                return [], 0, Status.unschedulable("total min quota exceeded")

        # Reprieve as many victims as possible (:626-673): split potential
        # victims by PodDisruptionBudget violation and try to reprieve the
        # violating ones FIRST (capacity is freest at the start of the
        # walk, minimising PDB violations); victims that stay despite
        # violating a budget are counted for the node-choice tiebreak.
        violating, non_violating = self._split_pdb_violation(
            potential, pdbs, gang_cache, shrink_out)
        victims: list[Pod] = []
        num_violating = 0

        def reprieve(pv: Pod) -> bool:
            add(pv)
            fits = fw.run_filter_plugins(wstate, pod, ni).is_success
            over_quota = preemptor_info is not None and (
                preemptor_info.used_over_max_with(nominated_in_eq)
                or snapshot.aggregated_used_over_min_with(nominated_all)
            )
            if not fits or over_quota:
                remove(pv)
                victims.append(pv)
                return False
            return True

        # Reprieve order is the WALK order inverted: candidates from
        # the most-protected remaining tier (batch before best-effort)
        # and highest priority get their capacity back first, so the
        # victims that actually die are the scavengers — without the
        # tier key here the reprieve pass silently undoes the
        # tier-ordered walk above.  Shrink victims reprieve LAST for
        # the same reason: they are the cheapest rung, so they must be
        # the last deaths undone.  The displaced-preemptor restart-cost
        # key mirrors into the reprieve too (most-progress reprieved
        # first), or the reprieve would silently undo the
        # least-progress-first walk exactly like the tier key story.
        _shrunk = shrink_out or set()
        by_prio = lambda p: (p.metadata.uid in _shrunk,  # noqa: E731
                             tier_rank(p), -p.spec.priority,
                             -_restart_cost(p),
                             p.metadata.creation_timestamp)
        for pv in sorted(violating, key=by_prio):
            if not reprieve(pv):
                num_violating += 1
        for pv in sorted(non_violating, key=by_prio):
            reprieve(pv)

        # Gang coherence: a reprieved candidate whose gang-mate stayed a
        # victim dies anyway at eviction time (evict_gang is all-or-nothing)
        # — fold it back into the victim set so the PDB-violation count and
        # the node-choice key reflect the true eviction set.  SHRINK
        # victims never doom their gang (they die alone by contract), so
        # they contribute nothing here.
        doomed_gangs = {(v.metadata.namespace, gang_name(v))
                        for v in victims
                        if gang_name(v) and v.metadata.uid not in _shrunk}
        if doomed_gangs:
            victim_uids = {v.metadata.uid for v in victims}
            violating_uids = {p.metadata.uid for p in violating}
            for pv in potential:
                if pv.metadata.uid in victim_uids:
                    continue
                g = gang_name(pv)
                if g and (pv.metadata.namespace, g) in doomed_gangs:
                    remove(pv)
                    victims.append(pv)
                    victim_uids.add(pv.metadata.uid)
                    if pv.metadata.uid in violating_uids:
                        num_violating += 1
        return victims, num_violating, Status.ok()

    def _eviction_set(self, victim: Pod,
                      cache: dict | None = None,
                      shrink_uids: set[str] | None = None) -> list[Pod]:
        """The amplification set of evicting `victim`: gang eviction is
        all-or-nothing (gang.evict_gang deletes every member), so the whole
        group is disrupted, wherever its members run — EXCEPT a shrink
        victim (elastic dp member above min), which is disrupted alone.
        `cache` memoises the O(namespace pods) membership list per
        (namespace, gang)."""
        from nos_tpu.scheduler.gang import gang_name

        g = gang_name(victim)
        if not g or self._api is None \
                or (shrink_uids is not None
                    and victim.metadata.uid in shrink_uids):
            return [victim]
        key = (victim.metadata.namespace, g)
        members = cache.get(key) if cache is not None else None
        if members is None:
            members = self._api.list(
                KIND_POD, namespace=victim.metadata.namespace,
                label_selector={C.LABEL_POD_GROUP: g})
            if cache is not None:
                cache[key] = members
        if not any(m.metadata.uid == victim.metadata.uid for m in members):
            members = [victim] + members
        return members

    def _split_pdb_violation(
            self, pods: list[Pod], pdbs: list | None,
            gang_cache: dict | None = None,
            shrink_uids: set[str] | None = None
    ) -> tuple[list[Pod], list[Pod]]:
        """filterPodsWithPDBViolation analog, gang-aware: evicting a gang
        member evicts its whole group, so budget accounting charges every
        RUNNING member of the candidate's eviction set — a candidate
        violates when any matching budget lacks allowance for the full
        amplification set, not just the candidate itself (prior same-walk
        victims consume budget; a member already charged in this walk is
        not re-charged).  Non-running members never consume budget, matching
        the healthy-pod accounting of refresh_pdb_status."""
        from nos_tpu.api.pdb import (
            KIND_POD_DISRUPTION_BUDGET, refresh_pdb_status,
        )

        if pdbs is None:
            pdbs = []
            if self._api is not None:
                pdbs = [refresh_pdb_status(self._api, pdb)
                        for pdb in self._api.list(
                            KIND_POD_DISRUPTION_BUDGET)]
        if not pdbs:
            return [], list(pods)
        allowed = [pdb.status.disruptions_allowed for pdb in pdbs]
        charged: set[tuple[int, str]] = set()
        violating: list[Pod] = []
        non_violating: list[Pod] = []
        for pod in pods:
            needed: dict[int, list[str]] = {}
            for m in self._eviction_set(pod, gang_cache, shrink_uids):
                if m.status.phase != RUNNING:
                    continue  # only healthy pods consume disruption budget
                for i, pdb in enumerate(pdbs):
                    if pdb.matches(m) and (i, m.metadata.uid) not in charged:
                        needed.setdefault(i, []).append(m.metadata.uid)
            if any(allowed[i] < len(uids) for i, uids in needed.items()):
                violating.append(pod)
                continue
            for i, uids in needed.items():
                allowed[i] -= len(uids)
                charged.update((i, u) for u in uids)
            non_violating.append(pod)
        return violating, non_violating
