"""Scheduler framework: plugin pipeline + NodeInfo snapshots.

The reference embeds the *real* kube-scheduler framework in-process and runs
its PreFilter/Filter pipeline against hypothetical node states — both inside
the partitioning planner (reference internal/partitioning/core/planner.go:178-207,
wired with a fake shared lister at cmd/gpupartitioner/gpupartitioner.go:294-318)
and as the actual scheduler (cmd/scheduler).  This module is our equivalent
framework: the same object serves (a) the planner's what-if simulation and
(b) the real scheduling loop in the simulator — exactly the reference's trick
of production code reusing the test fake (SURVEY.md §3.5).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Iterable, Protocol, runtime_checkable

from nos_tpu.api.constants import (
    LABEL_POD_GROUP, is_migration_drain, is_warm_spare_labels,
)
from nos_tpu.kube.objects import Node, Pod
from nos_tpu.kube.resources import (
    ResourceList, fits, pod_request, subtract, sum_resources,
)
from nos_tpu.obs.trace import (
    bump as obs_bump, get_tracer as obs_tracer, span as obs_span)

# ---------------------------------------------------------------------------
# Status codes
# ---------------------------------------------------------------------------

SUCCESS = "Success"
UNSCHEDULABLE = "Unschedulable"
ERROR = "Error"


@dataclass
class Status:
    code: str = SUCCESS
    message: str = ""
    # Machine-readable rejection class (e.g. "quota" from
    # CapacityScheduling) — lets the scheduler react to WHY a pod is
    # unschedulable without parsing messages.  "" = unclassified.
    reason: str = ""
    # Name of the plugin that produced a non-success verdict (set by the
    # Framework runners) — the decision journal's "rejected by plugin P"
    # provenance.  "" = success or framework-level verdict.
    plugin: str = ""

    @property
    def is_success(self) -> bool:
        return self.code == SUCCESS

    @staticmethod
    def ok() -> "Status":
        return Status(SUCCESS)

    @staticmethod
    def unschedulable(msg: str, reason: str = "") -> "Status":
        return Status(UNSCHEDULABLE, msg, reason)

    @staticmethod
    def error(msg: str) -> "Status":
        return Status(ERROR, msg)


# ---------------------------------------------------------------------------
# NodeInfo
# ---------------------------------------------------------------------------


@dataclass
class NodeInfo:
    """In-memory scheduling view of one node (the framework.NodeInfo analog).
    `allocatable` includes extended resources; partitioning strategies mutate
    it when simulating hypothetical geometries (reference
    pkg/gpu/mig/node.go:171-195 recomputing ScalarResources)."""

    node: Node
    pods: list[Pod] = field(default_factory=list)
    requested: ResourceList = field(default_factory=dict)

    @property
    def name(self) -> str:
        return self.node.metadata.name

    @property
    def allocatable(self) -> ResourceList:
        return self.node.status.allocatable

    def free(self) -> ResourceList:
        return subtract(self.allocatable, self.requested)

    def add_pod(self, pod: Pod) -> None:
        self.pods.append(pod)
        self.requested = sum_resources(self.requested, pod_request(pod))

    def remove_pod(self, pod: Pod) -> bool:
        for i, p in enumerate(self.pods):
            if p.metadata.uid == pod.metadata.uid or p.key == pod.key:
                self.pods.pop(i)
                self.requested = subtract(self.requested, pod_request(p))
                return True
        return False

    def clone(self) -> "NodeInfo":
        # structural copy (FastCopy) without the copy.deepcopy dispatch
        # prologue: NodeInfo.clone runs per candidate in gang what-ifs
        # and per COW fork in the planner
        return NodeInfo(
            node=self.node.__deepcopy__({}),
            pods=list(self.pods),
            requested=dict(self.requested),
        )


def filter_equivalence_key(pod: Pod) -> tuple:
    """Equivalence class of a pod under the in-tree Filter pipeline: the
    verdict is a pure function of (namespace, gang, request) against
    fixed node state — quota checks live entirely in PreFilter.  Shared
    by the scheduler's per-cycle Filter memo and the planner's per-fork
    memo; a future Filter plugin consulting any OTHER pod attribute must
    extend this key, or the memos return verdicts for the wrong class."""
    return (pod.metadata.namespace,
            pod.metadata.labels.get(LABEL_POD_GROUP, ""),
            frozenset(pod_request(pod).items()))


# ---------------------------------------------------------------------------
# Cycle state
# ---------------------------------------------------------------------------


class CycleState(dict):
    """Per-scheduling-cycle scratch space shared across plugins."""


# ---------------------------------------------------------------------------
# Plugin protocols
# ---------------------------------------------------------------------------


@runtime_checkable
class PreFilterPlugin(Protocol):
    name: str

    def pre_filter(self, state: CycleState, pod: Pod,
                   nodes: "SharedLister") -> Status: ...


@runtime_checkable
class FilterPlugin(Protocol):
    name: str

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status: ...


@runtime_checkable
class PostFilterPlugin(Protocol):
    name: str

    def post_filter(self, state: CycleState, pod: Pod,
                    nodes: "SharedLister") -> tuple[str, Status]: ...


@runtime_checkable
class ReservePlugin(Protocol):
    name: str

    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status: ...

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None: ...


@runtime_checkable
class PreFilterExtensions(Protocol):
    """AddPod/RemovePod extensions keeping cycle-state snapshots coherent
    during preemption what-ifs (reference capacity_scheduling.go:286-321)."""

    def add_pod(self, state: CycleState, pod_to_schedule: Pod,
                pod_to_add: Pod, node_info: NodeInfo) -> Status: ...

    def remove_pod(self, state: CycleState, pod_to_schedule: Pod,
                   pod_to_remove: Pod, node_info: NodeInfo) -> Status: ...


# ---------------------------------------------------------------------------
# Shared lister (the fake-shared-lister trick)
# ---------------------------------------------------------------------------


class SharedLister:
    """Holds the NodeInfo snapshot the framework schedules against.  The
    planner passes explicit hypothetical snapshots (reference
    pkg/test/util/fake.go:38-251, reused by production)."""

    def __init__(self, node_infos: Iterable[NodeInfo] = ()) -> None:
        self._infos: dict[str, NodeInfo] = {ni.name: ni for ni in node_infos}

    def list(self) -> list[NodeInfo]:
        return list(self._infos.values())

    def get(self, name: str) -> NodeInfo | None:
        return self._infos.get(name)

    def set(self, ni: NodeInfo) -> None:
        self._infos[ni.name] = ni


# ---------------------------------------------------------------------------
# Built-in plugin: NodeResourcesFit
# ---------------------------------------------------------------------------


def _slice_chips(resources: ResourceList) -> int:
    """Total chip-equivalents across the slice profile resources."""
    from nos_tpu.topology.profile import extract_slice_requests

    return sum(shape.chips * qty
               for shape, qty in extract_slice_requests(resources).items())


class NodeResourcesFit:
    """The in-tree fit plugin: pod request must fit node free capacity.

    For slice resources the per-profile check alone is unsound while a
    repartition is in flight: a bound pod whose profile was re-carved
    away no longer subtracts from ANY advertised profile, so per-profile
    free looks positive while the node's chips are spoken for.  The
    aggregate chip-equivalent guard closes that window — a node can
    never be bound past its carved chip capacity, whatever the current
    geometry says per profile."""

    name = "NodeResourcesFit"

    def filter(self, state: CycleState, pod: Pod, node_info: NodeInfo) -> Status:
        req = pod_request(pod)
        if fits(req, node_info.free()):
            pod_chips = _slice_chips(req)
            if pod_chips:
                cap = _slice_chips(node_info.allocatable)
                used = _slice_chips(node_info.requested)
                if used + pod_chips > cap:
                    return Status.unschedulable(
                        f"insufficient slice chips ({used}+{pod_chips} "
                        f"over {cap}; geometry in flux)")
            return Status.ok()
        missing = [
            k for k, v in req.items()
            if v > 0 and node_info.free().get(k, 0.0) < v
        ]
        return Status.unschedulable(
            f"insufficient {', '.join(sorted(missing))}"
        )


# ---------------------------------------------------------------------------
# Built-in plugin: SpareGuard
# ---------------------------------------------------------------------------


class SpareGuard:
    """A host labeled ``nos.tpu/spare: "warm"`` is a pre-carved warm
    replacement (docs/scheduler.md, "Self-healing node-loss recovery"):
    it accepts NO pods until the spare policy promotes it by removing
    the label.  Registered as a plain Filter so every placement path —
    the cycle loop, gang what-ifs, preemption what-ifs, the elastic
    grow probe — respects the hold without per-call-site checks.  With
    no spare labels in the cluster the plugin rejects nothing and every
    decision (and journal message) is byte-identical to a build without
    it.  Runs AFTER NodeResourcesFit so the native prescreen's
    exact-message contract (native_filter.py `message_exact`) is
    untouched."""

    name = "SpareGuard"

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        if is_warm_spare_labels(node_info.node.metadata.labels):
            return Status.unschedulable("node held as warm spare")
        return Status.ok()


class MigrationDrainGuard:
    """A node being drain-migrated (``nos.tpu/defrag-drain`` with a
    ``migrate:`` value — stamped by partitioning/core/failure.py on a
    suspect or maintenance host) accepts no NEW pods: its agent is
    presumed dying, so anything bound there would be admitted by
    nobody and lost with the host.  This is deliberately HARDER than a
    defrag drain (same annotation, proposal-id value), which stays a
    soft score-key avoidance — a defrag'd host is healthy and refusing
    it outright would shrink the fleet for a mere optimization.  With
    no migration drains the plugin rejects nothing: decisions are
    byte-identical to a build without it."""

    name = "MigrationDrainGuard"

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        if is_migration_drain(node_info.node.metadata.annotations):
            return Status.unschedulable(
                "node draining for migration")
        return Status.ok()


# ---------------------------------------------------------------------------
# Framework
# ---------------------------------------------------------------------------


class Framework:
    """Ordered plugin runner (the schedulerruntime.NewFramework analog).

    Extension-point membership is resolved ONCE at construction into
    dispatch tables: a runtime-checkable Protocol isinstance walks every
    protocol attribute per call, and with Filter running per pod x node
    in both the scheduler and the planner simulation it dominated the
    v5e-256 plan wall time (55% of the profile).  The plugin list is
    fixed at construction, so the capability check cannot go stale."""

    def __init__(self, plugins: Iterable[object] = ()) -> None:
        self._plugins = list(plugins) or [NodeResourcesFit()]
        self._lock = threading.RLock()
        self._pre_filter = [
            p for p in self._plugins
            if isinstance(p, PreFilterPlugin) and hasattr(p, "pre_filter")]
        self._filter = [
            p for p in self._plugins
            if isinstance(p, FilterPlugin) and hasattr(p, "filter")]
        self._post_filter = [
            p for p in self._plugins
            if isinstance(p, PostFilterPlugin) and hasattr(p, "post_filter")]
        self._extensions = [
            p for p in self._plugins
            if isinstance(p, PreFilterExtensions) and hasattr(p, "add_pod")]
        self._reserve = [
            p for p in self._plugins
            if isinstance(p, ReservePlugin) and hasattr(p, "reserve")]

    @property
    def plugins(self) -> list[object]:
        return list(self._plugins)

    @property
    def filter_chain(self) -> list[object]:
        """The Filter dispatch table in run order — the native
        prescreen (scheduler/native_filter.py) gates its soundness
        levels on this chain's shape."""
        return list(self._filter)

    def run_pre_filter_plugins(self, state: CycleState, pod: Pod,
                               nodes: SharedLister) -> Status:
        obs_bump("prefilter_runs")
        if not self._pre_filter:
            # planner frameworks typically register no PreFilter plugin;
            # skip the lock round-trip on the per-pod x candidate path
            return Status.ok()
        with self._lock:
            for p in self._pre_filter:
                st = p.pre_filter(state, pod, nodes)
                if not st.is_success:
                    st.plugin = getattr(p, "name", type(p).__name__)
                    return st
            return Status.ok()

    def run_filter_plugins(self, state: CycleState, pod: Pod,
                           node_info: NodeInfo) -> Status:
        # one counter bump on the enclosing span in every mode (cheap:
        # Filter runs per pod x node in both the scheduler and the
        # planner simulation, and explain/troubleshooting read the
        # reverts/filter_runs ratio); detailed tracers additionally get
        # a real child span per pipeline run
        obs_bump("filter_runs")
        tracer = obs_tracer()
        if tracer.detailed and tracer.enabled:
            with tracer.span("framework.filter", pod=pod.key,
                             node=node_info.name) as sp:
                st = self._filter_pipeline(state, pod, node_info)
                if not st.is_success:
                    sp.set("plugin", st.plugin)
                    sp.set("reason", st.message)
                return st
        return self._filter_pipeline(state, pod, node_info)

    def _filter_pipeline(self, state: CycleState, pod: Pod,
                         node_info: NodeInfo) -> Status:
        with self._lock:
            for p in self._filter:
                st = p.filter(state, pod, node_info)
                if not st.is_success:
                    st.plugin = getattr(p, "name", type(p).__name__)
                    return st
            return Status.ok()

    def run_post_filter_plugins(self, state: CycleState, pod: Pod,
                                nodes: SharedLister) -> tuple[str, Status]:
        with obs_span("framework.post_filter", pod=pod.key):
            with self._lock:
                for p in self._post_filter:
                    nominated, st = p.post_filter(state, pod, nodes)
                    if st.is_success:
                        return nominated, st
                return "", Status.unschedulable(
                    "no postfilter plugin succeeded")

    def run_pre_filter_extension_add_pod(
            self, state: CycleState, pod_to_schedule: Pod, pod_to_add: Pod,
            node_info: NodeInfo) -> Status:
        """Book a hypothetically-placed pod into every plugin's cycle-state
        snapshot (reference capacity_scheduling.go:286-302) — used by
        preemption what-ifs and gang placement."""
        with self._lock:
            for p in self._extensions:
                st = p.add_pod(state, pod_to_schedule, pod_to_add,
                               node_info)
                if not st.is_success:
                    return st
            return Status.ok()

    def run_reserve_plugins(self, state: CycleState, pod: Pod,
                            node_name: str) -> Status:
        with self._lock:
            for p in self._reserve:
                st = p.reserve(state, pod, node_name)
                if not st.is_success:
                    return st
            return Status.ok()

    def run_unreserve_plugins(self, state: CycleState, pod: Pod,
                              node_name: str) -> None:
        with self._lock:
            for p in self._reserve:
                if hasattr(p, "unreserve"):
                    p.unreserve(state, pod, node_name)
