"""Watch-driven scheduler cluster view — the kube-scheduler cache analog.

`Scheduler.snapshot()` used to rebuild every NodeInfo from a full
`api.list` scan of nodes AND pods once per cycle (one deep copy of the
whole store per cycle; BENCH_r05: 42.7 ms p50 / 96.3 ms p99 per cycle on
the v5e-256 trace).  This cache subscribes to Node/Pod watch events
(kube/client.py Informer) and maintains the view incrementally:

- the latest Node object and the bound active pods per node are kept in
  watch-updated indexes;
- every event touching a node (bind, evict/delete, phase change,
  geometry/annotation/label write) bumps that node's generation counter;
- `snapshot()` rebuilds the NodeInfo for exactly the nodes whose
  generation moved and reuses the cached object for every other node.

The incremental decision plane (ISSUE 18) layers three more watch-fed
views on the same event stream:

- a fleet-wide **view epoch** (`view_epoch()`), bumped with every node
  generation bump, that lets `snapshot()` return the SAME SharedLister
  object across cycles where nothing moved — and lets downstream memos
  (waste waterfall, victim prescreen masks) key their validity on one
  integer instead of re-deriving from the fleet;
- a **dirty set** (`drain_dirty()`): the node names bumped since the
  last drain.  ``None`` means "everything" (initial state, or after
  `invalidate_all()`), which is the level-triggered backstop's escape
  hatch — the scheduler maps it to a full rescan;
- a **pending-pod index** (`pending_pods()`) and a **gang-key set**
  (`has_gang_pods()`), so the cycle's work list and the elastic-grow
  gate stop paying an O(store) deep-copy `list()` per cycle.

Coherence with the assume cache: the scheduler mutates a cycle
snapshot's NodeInfos in place when it assumes a just-bound pod
(`Scheduler._assume_bound`).  Every such mutation is paired with an API
write (the bind patch) whose watch event has ALREADY bumped the node's
generation — the watch bus is synchronous — so the next `snapshot()`
call rebuilds that node from store state and the in-place mutation never
leaks into a later cycle.

Under the chaos substrate, dropped watch events leave the view stale
until the chaos replay redelivers them at current state — the same
staleness window a real informer has across a stream reconnect; the
scheduler already tolerates it (binds are re-validated by admission),
and the periodic full-rescan backstop re-levels the dirty set.
"""

from __future__ import annotations

import threading

from nos_tpu.api.constants import LABEL_POD_GROUP
from nos_tpu.kube.client import APIServer, Informer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import Node, PENDING, Pod, RUNNING
from nos_tpu.scheduler.framework import NodeInfo, SharedLister
from nos_tpu.utils.guards import guarded_by, invalidated_by


@guarded_by("_lock", "_node_objs", "_pods_by_node", "_pod_node",
            "_gen", "_built", "_epoch", "_dirty", "_snap",
            "_pending", "_gang_keys")
@invalidated_by("_bump_locked", "_node_objs", "_pods_by_node", "_pod_node")
class SchedulerCache:
    """Every index is written on watch fan-out threads AND read by the
    scheduling loop: the @guarded_by declaration is checked statically
    (noslint N010) and at soak time (lockcheck.guard_state).  The
    @invalidated_by declaration certifies the generation protocol
    (noslint N012): every in-place mutation of the node/pod indexes is
    post-dominated by a _bump_locked emission, so snapshot()'s
    generation-gated NodeInfo reuse can never serve a stale build.
    The epoch/dirty/snapshot/pending views are derived state keyed on
    that same emission (every `_bump_locked` advances them in the same
    critical section), not independently mutated sources — they ride
    the declared protocol rather than extending it."""

    def __init__(self, api: APIServer) -> None:
        self._lock = threading.Lock()
        # node objects live in the cache's OWN index, written in the
        # same critical section as the generation bump: snapshot() must
        # read (object, generation) atomically, or a concurrent node
        # write between the two reads would tag a NodeInfo built from
        # the stale object with the NEW generation — consuming the very
        # signal meant to invalidate it
        self._node_objs: dict[str, object] = {}
        # pods are indexed independently of node existence: a pod bound
        # to a node the cache has not seen yet (watch registration
        # races, replacement hosts) is picked up on the node's first
        # NodeInfo build
        self._pods_by_node: dict[str, dict[str, Pod]] = {}
        self._pod_node: dict[str, str] = {}
        self._gen: dict[str, int] = {}
        self._built: dict[str, tuple[int, NodeInfo]] = {}
        # fleet-wide view epoch: moves with every per-node bump, so one
        # integer comparison certifies "nothing in the fleet changed"
        self._epoch = 0
        # dirty node names since the last drain; None = everything is
        # dirty (initial state and after invalidate_all) so the first
        # cycle and the backstop both take the full-rescan path
        self._dirty: set[str] | None = None
        # epoch-gated snapshot reuse: the same SharedLister object is
        # handed back while the epoch stands still, so a clean cycle
        # costs zero NodeInfo list rebuilds
        self._snap: tuple[int, SharedLister] | None = None
        # pending (unbound) pods and gang-labeled pod keys, maintained
        # from the same pod stream: the cycle work list without a full
        # store scan.  Watch delivery hands this cache its own deep
        # copies, so the stored objects are private to it.
        self._pending: dict[str, Pod] = {}
        self._gang_keys: set[str] = set()
        # hook order matters: the pod handler reads these indexes, so
        # they exist before the informers replay their initial ADDEDs;
        # store=False — this cache IS the store, a second copy per object
        # on the synchronous watch path would buy nothing
        self._nodes = Informer(api, KIND_NODE, on_event=self._on_node,
                               store=False)
        self._pods = Informer(api, KIND_POD, on_event=self._on_pod,
                              store=False)

    # -- watch handlers (fire on the API server's synchronous bus) ----------
    # the _locked suffix is load-bearing: noslint N010 certifies
    # that every caller already holds the cache lock
    def _bump_locked(self, node_name: str) -> None:
        self._gen[node_name] = self._gen.get(node_name, 0) + 1
        self._epoch += 1
        if self._dirty is not None:
            self._dirty.add(node_name)

    def _on_node(self, event: str, node: Node) -> None:
        name = node.metadata.name
        with self._lock:
            if event == "DELETED":
                self._node_objs.pop(name, None)
                self._built.pop(name, None)
            else:
                self._node_objs[name] = node
            self._bump_locked(name)

    def _on_pod(self, event: str, pod: Pod) -> None:
        key = pod.key
        tracked = (event != "DELETED" and bool(pod.spec.node_name)
                   and pod.status.phase in (PENDING, RUNNING))
        pending = (event != "DELETED" and not pod.spec.node_name
                   and pod.status.phase == PENDING)
        gang = (event != "DELETED"
                and bool(pod.metadata.labels.get(LABEL_POD_GROUP)))
        with self._lock:
            if pending:
                self._pending[key] = pod
            else:
                self._pending.pop(key, None)
            if gang:
                self._gang_keys.add(key)
            else:
                self._gang_keys.discard(key)
            prev = self._pod_node.get(key)
            if prev is not None and (not tracked
                                     or prev != pod.spec.node_name):
                self._pods_by_node.get(prev, {}).pop(key, None)
                del self._pod_node[key]
                self._bump_locked(prev)
            if tracked:
                node_name = pod.spec.node_name
                self._pods_by_node.setdefault(node_name, {})[key] = pod
                self._pod_node[key] = node_name
                self._bump_locked(node_name)

    def assume(self, pod: Pod) -> None:
        """Book a just-bound pod straight into the cache indexes.

        On a synchronous bus (in-memory APIServer) this is idempotent
        with the bind event that already arrived.  On an asynchronous
        substrate (kube/rest.py pumps Node and Pod streams from separate
        threads) the bind's own pod event may LAG a node event: a
        rebuild triggered by that node event would resurrect the
        pre-bind view — phantom free capacity — unless the assumed pod
        is already in the index.  The eventual pod event overwrites the
        same key, so the two paths converge."""
        node_name = pod.spec.node_name
        with self._lock:
            self._pending.pop(pod.key, None)
            self._pods_by_node.setdefault(node_name, {})[pod.key] = pod
            self._pod_node[pod.key] = node_name
            self._bump_locked(node_name)

    # -- incremental-cycle feeds --------------------------------------------
    def drain_dirty(self) -> frozenset[str] | None:
        """Node names bumped since the last drain, then reset the set.
        ``None`` means everything is dirty (first drain, or after
        `invalidate_all()`) — the caller must full-rescan."""
        with self._lock:
            dirty = self._dirty
            self._dirty = set()
            return None if dirty is None else frozenset(dirty)

    def invalidate_all(self) -> None:
        """Level-trigger: forget all incremental state.  The next
        `drain_dirty()` returns None and the next `snapshot()` rebuilds;
        the periodic backstop and test harnesses call this."""
        with self._lock:
            self._dirty = None
            self._snap = None
            self._epoch += 1

    def view_epoch(self) -> int:
        """Fleet-wide change counter: equal epochs certify that no node
        or bound-pod event landed in between (memo-key material)."""
        with self._lock:
            return self._epoch

    def pending_pods(self) -> list[Pod]:
        """The unbound PENDING pods, from the watch-maintained index —
        no store scan, no deep copies.  Callers treat the objects as
        read-only (they are this cache's private watch copies)."""
        with self._lock:
            return list(self._pending.values())

    def has_gang_pods(self) -> bool:
        """Whether any live pod carries the gang (pod-group) label —
        the elastic-grow no-op gate."""
        with self._lock:
            return bool(self._gang_keys)

    # -- the per-cycle snapshot ---------------------------------------------
    def snapshot(self) -> SharedLister:
        """A SharedLister over the current view.  NodeInfos for
        unchanged nodes are the SAME objects as the previous snapshot
        (generation-gated reuse); changed nodes are rebuilt from the
        watch-maintained node/pod records.  While the view epoch stands
        still the SAME SharedLister object is returned, so a clean
        cycle pays one integer compare instead of an O(nodes) rebuild."""
        with self._lock:
            if self._snap is not None and self._snap[0] == self._epoch:
                return self._snap[1]
            infos = []
            for name, node in self._node_objs.items():
                gen = self._gen.get(name, 0)
                cached = self._built.get(name)
                if cached is None or cached[0] != gen:
                    ni = NodeInfo(node=node)
                    for pod in self._pods_by_node.get(name, {}).values():
                        ni.add_pod(pod)
                    cached = (gen, ni)
                    self._built[name] = cached
                infos.append(cached[1])
            lister = SharedLister(infos)
            self._snap = (self._epoch, lister)
            return lister

    def close(self) -> None:
        self._nodes.close()
        self._pods.close()
