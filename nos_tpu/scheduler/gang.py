"""Gang scheduling: all-or-nothing PodGroup admission with ICI topology.

New for the TPU build (SURVEY.md §7 step 6 — no reference analog): a
multi-host JAX job is useless partially placed, so its pods must bind
together onto hosts sharing one physical TPU pod's ICI domain.

Mechanics (the analog of the coscheduling plugin's Permit-stage holding,
recast for this framework's synchronous scheduler): the scheduler groups
pending pods by the `nos.tpu/pod-group` label and simulates placing the
WHOLE gang on a cloned cluster snapshot — each member consumes capacity the
next member sees.  Only if every member fits does anything bind; otherwise
every member is marked unschedulable, which feeds the partitioner's batcher
with the gang's full demand at once (so the planner carves for the whole
job, not one pod).

Topology: the scheduler tries one candidate physical pod (`nos.tpu/pod-id`
ICI domain) at a time, best-fit first — the pod with the LEAST free
capacity that still holds the whole gang — so large pods stay whole for
large gangs; the TopologyFilter rejects hosts outside the pinned domain,
keeping the gang's collectives on ICI rather than DCN.
"""

from __future__ import annotations

import logging
from typing import Any

from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_POD_GROUP, NotFound
from nos_tpu.kube.objects import Pod
from nos_tpu.scheduler.framework import (
    CycleState, NodeInfo, SharedLister, Status,
)
from nos_tpu.topology.shape import Shape

logger = logging.getLogger(__name__)

GANG_POD_ID_KEY = "gang-pinned-pod-id"


def gang_name(pod: Pod) -> str:
    return pod.metadata.labels.get(C.LABEL_POD_GROUP, "")


def get_pod_group(api: APIServer, name: str, namespace: str) -> Any:
    try:
        return api.get(KIND_POD_GROUP, name, namespace)
    except NotFound:
        return None


def set_pod_group_status(api: APIServer, pg: Any, phase: str,
                         scheduled: int) -> None:
    def mutate(o: Any) -> None:
        o.status.phase = phase
        o.status.scheduled = scheduled

    try:
        from nos_tpu.utils.retry import retry_on_conflict

        retry_on_conflict(api, KIND_POD_GROUP, pg.metadata.name, mutate,
                          pg.metadata.namespace, component="gang")
    except NotFound:
        pass


def requested_mesh_chips(pg: Any) -> int | None:
    """Chip count implied by the PodGroup's mesh shape, if any."""
    if pg is None or not pg.spec.mesh:
        return None
    try:
        return Shape.parse(pg.spec.mesh).chips
    except ValueError:
        logger.warning("pod group %s has unparseable mesh %r",
                       pg.metadata.name, pg.spec.mesh)
        return None


_MESH_CHIPS_KEY = "topo-mesh-chips"
_POD_CHIPS_KEY = "topo-pod-chip-counts"
GANG_HOST_SET_KEY = "gang-allowed-hosts"


def gang_slice_windows(api: APIServer, members: list[Pod]
                       ) -> list[tuple[str, frozenset[str] | None]]:
    """Placement candidates for a gang consuming one multi-host slice: the
    host-index-aligned windows matching the partitioner's shard adjacency
    convention (nos_tpu/partitioning/slicepart/group.py).  Returns
    (pod_id, member host names) per candidate window.  hosts_needed is
    derived per physical pod from THAT pod's generation (a mixed-generation
    cluster has different window sizes per pod — mirroring _group_pass's
    per-generation classification); a pod whose generation fits the shape
    on a single host yields a (pod_id, None) whole-domain candidate.
    Returns [] when the gang does not request a slice resource or no
    generation needs window pinning (the best-fit domain fallback wins)."""
    from nos_tpu.kube.resources import pod_request
    from nos_tpu.topology import DEFAULT_REGISTRY
    from nos_tpu.topology.profile import extract_slice_requests

    shapes = set()
    for pod in members:
        shapes.update(extract_slice_requests(pod_request(pod)))
    if len(shapes) != 1:
        return []
    # noslint: N011 — singleton set: the len(shapes) == 1 guard above makes the only element order-free
    shape = next(iter(shapes))

    by_pod: dict[str, dict[int, object]] = {}
    needed_by_pod: dict[str, int | None] = {}  # None = sub-host shape
    mixed_pids: set[str] = set()  # permanently poisoned, not just popped
    for node in api.list("Node"):
        labels = node.metadata.labels
        pid = labels.get(C.LABEL_POD_ID, "")
        accel = labels.get(C.LABEL_ACCELERATOR, "")
        if not pid or pid in mixed_pids \
                or accel not in DEFAULT_REGISTRY.generations:
            continue
        gen = DEFAULT_REGISTRY.get(accel)
        needed = (None if shape.chips <= gen.chips_per_host
                  else gen.hosts_for(shape))
        if pid in needed_by_pod and needed_by_pod[pid] != needed:
            logger.warning("TPU pod %s spans generations; skipping", pid)
            mixed_pids.add(pid)
            by_pod.pop(pid, None)
            continue
        needed_by_pod[pid] = needed
        try:
            idx = int(labels.get(C.LABEL_HOST_INDEX, "0"))
        except ValueError:
            continue
        by_pod.setdefault(pid, {})[idx] = node.metadata.name
    if not any(needed_by_pod[pid] for pid in by_pod):
        return []  # every usable generation is sub-host: no constraint
    from nos_tpu.topology.windows import aligned_index_windows

    out: list[tuple[str, frozenset[str] | None]] = []
    for pid in sorted(by_pod):
        hosts = by_pod[pid]
        needed = needed_by_pod.get(pid)
        if needed is None:
            out.append((pid, None))  # sub-host generation: whole domain
            continue
        for window in aligned_index_windows(hosts, needed):
            out.append((pid, frozenset(hosts[i] for i in window)))
    return out


class TopologyFilter:
    """Filter plugin: gang members must share one physical TPU pod, and the
    pod must be large enough for the requested mesh.  Cycle-invariant
    lookups (the PodGroup's mesh requirement, per-pod chip totals) are
    computed once in PreFilter and stashed in cycle state — Filter runs
    per member x node and must stay O(1)."""

    name = "TopologyFilter"

    def __init__(self, api: APIServer) -> None:
        self._api = api

    def pre_filter(self, state: CycleState, pod: Pod,
                   nodes: SharedLister) -> Status:
        gang = gang_name(pod)
        if not gang:
            return Status.ok()
        if _MESH_CHIPS_KEY not in state:
            pg = get_pod_group(self._api, gang, pod.metadata.namespace)
            state[_MESH_CHIPS_KEY] = requested_mesh_chips(pg)
        if _POD_CHIPS_KEY not in state:
            counts: dict[str, int] = {}
            for ni in nodes.list():
                labels = ni.node.metadata.labels
                pid = labels.get(C.LABEL_POD_ID, "")
                if pid:
                    counts[pid] = counts.get(pid, 0) + int(
                        labels.get(C.LABEL_CHIP_COUNT, "0"))
            state[_POD_CHIPS_KEY] = counts
        return Status.ok()

    def filter(self, state: CycleState, pod: Pod,
               node_info: NodeInfo) -> Status:
        gang = gang_name(pod)
        if not gang:
            return Status.ok()
        node_pod_id = node_info.node.metadata.labels.get(C.LABEL_POD_ID, "")
        pinned = state.get(GANG_POD_ID_KEY)
        # "" pins to unlabeled hosts only — a gang must never straddle a
        # labeled ICI domain and anything else.
        if pinned is not None and node_pod_id != pinned:
            return Status.unschedulable(
                f"gang {gang} pinned to TPU pod {pinned or '(unlabeled)'}, "
                f"node is in {node_pod_id or '(unlabeled)'}"
            )
        allowed_hosts = state.get(GANG_HOST_SET_KEY)
        if allowed_hosts is not None and node_info.name not in allowed_hosts:
            return Status.unschedulable(
                f"gang {gang} pinned to slice hosts "
                f"{sorted(allowed_hosts)}, node {node_info.name} is outside"
            )
        chips = state.get(_MESH_CHIPS_KEY)
        if chips is not None and node_pod_id:
            total = state.get(_POD_CHIPS_KEY, {}).get(node_pod_id, 0)
            if total < chips:
                return Status.unschedulable(
                    f"TPU pod {node_pod_id} has {total} chips < mesh "
                    f"requirement {chips}"
                )
        return Status.ok()


def evict_gang(api: APIServer, victim: Pod) -> list[str]:
    """A gang is all-or-nothing in death too: evicting one member evicts
    the whole group (partial gangs would deadlock the job while holding
    chips — SURVEY.md §7 hard part 2)."""
    gang = gang_name(victim)
    doomed = [victim]
    if gang:
        doomed = api.list(
            "Pod", namespace=victim.metadata.namespace,
            label_selector={C.LABEL_POD_GROUP: gang})
    deleted = []
    for p in doomed:
        try:
            api.delete("Pod", p.metadata.name, p.metadata.namespace)
            deleted.append(p.key)
        except NotFound:
            pass
    if gang:
        pg = get_pod_group(api, gang, victim.metadata.namespace)
        if pg is not None:
            set_pod_group_status(api, pg, "Pending", 0)
    return deleted
