"""Pod predicates.

Analog of reference pkg/util/pod/pod.go:31-101.
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.kube.objects import PENDING, Pod


def extra_resources_could_help_scheduling(pod: Pod) -> bool:
    """Pending + marked unschedulable + not preempting + not owned by a
    DaemonSet (reference pod.go:41-48): these are the pods a repartition
    could rescue."""
    return (
        pod.status.phase == PENDING
        and pod.is_unschedulable()
        and not pod.status.nominated_node_name
        and pod.metadata.owner_kind != "DaemonSet"
    )


def is_over_quota(pod: Pod) -> bool:
    return pod.metadata.labels.get(C.LABEL_CAPACITY) == C.CAPACITY_OVER_QUOTA


def is_in_quota(pod: Pod) -> bool:
    return pod.metadata.labels.get(C.LABEL_CAPACITY) == C.CAPACITY_IN_QUOTA


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.spec.node_name)
