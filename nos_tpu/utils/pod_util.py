"""Pod predicates.

Analog of reference pkg/util/pod/pod.go:31-101.
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.kube.objects import PENDING, Pod


def extra_resources_could_help_scheduling(pod: Pod) -> bool:
    """Pending + marked unschedulable + not preempting + not owned by a
    DaemonSet (reference pod.go:41-48): these are the pods a repartition
    could rescue."""
    return (
        pod.status.phase == PENDING
        and pod.is_unschedulable()
        and not pod.status.nominated_node_name
        and pod.metadata.owner_kind != "DaemonSet"
    )


def workload_class(pod: Pod) -> str:
    """Telemetry workload class: the machine class / time-share unit the
    pod consumes, the `class=` label of every per-class SLO series
    (nos_tpu_schedule_latency_seconds, pending gauges — see
    docs/observability.md).  Mirrors the bench trace taxonomy:
    ``gang-<shape>`` for pod-group members, ``slice-<shape>`` for
    single slice consumers, ``ts-<gb>`` for time-share units,
    ``other`` for anything else.  Classes must stay LOW-cardinality:
    they come from the finite profile table, never from pod names."""
    from nos_tpu.kube.resources import pod_request
    from nos_tpu.topology.profile import (
        extract_slice_requests, extract_timeshare_requests,
    )

    req = pod_request(pod)
    slices = extract_slice_requests(req)
    if slices:
        shape = max(slices, key=lambda s: (s.chips, str(s)))
        kind = ("gang" if pod.metadata.labels.get(C.LABEL_POD_GROUP)
                else "slice")
        return f"{kind}-{shape}"
    timeshare = extract_timeshare_requests(req)
    if timeshare:
        return f"ts-{max(timeshare)}"
    return "other"


def is_over_quota(pod: Pod) -> bool:
    return pod.metadata.labels.get(C.LABEL_CAPACITY) == C.CAPACITY_OVER_QUOTA


def is_in_quota(pod: Pod) -> bool:
    return pod.metadata.labels.get(C.LABEL_CAPACITY) == C.CAPACITY_IN_QUOTA


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.spec.node_name)
