"""Pod predicates.

Analog of reference pkg/util/pod/pod.go:31-101.
"""

from __future__ import annotations

import math

from nos_tpu.api import constants as C
from nos_tpu.kube.objects import PENDING, Pod


def extra_resources_could_help_scheduling(pod: Pod) -> bool:
    """Pending + marked unschedulable + not preempting + not owned by a
    DaemonSet (reference pod.go:41-48): these are the pods a repartition
    could rescue."""
    return (
        pod.status.phase == PENDING
        and pod.is_unschedulable()
        and not pod.status.nominated_node_name
        and pod.metadata.owner_kind != "DaemonSet"
    )


def workload_tier(pod: Pod) -> str:
    """The pod's workload tier under the ``nos.tpu/tier`` contract
    (docs/serving.md): ``serving`` | ``batch`` | ``best-effort``.
    Absent or unrecognized values read as ``batch`` — every pre-tier
    workload was batch/training-shaped, and a typo in the label must
    degrade to the preemptible default, never silently grant the
    protected serving tier."""
    tier = pod.metadata.labels.get(C.LABEL_TIER, "")
    if tier in (C.TIER_SERVING, C.TIER_BATCH, C.TIER_BEST_EFFORT):
        return tier
    return C.TIER_BATCH


def tier_rank(pod: Pod) -> int:
    """Admission-queue rank of the pod's tier: serving first (0), batch
    (1), best-effort last (2).  The scheduler sorts each cycle's queue
    by this BEFORE priority, so a serving replica is always picked ahead
    of any batch gang regardless of PriorityClass arithmetic."""
    return {C.TIER_SERVING: 0, C.TIER_BATCH: 1,
            C.TIER_BEST_EFFORT: 2}[workload_tier(pod)]


def displaced_value(cause: str, now: float) -> str:
    """Render the ``nos.tpu/displaced`` annotation value for a workload
    displaced at `now` (the stamping clock must share the scheduler's
    time domain — the rebind latency is clock() minus this stamp)."""
    return f"{cause}@{now:.3f}"


def displacement(pod: Pod) -> tuple[str, float] | None:
    """(cause, stamped-at) of a displaced pod, or None when the pod is
    not displaced or the annotation is malformed — a garbage stamp
    degrades to not-displaced (normal admission rank), never to a
    permanent head-of-line boost."""
    raw = pod.metadata.annotations.get(C.ANNOT_DISPLACED, "")
    if not raw:
        return None
    cause, sep, ts_raw = raw.rpartition("@")
    if not sep or not cause:
        return None
    try:
        ts = float(ts_raw)
    except ValueError:
        return None
    if not math.isfinite(ts):
        return None
    return cause, ts


def is_displaced_fresh(pod: Pod, now: float = 0.0,
                       age_cap_s: float = 0.0) -> bool:
    """THE "counts as displaced" predicate — a batch/best-effort pod
    carrying an unexpired ``nos.tpu/displaced`` stamp.  Shared by the
    admission queue's head-of-line slot and capacityscheduling's
    restart-cost victim walk so the two can never disagree: a pod
    whose boost expired (stamp older than `age_cap_s` > 0) reads plain
    batch in BOTH, and a serving pod's stamp alters neither (serving
    already outranks displaced).  `age_cap_s` <= 0 means no expiry."""
    if tier_rank(pod) == 0:
        return False
    disp = displacement(pod)
    if disp is None:
        return False
    return age_cap_s <= 0.0 or now - disp[1] <= age_cap_s


def admission_rank(pod: Pod, now: float = 0.0,
                   age_cap_s: float = 0.0) -> int:
    """Admission-queue rank with the displaced head-of-line slot
    (docs/scheduler.md): serving 0, displaced batch/best-effort 1,
    batch 2, best-effort 4.  A displaced victim of node loss or a
    drain-migration rebinds ahead of the whole batch backlog but never
    ahead of serving; once its stamp is older than `age_cap_s` (> 0)
    the boost expires — an unplaceable displaced pod must not camp the
    head of the queue forever.  `age_cap_s` <= 0 means no expiry.
    With no displaced pods this is a monotone transform of
    ``tier_rank`` — the sort order is byte-identical."""
    rank = 2 * tier_rank(pod)
    if rank >= 2 and is_displaced_fresh(pod, now, age_cap_s):
        rank = 1
    return rank


def job_progress(pod: Pod) -> float:
    """The workload-reported ``nos.tpu/job-progress`` fraction in
    [0, 1] (absent/garbage/non-finite = 0: nothing to lose) — the
    restart-cost signal drain preemption and the displaced-preemptor
    victim walk key on."""
    raw = pod.metadata.annotations.get(C.ANNOT_JOB_PROGRESS, "")
    try:
        value = float(raw)
    except ValueError:
        return 0.0
    if not math.isfinite(value):
        return 0.0
    return min(1.0, max(0.0, value))


def workload_class(pod: Pod) -> str:
    """Telemetry workload class: the machine class / time-share unit the
    pod consumes, the `class=` label of every per-class SLO series
    (nos_tpu_schedule_latency_seconds, pending gauges — see
    docs/observability.md).  Mirrors the bench trace taxonomy:
    ``gang-<shape>`` for pod-group members, ``slice-<shape>`` for
    single slice consumers, ``ts-<gb>`` for time-share units,
    ``other`` for anything else.  Tier refinements: every serving-tier
    pod is class ``serving`` (ONE protected class — the tier's
    millisecond SLO is a promise about the tier, not about each slice
    shape), and best-effort pods carry a ``be-`` prefix so the
    scoreboard can split tiers without a second label.  Classes must
    stay LOW-cardinality: they come from the finite profile table,
    never from pod names."""
    from nos_tpu.kube.resources import pod_request
    from nos_tpu.topology.profile import (
        extract_slice_requests, extract_timeshare_requests,
    )

    tier = workload_tier(pod)
    if tier == C.TIER_SERVING:
        return "serving"
    req = pod_request(pod)
    base = "other"
    slices = extract_slice_requests(req)
    if slices:
        shape = max(slices, key=lambda s: (s.chips, str(s)))
        kind = ("gang" if pod.metadata.labels.get(C.LABEL_POD_GROUP)
                else "slice")
        base = f"{kind}-{shape}"
    else:
        timeshare = extract_timeshare_requests(req)
        if timeshare:
            base = f"ts-{max(timeshare)}"
    if tier == C.TIER_BEST_EFFORT:
        return f"be-{base}"
    return base


def class_tier(cls: str) -> str:
    """Tier of a telemetry workload class (the inverse mapping the
    scoreboard uses to fold per-class series into per-tier rows):
    ``serving`` -> serving, ``be-*`` -> best-effort, everything else ->
    batch."""
    if cls == "serving":
        return C.TIER_SERVING
    if cls.startswith("be-"):
        return C.TIER_BEST_EFFORT
    return C.TIER_BATCH


def is_elastic_dp(pod: Pod) -> bool:
    """True when the pod participates in the malleable-gang contract
    (``nos.tpu/elastic: "dp"`` AND a pod-group label): the control plane
    may grow/shrink its gang's dp axis within the replica bounds.  A
    bare elastic annotation without a gang is meaningless and reads
    rigid."""
    return (pod.metadata.annotations.get(C.ANNOT_ELASTIC, "")
            == C.ELASTIC_DP
            and bool(pod.metadata.labels.get(C.LABEL_POD_GROUP, "")))


def elastic_replica_bounds(pod: Pod) -> tuple[int, int] | None:
    """(min_replicas, max_replicas) of an elastic-dp member, or None
    when the pod is not elastic or its bounds are absent/garbage/
    inverted — a malformed contract degrades to rigid (no resize),
    never to unbounded."""
    if not is_elastic_dp(pod):
        return None
    annots = pod.metadata.annotations
    try:
        lo = int(annots.get(C.ANNOT_MIN_REPLICAS, ""))
        hi = int(annots.get(C.ANNOT_MAX_REPLICAS, ""))
    except ValueError:
        return None
    if lo < 1 or hi < lo:
        return None
    return lo, hi


def is_over_quota(pod: Pod) -> bool:
    return pod.metadata.labels.get(C.LABEL_CAPACITY) == C.CAPACITY_OVER_QUOTA


def is_in_quota(pod: Pod) -> bool:
    return pod.metadata.labels.get(C.LABEL_CAPACITY) == C.CAPACITY_IN_QUOTA


def is_scheduled(pod: Pod) -> bool:
    return bool(pod.spec.node_name)
