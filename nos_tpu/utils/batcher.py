"""Generic pod batcher with timeout + idle windows.

Analog of reference pkg/util/batcher.go:25-130 (`util.Batcher[T]`): a batch
becomes ready when either `timeout` has elapsed since the first add, or
`idle` has elapsed since the last add.  The reference uses goroutines and
channels; here the clock is injected and `ready()` is polled by the
controller loop, which keeps the whole control plane deterministic in tests
and in the simulator (and lets the simulator compress time).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Generic, TypeVar

from nos_tpu.utils.guards import guarded_by

T = TypeVar("T")


@guarded_by("_lock", "_items", "_first_add", "_last_add")
class Batcher(Generic[T]):
    def __init__(self, timeout_s: float, idle_s: float,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.timeout_s = timeout_s
        self.idle_s = idle_s
        self._clock = clock
        self._lock = threading.Lock()
        self._items: dict[str, T] = {}
        self._first_add: float | None = None
        self._last_add: float | None = None

    def add(self, key: str, item: T) -> None:
        """Non-blocking add; duplicate keys refresh the item but the idle
        window restarts either way (batcher.go Add).  add() runs on watch
        fan-out threads while ready()/drain() run on the controller loop."""
        with self._lock:
            now = self._clock()
            if self._first_add is None:
                self._first_add = now
            self._last_add = now
            self._items[key] = item

    def ready(self) -> bool:
        with self._lock:
            if self._first_add is None:
                return False
            now = self._clock()
            if now - self._first_add >= self.timeout_s:
                return True
            last = self._last_add if self._last_add is not None else now
            return now - last >= self.idle_s

    def drain(self) -> list[T]:
        with self._lock:
            items = list(self._items.values())
            self._reset_locked()
            return items

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()

    def _reset_locked(self) -> None:
        self._items.clear()
        self._first_add = None
        self._last_add = None

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)
