"""Retry-on-conflict substrate for the decision plane's writes.

Every annotation/status write in the control plane is a read-modify-write
patch; against a real apiserver (or the chaos substrate,
nos_tpu/testing/chaos.py) any of them can fail with `Conflict` (409) or a
transient transport error.  The reference leans on controller-runtime's
`retry.RetryOnConflict` (k8s.io/client-go/util/retry) at its patch sites;
this module is that helper for the APIServer surface, plus the capped
jittered backoff the KubeClient watch-reconnect loop uses.

`mutate` re-reads the object on every attempt (api.patch re-fetches before
calling it), so a retried patch is computed against the winner's state —
never a blind replay of a stale diff.

Transient transport errors (OSError) are retried too — an explicit
widening over client-go's Conflict-only helper, because a dropped LB
connection must not wedge the handshake.  The cost: a response lost
AFTER the server committed gets the mutate applied twice.  Every mutate
passed here must therefore be IDEMPOTENT against current state
(set-annotation / set-label / set-status writes are; a read-modify-write
counter bump is only if double-increment is harmless, as the plugin
generation's staleness ordering is).
"""

from __future__ import annotations

import logging
import random
import time
from typing import Any, Callable

from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import Conflict, TransientAPIError

logger = logging.getLogger(__name__)

# Test seam: soak tests replace this with a no-op so hundreds of injected
# conflicts retry instantly (the backoff *schedule* is still computed and
# asserted on; only the actual blocking is skipped).
sleep: Callable[[float], None] = time.sleep

DEFAULT_ATTEMPTS = 8
DEFAULT_BASE_DELAY_S = 0.02
DEFAULT_MAX_DELAY_S = 1.0

REGISTRY.describe("nos_tpu_retry_total",
                  "Write attempts retried after Conflict/transient errors")
REGISTRY.describe("nos_tpu_retry_exhausted_total",
                  "Writes abandoned after exhausting retry attempts")

# Exceptions worth retrying: optimistic-concurrency losses, transport
# blips (ConnectionError, URLError, timeouts — all OSError), and
# server-side 5xx/429 (TransientAPIError from kube/rest.py).  NotFound is
# deliberately NOT here: a vanished object is a state change, not a blip,
# and every call site has its own NotFound policy.
RETRYABLE = (Conflict, OSError, TransientAPIError)


class Backoff:
    """Capped exponential backoff with full jitter.

    `next_delay()` grows base * factor^n up to `cap_s`, jittered over
    [cap*(1-jitter), cap] so a fleet of reconnecting watchers does not
    thundering-herd the apiserver; `reset()` on success.
    """

    def __init__(self, base_s: float = 0.2, cap_s: float = 30.0,
                 factor: float = 2.0, jitter: float = 0.5,
                 rng: random.Random | None = None) -> None:
        self.base_s = base_s
        self.cap_s = cap_s
        self.factor = factor
        self.jitter = jitter
        self._rng = rng or random.Random()
        self._n = 0

    def next_delay(self) -> float:
        raw = min(self.cap_s, self.base_s * (self.factor ** self._n))
        self._n += 1
        if not self.jitter:
            return raw
        return raw * (1.0 - self.jitter * self._rng.random())

    def reset(self) -> None:
        self._n = 0


def retry_on_conflict(api, kind: str, name: str,
                      mutate: Callable[[Any], None],
                      namespace: str = "", *,
                      component: str = "",
                      attempts: int = DEFAULT_ATTEMPTS,
                      base_delay_s: float = DEFAULT_BASE_DELAY_S,
                      max_delay_s: float = DEFAULT_MAX_DELAY_S) -> Any:
    """api.patch(kind, name, namespace, mutate=mutate) with jittered
    exponential backoff on Conflict/transient errors.

    Emits `nos_tpu_retry_total` per retried attempt and
    `nos_tpu_retry_exhausted_total` (then re-raises) when `attempts`
    are burned — a climbing exhausted counter is a contended object or
    a down apiserver, not normal operation (docs/troubleshooting.md).
    """
    labels = {"component": component or kind}
    backoff = Backoff(base_s=base_delay_s, cap_s=max_delay_s)
    last: Exception | None = None
    for attempt in range(attempts):
        try:
            return api.patch(kind, name, namespace, mutate=mutate)
        except RETRYABLE as e:  # noqa: PERF203 — retry loop
            last = e
            REGISTRY.inc("nos_tpu_retry_total", labels=labels)
            if attempt == attempts - 1:
                break
            delay = backoff.next_delay()
            logger.debug("retry %s %s/%s (%s, attempt %d/%d, %.3fs): %s",
                         kind, namespace, name, labels["component"],
                         attempt + 1, attempts, delay, e)
            sleep(delay)
    REGISTRY.inc("nos_tpu_retry_exhausted_total", labels=labels)
    logger.warning("retry exhausted after %d attempts: %s %s/%s (%s): %s",
                   attempts, kind, namespace, name, labels["component"],
                   last)
    assert last is not None
    raise last
