"""``@guarded_by``: declared lock ownership for shared mutable state.

The decision plane's thread-safety today rests on conventions written in
comments ("every mutator takes ``self._lock``").  This decorator makes
the convention a *declaration* that two independent checkers read:

- **statically** — noslint N010 (nos_tpu/analysis/rules_flow.py) proves
  every write site of a declared field sits syntactically under
  ``with self.<lock>:`` (or inside a ``*_locked`` method, whose call
  sites must themselves hold the lock);
- **dynamically** — ``nos_tpu.testing.lockcheck.guard_state`` reads the
  same ``__guarded_by__`` table and convicts any runtime write to a
  declared field without its lock held, under the chaos soak.

One annotation, two proofs — the same contract PR 2 split between
comments and ``guard_state`` call-site arguments.

Usage::

    @guarded_by("_lock", "_nodes", "_gen", "_built")
    class SchedulerCache:
        def __init__(self):
            self._lock = threading.Lock()
            ...

Stacking is allowed for classes with more than one lock; each field
belongs to exactly one lock (re-declaring a field raises at import
time — the table must be unambiguous for both checkers).  The lock and
field names must be string literals: N010 checks them without running
the code.  Subclasses inherit the parent's table and may extend it
(``DecisionJournal`` adds ``_seq`` to ``BoundedRing``'s ``_items``).

Runtime cost: one class attribute.  The decorator changes no behavior.
"""

from __future__ import annotations

from typing import TypeVar

T = TypeVar("T", bound=type)


def _all_names(head: str, fields: tuple[str, ...]) -> bool:
    """Both checkers read these tables as attribute names; anything that
    is not a non-empty string is unresolvable for them, so reject it at
    declaration time rather than letting the contract silently decay."""
    return (isinstance(head, str) and bool(head) and bool(fields)
            and all(isinstance(f, str) and f for f in fields))


def guarded_by(lock_attr: str, *fields: str):
    """Declare that ``fields`` may only be written with ``self.<lock_attr>``
    held.  Returns a class decorator; see the module docstring."""
    if not _all_names(lock_attr, fields):
        raise ValueError("guarded_by needs a lock attribute name and "
                         "at least the fields it guards, all non-empty "
                         "strings")

    def decorate(cls: T) -> T:
        # copy-on-extend: never mutate a base class's table in place
        table = dict(getattr(cls, "__guarded_by__", {}))
        for f in fields:
            prior = table.get(f)
            if prior is not None and prior != lock_attr:
                raise ValueError(
                    f"{cls.__name__}.{f} declared guarded by both "
                    f"{prior!r} and {lock_attr!r} — one lock per field")
            table[f] = lock_attr
        cls.__guarded_by__ = table
        return cls

    return decorate


def guarded_fields(cls: type) -> dict[str, str]:
    """The declared field -> lock-attribute table ({} when undeclared)."""
    return dict(getattr(cls, "__guarded_by__", {}))


def invalidated_by(event: str, *fields: str):
    """Declare that in-place mutations of ``fields`` feed a derived cache
    whose coherence signal is ``event`` — a method of the class (e.g.
    ``SchedulerCache._bump_locked``) or a counter attribute that every
    mutator bumps (e.g. ``ClusterSnapshot._mutation_gen``).

    Like :func:`guarded_by`, the decorator is pure declaration (one class
    attribute, no behavior change) read by an independent checker:
    noslint N012 (nos_tpu/analysis/rules_det.py) proves every in-place
    mutation site of a declared field is post-dominated by an emission of
    its event — a call whose last segment is the event name, or an
    assignment/augassignment to ``self.<event>``.  Whole-field rebinds
    (``self._cache = {}``) are the invalidate-by-rebuild idiom and are
    not convicted; ``__init__``/``__post_init__`` and the event method
    itself are exempt.

    Usage::

        @invalidated_by("_bump_locked", "_node_objs", "_pods_by_node")
        class SchedulerCache:
            ...

    Stacking is allowed for classes with more than one invalidation
    event; each field belongs to exactly one event (re-declaring a field
    under a different event raises at import time).  Event and field
    names must be string literals so N012 can check without running the
    code.  Subclasses inherit and may extend the table.
    """
    if not _all_names(event, fields):
        raise ValueError("invalidated_by needs an event name and "
                         "at least one watched field, all non-empty "
                         "strings")

    def decorate(cls: T) -> T:
        # copy-on-extend: never mutate a base class's table in place
        table = dict(getattr(cls, "__invalidated_by__", {}))
        for f in fields:
            prior = table.get(f)
            if prior is not None and prior != event:
                raise ValueError(
                    f"{cls.__name__}.{f} declared invalidated by both "
                    f"{prior!r} and {event!r} — one event per field")
            table[f] = event
        cls.__invalidated_by__ = table
        return cls

    return decorate


def invalidated_fields(cls: type) -> dict[str, str]:
    """The declared field -> invalidation-event table ({} when undeclared)."""
    return dict(getattr(cls, "__invalidated_by__", {}))
