"""``@guarded_by``: declared lock ownership for shared mutable state.

The decision plane's thread-safety today rests on conventions written in
comments ("every mutator takes ``self._lock``").  This decorator makes
the convention a *declaration* that two independent checkers read:

- **statically** — noslint N010 (nos_tpu/analysis/rules_flow.py) proves
  every write site of a declared field sits syntactically under
  ``with self.<lock>:`` (or inside a ``*_locked`` method, whose call
  sites must themselves hold the lock);
- **dynamically** — ``nos_tpu.testing.lockcheck.guard_state`` reads the
  same ``__guarded_by__`` table and convicts any runtime write to a
  declared field without its lock held, under the chaos soak.

One annotation, two proofs — the same contract PR 2 split between
comments and ``guard_state`` call-site arguments.

Usage::

    @guarded_by("_lock", "_nodes", "_gen", "_built")
    class SchedulerCache:
        def __init__(self):
            self._lock = threading.Lock()
            ...

Stacking is allowed for classes with more than one lock; each field
belongs to exactly one lock (re-declaring a field raises at import
time — the table must be unambiguous for both checkers).  The lock and
field names must be string literals: N010 checks them without running
the code.  Subclasses inherit the parent's table and may extend it
(``DecisionJournal`` adds ``_seq`` to ``BoundedRing``'s ``_items``).

Runtime cost: one class attribute.  The decorator changes no behavior.
"""

from __future__ import annotations

from typing import TypeVar

T = TypeVar("T", bound=type)


def guarded_by(lock_attr: str, *fields: str):
    """Declare that ``fields`` may only be written with ``self.<lock_attr>``
    held.  Returns a class decorator; see the module docstring."""
    if not lock_attr or not fields or not all(fields):
        raise ValueError("guarded_by needs a lock attribute name and "
                         "at least the fields it guards")

    def decorate(cls: T) -> T:
        # copy-on-extend: never mutate a base class's table in place
        table = dict(getattr(cls, "__guarded_by__", {}))
        for f in fields:
            prior = table.get(f)
            if prior is not None and prior != lock_attr:
                raise ValueError(
                    f"{cls.__name__}.{f} declared guarded by both "
                    f"{prior!r} and {lock_attr!r} — one lock per field")
            table[f] = lock_attr
        cls.__guarded_by__ = table
        return cls

    return decorate


def guarded_fields(cls: type) -> dict[str, str]:
    """The declared field -> lock-attribute table ({} when undeclared)."""
    return dict(getattr(cls, "__guarded_by__", {}))
