"""Shared bounded-ring plumbing for the span ring and decision journal.

The memory bound is the contract: a long-lived control plane keeps the
most recent `maxlen` items and counts what it evicted, instead of
growing.  Both obs.trace.RingExporter and obs.journal.DecisionJournal
build on this so the eviction accounting and snapshot consistency live
in exactly one place.
"""

from __future__ import annotations

import json
import threading
from collections import deque

from nos_tpu.utils.guards import guarded_by


@guarded_by("_lock", "_items", "_dropped")
class BoundedRing:
    """Lock-guarded ``deque(maxlen)`` with an eviction counter.

    Subclasses append via ``_push_locked`` while holding ``self._lock``
    (so they can fold their own bookkeeping — e.g. a sequence number —
    into the same critical section) and bump their eviction metric
    OUTSIDE the lock using the returned flag.  Items must expose
    ``to_dict()``.
    """

    def __init__(self, maxlen: int) -> None:
        self.maxlen = maxlen
        self._lock = threading.Lock()
        self._items: deque = deque(maxlen=maxlen)
        self._dropped = 0

    def _push_locked(self, item: object) -> bool:
        """Append (caller holds ``self._lock``); True if one evicted."""
        evicted = len(self._items) == self.maxlen
        if evicted:
            self._dropped += 1
        self._items.append(item)      # deque(maxlen) evicts oldest
        return evicted

    def dump(self) -> list[dict]:
        with self._lock:
            items = list(self._items)
        return [i.to_dict() for i in items]

    def to_json(self, indent: int | None = None) -> str:
        return json.dumps(self.dump(), indent=indent)

    def clear(self) -> None:
        with self._lock:
            self._items.clear()

    @property
    def dropped(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)
