"""Decision-plane observability: causal tracing, decision journal,
and the `nos explain` flight recorder.

Three layers over the scheduler ↔ partitioner ↔ actuator pipeline
(docs/observability.md):

- obs.trace — span API (contextvars propagation, injectable clock,
  bounded ring exporter, span-latency histograms in the metrics
  registry);
- obs.journal — bounded append-only log of decisions (rejections with
  per-node plugin reasons, plan commits/reverts, quarantine and quota
  transitions, preemption victim selection);
- obs.explain — reconstructs "why is this pod pending?" and "where did
  this plan's budget go?" from a flight snapshot; `python -m
  nos_tpu.obs` is the CLI, and the cmd/_runtime health server serves
  live snapshots at /debug/flightrecorder.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Iterator

from .explain import explain_plan, explain_pod
from .journal import (
    DecisionJournal, DecisionRecord, get_journal, record, set_journal,
)
from .ledger import (
    ChipSecondLedger, get_ledger, set_ledger,
)
from .slo import (
    SLOEngine, SLOObjective, get_engine, set_engine,
)
from .timeseries import TimeSeriesSampler
from .trace import (
    RingExporter, Span, Tracer, bump, current_span, detail_span,
    get_tracer, set_tracer, span,
)

__all__ = [
    "ChipSecondLedger", "DecisionJournal", "DecisionRecord",
    "RingExporter", "SLOEngine", "SLOObjective", "Span",
    "TimeSeriesSampler", "Tracer",
    "bump", "current_span", "detail_span", "explain_plan", "explain_pod",
    "flight_snapshot", "get_engine", "get_journal", "get_ledger",
    "get_tracer", "record", "scoped", "set_engine", "set_flight_block",
    "set_journal", "set_ledger", "set_tracer", "span",
]

# Extra named blocks a component can ride into the flight-recorder
# payload (the capacity plane registers its report here, so `obs
# capacity` works from the same one-fetch snapshot as waste/explain).
# A provider is a zero-arg callable returning a JSON-ready dict; a
# raising provider is dropped from THAT snapshot, never fails the dump.
_flight_blocks: dict = {}


def set_flight_block(name, provider=None):
    """Register (or, with provider=None, remove) a named snapshot
    block.  Returns the previous provider."""
    prev = _flight_blocks.get(name)
    if provider is None:
        _flight_blocks.pop(name, None)
    else:
        _flight_blocks[name] = provider
    return prev


def flight_snapshot() -> dict:
    """The flight-recorder snapshot: every finished span in the ring +
    the full journal, as plain dicts (JSON-ready), plus the SLO
    engine's latest report when one is installed.  This is the format
    obs.explain consumes and /debug/flightrecorder serves."""
    tracer = get_tracer()
    journal = get_journal()
    snapshot = {
        "spans": tracer.ring.dump(),
        "spans_dropped": tracer.ring.dropped,
        "journal": journal.dump(),
        "journal_dropped": journal.dropped,
    }
    engine = get_engine()
    if engine is not None:
        snapshot["slo"] = engine.report()
    # the chip-second waterfall rides in the SAME payload as the
    # journal, so `obs waste`'s culprit→journal join works from one
    # fetch (the explain/slo workflow, docs/observability.md)
    snapshot["waste"] = get_ledger().report()
    for name, provider in list(_flight_blocks.items()):
        try:
            snapshot[name] = provider()
        except Exception:  # noqa: BLE001 — a sick block must not kill the dump
            logging.getLogger(__name__).warning(
                "flight snapshot block %r raised; omitted", name)
    return snapshot


@contextlib.contextmanager
def scoped(tracer: Tracer | None = None,
           journal: DecisionJournal | None = None,
           engine: SLOEngine | None = None,
           ledger: ChipSecondLedger | None = None) -> Iterator[None]:
    """Install a tracer/journal (and optionally an SLO engine and a
    chip-second ledger) for the duration of the block and restore the
    previous set on exit — how tests (and the lockcheck-instrumented
    chaos soak) observe an isolated run without leaking state into the
    process globals."""
    prev_tracer = set_tracer(tracer) if tracer is not None else None
    prev_journal = set_journal(journal) if journal is not None else None
    prev_engine = set_engine(engine) if engine is not None else None
    prev_ledger = set_ledger(ledger) if ledger is not None else None
    try:
        yield
    finally:
        if prev_tracer is not None:
            set_tracer(prev_tracer)
        if prev_journal is not None:
            set_journal(prev_journal)
        if engine is not None:
            set_engine(prev_engine)
        if prev_ledger is not None:
            set_ledger(prev_ledger)
