"""The decision journal: a bounded append-only log of *decisions*.

Metrics answer "how many"; watch events answer "what changed"; neither
answers the operator's actual question — *why is this pod still
pending?*  The journal records the decision plane's verdicts at the
moment they are made, with enough structure to reconstruct the causal
chain afterwards (obs/explain.py):

- pod rejected — per-node `plugin: reason` detail from the scheduler's
  Filter pipeline (capped; distinct-reason counts are always complete);
- pod bound / nominated, gang admitted / rejected;
- plan cycle ran, per-node geometry commit / revert;
- node quarantined / released (plan deadline, actuation breaker);
- quota borrow / reclaim label flips, quota head-of-line claims;
- preemption victim selection.

Each record carries the ambient trace context (obs/trace.py), so a
journal line links back to the span tree that produced it.

Design constraints, in priority order:

1. **Bounded memory** — a deque(maxlen) plus an eviction counter; a
   week-long run keeps the newest `maxlen` decisions, never grows.
2. **Leaf lock** — `record()` takes the journal lock for the append
   only and calls nothing under it (no logging, no registry, no other
   lock), so instrumenting a call site can never add a lock-order edge
   (verified under lockcheck in the chaos soak).
3. **Injectable clock** — timestamps come from the journal's clock so
   chaos seeds reproduce byte-identical journals (noslint N002).
"""

from __future__ import annotations

import contextlib
import contextvars
import time
from typing import Callable, Iterator

from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.utils.guards import guarded_by

from ._ring import BoundedRing
from .trace import current_span

REGISTRY.describe("nos_tpu_journal_records_total",
                  "Decision-journal records appended, per category")
REGISTRY.describe("nos_tpu_journal_dropped_total",
                  "Decision records evicted from the bounded journal")

# Per-record multi-entity detail cap: per-node verdicts, gang member
# lists, lagging-node sets.  Aggregate counts on the record are always
# complete; the listed entities are capped so one cluster-wide decision
# cannot blow the journal's memory bound.
MAX_JOURNAL_NODES = 32

# -- decision categories (the journal's schema; docs/observability.md) ------
POD_REJECTED = "pod-rejected"
POD_BOUND = "pod-bound"
POD_NOMINATED = "pod-nominated"
GANG_ADMITTED = "gang-admitted"
GANG_REJECTED = "gang-rejected"
QUOTA_HOL_CLAIM = "quota-hol-claim"
QUOTA_BORROW = "quota-borrow"
QUOTA_RECLAIM = "quota-reclaim"
PREEMPTION = "preemption"
PREEMPTION_NONE = "preemption-none"
PLAN_CYCLE = "plan-cycle"
PLAN_SHARD_MERGED = "plan-shard-merged"
PLAN_NODE_COMMITTED = "plan-node-committed"
PLAN_NODE_REVERTED = "plan-node-reverted"
NODE_ACTUATED = "node-actuated"
ACTUATION_FAILED = "actuation-failed"
QUARANTINED = "quarantined"
QUARANTINE_RELEASED = "quarantine-released"
HANDSHAKE_WAIT = "handshake-wait"
SLO_BREACH = "slo-breach"
SLO_RECOVERED = "slo-recovered"
AUTOSCALE = "autoscale"
# Defragmentation plane (partitioning/core/defrag.py): a proposal is
# PROPOSED when the what-if fork proves every victim relocatable,
# APPLIED when it clears the payback threshold and its evictions fire,
# REJECTED when it fails payback / PDB allowance / drains past its
# deadline.  GANG_RESIZED records an elastic gang's dp grow/shrink.
DEFRAG_PROPOSED = "defrag-proposed"
DEFRAG_APPLIED = "defrag-applied"
DEFRAG_REJECTED = "defrag-rejected"
GANG_RESIZED = "gang-resized"
# Self-healing node-loss recovery (partitioning/core/failure.py +
# scheduler displaced head-of-line): a workload is DISPLACED when node
# loss / a drain-migration evicts it (cause + node recorded), REBOUND
# when the scheduler re-binds it (rebind latency from the displacement
# stamp); SPARE_PROMOTED records a warm spare taking over a vanished
# host's index.
JOB_DISPLACED = "job-displaced"
JOB_REBOUND = "job-rebound"
SPARE_PROMOTED = "spare-promoted"
# Cloud capacity plane (nos_tpu/capacity): a scale-up/replacement
# decision is REQUESTED when the provisioner asks the cloud for a node,
# LANDED when the node joined and became usable (latency from the
# request), FAILED when it was abandoned (stockout/quota/zombie reap/
# deadline; reason recorded).  STOCKOUT records a per-(machine class,
# zone) breaker transition (state recorded: open / half-open / closed).
# SPARE_BORROWED records a cross-pool spare promoted into a vacancy
# because the preferred machine class was stocked out.
PROVISION_REQUESTED = "provision-requested"
PROVISION_LANDED = "provision-landed"
PROVISION_FAILED = "provision-failed"
PROVISION_STOCKOUT = "provision-stockout"
SPARE_BORROWED = "spare-borrowed"
SCALE_DOWN = "scale-down"
# Request data plane (nos_tpu/requests): a request is SHED when every
# candidate replica's admission queue stayed full through the router's
# retry budget (service, session and retry count recorded — the router
# journals the DECISION to drop, never the millions of routine routes);
# SESSION_MIGRATED records a live session re-homed because its replica
# vanished (scale-down, node loss), with the old and new replica.
REQUEST_SHED = "request-shed"
SESSION_MIGRATED = "session-migrated"


class DecisionRecord:
    """One decision.  `subject` is the object the decision is about
    (pod key "ns/name", node name, "ns/gang", or a kind); `attrs` is
    category-specific detail (docs/observability.md has the schema)."""

    __slots__ = ("seq", "ts", "category", "subject", "attrs",
                 "trace_id", "span_id")

    def __init__(self, seq: int, ts: float, category: str, subject: str,
                 attrs: dict, trace_id: str, span_id: str) -> None:
        self.seq = seq
        self.ts = ts
        self.category = category
        self.subject = subject
        self.attrs = attrs
        self.trace_id = trace_id
        self.span_id = span_id

    def to_dict(self) -> dict:
        return {
            "seq": self.seq,
            "ts": self.ts,
            "category": self.category,
            "subject": self.subject,
            "attrs": dict(self.attrs),
            "trace_id": self.trace_id,
            "span_id": self.span_id,
        }


@guarded_by("_lock", "_seq")
class DecisionJournal(BoundedRing):
    """Bounded, totally-ordered (per journal) decision log.  Extends the
    ring's @guarded_by table with the sequence counter (same lock)."""

    def __init__(self, maxlen: int = 4096,
                 clock: Callable[[], float] = time.monotonic) -> None:
        super().__init__(maxlen)
        self._clock = clock
        self._seq = 0

    def record(self, category: str, subject: str,
               **attrs: object) -> DecisionRecord:
        """Append one decision; never raises, never blocks beyond the
        leaf append lock.  Returns the record (tests assert on it)."""
        span = current_span()
        ts = self._clock()
        rec = DecisionRecord(
            0, ts, category, subject, attrs,
            span.trace_id if span is not None else "",
            span.span_id if span is not None else "")
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            evicted = self._push_locked(rec)
        REGISTRY.inc("nos_tpu_journal_records_total",
                     labels={"category": category})
        if evicted:
            REGISTRY.inc("nos_tpu_journal_dropped_total")
        return rec

    # -- reads --------------------------------------------------------------
    def events(self, category: str | None = None,
               subject: str | None = None,
               limit: int | None = None) -> list[DecisionRecord]:
        """Matching records, oldest first (`limit` keeps the newest N)."""
        with self._lock:
            records = list(self._items)
        if category is not None:
            records = [r for r in records if r.category == category]
        if subject is not None:
            records = [r for r in records if r.subject == subject]
        if limit is not None:
            records = records[-limit:]
        return records


# ---------------------------------------------------------------------------
# Process-global journal (swappable, like obs.trace's tracer)
# ---------------------------------------------------------------------------

_journal = DecisionJournal()

# Context-local capture override (None = record straight to the process
# journal).  The parallel planner's shard workers run under a capture
# (see capture_records) so concurrent shards never interleave appends
# nondeterministically — the merge replays each shard's records into
# the ambient journal in pool-key order, which is what lets nosdiff
# (analysis/determinism.py) demand byte-identical journals across
# plan_workers settings.
_capture: "contextvars.ContextVar[JournalCapture | None]" = \
    contextvars.ContextVar("nos_tpu_journal_capture", default=None)


class JournalCapture:
    """Order-preserving buffer of ``record()`` calls for deterministic
    replay.  Deliberately NOT a DecisionJournal: no seq/ts stamping, no
    metrics — the replay into the ambient journal does all of that
    exactly once, so a captured decision is indistinguishable from one
    recorded inline (trace context is re-read at replay time; shard
    span ids are scheduling artifacts, not decisions)."""

    def __init__(self) -> None:
        self.calls: list[tuple[str, str, dict]] = []

    def record(self, category: str, subject: str,
               **attrs: object) -> DecisionRecord:
        self.calls.append((category, subject, attrs))
        return DecisionRecord(0, 0.0, category, subject, attrs, "", "")

    def replay(self) -> None:
        """Append every captured decision to the ambient journal, in
        capture order."""
        for category, subject, attrs in self.calls:
            record(category, subject, **attrs)


@contextlib.contextmanager
def capture_records(capture: JournalCapture) -> Iterator[JournalCapture]:
    """Route this context's ``record()`` calls into ``capture`` instead
    of the process journal (contextvar-scoped, so a worker running
    under ``contextvars.copy_context()`` captures without affecting its
    submitter)."""
    token = _capture.set(capture)
    try:
        yield capture
    finally:
        _capture.reset(token)


def get_journal() -> DecisionJournal:
    return _journal


def set_journal(journal: DecisionJournal) -> DecisionJournal:
    global _journal
    prev = _journal
    _journal = journal
    return prev


def record(category: str, subject: str, **attrs: object) -> DecisionRecord:
    """Record a decision in the process journal — THE call-site API.
    Under an active :func:`capture_records` context the decision is
    buffered for deterministic replay instead."""
    capture = _capture.get()
    if capture is not None:
        return capture.record(category, subject, **attrs)
    return _journal.record(category, subject, **attrs)
