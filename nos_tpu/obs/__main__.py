"""CLI: `python -m nos_tpu.obs` — explain pods/plans from a flight
snapshot, dump the recorder, or self-test the subsystem.

    python -m nos_tpu.obs explain pod <ns>/<name> --snapshot flight.json
    python -m nos_tpu.obs explain plan [--kind slice] --url http://host:8080
    python -m nos_tpu.obs dump --url http://host:8080
    python -m nos_tpu.obs --selftest

Snapshot sources: `--snapshot FILE` (a saved /debug/flightrecorder
payload; `-` = stdin) or `--url ADDR` (fetches ADDR/debug/flightrecorder
live).  `--selftest` runs an in-process end-to-end check of the span
API, journal, and explain reconstruction — the CI hook in
scripts/check.sh.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import explain_plan, explain_pod


def _load_snapshot(args: argparse.Namespace) -> dict:
    snapshot: dict
    if args.url:
        from urllib.request import urlopen

        url = args.url.rstrip("/") + "/debug/flightrecorder"
        with urlopen(url, timeout=10.0) as resp:   # noqa: S310 — operator URL
            snapshot = json.load(resp)
            return snapshot
    if args.snapshot == "-":
        snapshot = json.load(sys.stdin)
        return snapshot
    if args.snapshot:
        with open(args.snapshot, encoding="utf-8") as fh:
            snapshot = json.load(fh)
            return snapshot
    raise SystemExit(
        "no snapshot source: pass --snapshot FILE (or '-') or --url ADDR "
        "(the health server serves /debug/flightrecorder)")


def selftest() -> int:
    """In-process zero-cluster check: spans nest and propagate, the
    journal stays bounded and ordered, and explain reconstructs a
    rejection chain naming the plugin.  Prints ok/FAIL, returns rc."""
    from .journal import POD_BOUND, POD_REJECTED, DecisionJournal
    from .trace import RingExporter, Tracer

    failures: list[str] = []
    now = [0.0]

    def clock() -> float:
        now[0] += 0.5
        return now[0]

    tracer = Tracer(clock=clock, ring=RingExporter(maxlen=4))
    journal = DecisionJournal(maxlen=8, clock=clock)

    # span nesting + context propagation
    with tracer.span("outer", stage="selftest") as outer:
        with tracer.span("inner") as inner:
            if inner.trace_id != outer.trace_id:
                failures.append("child span did not inherit trace id")
            if inner.parent_id != outer.span_id:
                failures.append("child span did not link to parent")
            journal.record(POD_REJECTED, "default/victim",
                           reason="selftest",
                           message="no fit anywhere",
                           nodes={"host-0": "NodeResourcesFit: "
                                            "insufficient nos.tpu/slice-2x2"},
                           reason_counts={})
    if journal.events()[-1].trace_id != outer.trace_id:
        failures.append("journal record did not capture trace context")

    # ring bound
    for i in range(10):
        with tracer.span(f"churn-{i}"):
            pass
    if len(tracer.ring) != 4:
        failures.append(f"ring not bounded: {len(tracer.ring)} != 4")
    if tracer.ring.dropped != 8:
        failures.append(f"ring dropped miscounted: {tracer.ring.dropped}")

    # journal bound + total order
    for i in range(20):
        journal.record(POD_BOUND, f"default/p{i}", node="host-0")
    if len(journal) != 8:
        failures.append(f"journal not bounded: {len(journal)} != 8")
    seqs = [r.seq for r in journal.events()]
    if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
        failures.append(f"journal order broken: {seqs}")

    # explain reconstructs the rejection (fresh journal: the churn above
    # evicted the rejection record — that eviction is itself the test)
    journal2 = DecisionJournal(maxlen=8, clock=clock)
    journal2.record(POD_REJECTED, "default/stuck",
                    reason="", message="no fit",
                    nodes={"host-0": "NodeResourcesFit: insufficient "
                                     "nos.tpu/slice-2x2"},
                    reason_counts={})
    snapshot = {"spans": tracer.ring.dump(), "journal": journal2.dump()}
    text = "\n".join(explain_pod(snapshot, "default/stuck"))
    if "NodeResourcesFit" not in text or "host-0" not in text:
        failures.append(f"explain lost the rejecting plugin:\n{text}")

    if failures:
        print("obs selftest: FAIL", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("obs selftest: ok (spans, journal, explain)")
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nos_tpu.obs",
        description=__doc__.split("\n")[0])
    parser.add_argument("--selftest", action="store_true",
                        help="run the in-process subsystem check")
    sub = parser.add_subparsers(dest="command")

    p_explain = sub.add_parser("explain", help="reconstruct a causal answer")
    ex_sub = p_explain.add_subparsers(dest="what", required=True)
    p_pod = ex_sub.add_parser("pod", help="why is this pod pending?")
    p_pod.add_argument("key", help="pod as <namespace>/<name>")
    p_plan = ex_sub.add_parser("plan", help="where did the plan budget go?")
    p_plan.add_argument("--kind", default=None,
                        help="partitioning kind (slice|timeshare)")
    p_dump = sub.add_parser("dump", help="print the raw flight snapshot")
    for p in (p_pod, p_plan, p_dump):
        p.add_argument("--snapshot", default="",
                       help="saved /debug/flightrecorder JSON ('-'=stdin)")
        p.add_argument("--url", default="",
                       help="live health server base URL")

    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.command is None:
        parser.print_help()
        return 2
    try:
        snapshot = _load_snapshot(args)
    except json.JSONDecodeError as exc:
        print(f"snapshot is not valid JSON: {exc}", file=sys.stderr)
        return 1
    except OSError as exc:   # unreadable file, unreachable --url
        print(f"cannot read snapshot: {exc}", file=sys.stderr)
        return 1
    if not isinstance(snapshot, dict):
        print("snapshot is not a flight-recorder payload "
              "(expected a JSON object)", file=sys.stderr)
        return 1
    if args.command == "dump":
        print(json.dumps(snapshot, indent=2))
        return 0
    if args.what == "pod":
        if "/" not in args.key:
            print("pod key must be <namespace>/<name>", file=sys.stderr)
            return 2
        lines = explain_pod(snapshot, args.key)
    else:
        lines = explain_plan(snapshot, kind=args.kind)
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
