"""CLI: `python -m nos_tpu.obs` — explain pods/plans, report SLO
verdicts, render the fleet scoreboard or the chip-second waste
waterfall, dump the recorder, or self-test the subsystem.

    python -m nos_tpu.obs explain pod <ns>/<name> --snapshot flight.json
    python -m nos_tpu.obs explain plan [--kind slice] --url http://host:8080
    python -m nos_tpu.obs slo --snapshot bench.json
    python -m nos_tpu.obs top --url http://host:8080 [--watch 5]
    python -m nos_tpu.obs waste --url http://host:8080
    python -m nos_tpu.obs dump --url http://host:8080
    python -m nos_tpu.obs --selftest

Snapshot sources: `--snapshot FILE` (a saved /debug/flightrecorder,
/snapshot, /debug/slo, or bench.py payload; `-` = stdin) or `--url
ADDR` (fetches the right endpoint live: /debug/flightrecorder for
explain/dump, /debug/slo for slo, /snapshot for top).  `--selftest`
runs an in-process end-to-end check of the span API, journal, explain
reconstruction, time-series sampler, and SLO engine — the CI hook in
scripts/check.sh.
"""

from __future__ import annotations

import argparse
import json
import sys

from . import explain_plan, explain_pod
from . import journal as J


def _load_snapshot(args: argparse.Namespace,
                   endpoint: str = "/debug/flightrecorder") -> dict:
    snapshot: dict
    if args.url:
        from urllib.request import urlopen

        url = args.url.rstrip("/") + endpoint
        with urlopen(url, timeout=10.0) as resp:   # noqa: S310 — operator URL
            snapshot = json.load(resp)
            return snapshot
    if args.snapshot == "-":
        snapshot = json.load(sys.stdin)
        return snapshot
    if args.snapshot:
        with open(args.snapshot, encoding="utf-8") as fh:
            snapshot = json.load(fh)
            return snapshot
    raise SystemExit(
        "no snapshot source: pass --snapshot FILE (or '-') or --url ADDR "
        f"(the health server serves {endpoint})")


def _find_slo_block(payload: dict) -> dict | None:
    """The SLO report inside any payload shape we serve: a /debug/slo
    body (verdicts at top level), a flight/state snapshot or bench
    output carrying an "slo" block, or bench.py's single JSON nesting
    the utilization block."""
    if "verdicts" in payload and "objectives" in payload:
        return payload
    for holder in (payload, payload.get("utilization", {})):
        block = holder.get("slo") if isinstance(holder, dict) else None
        if isinstance(block, dict) and "verdicts" in block:
            return block
    return None


def _fmt(v: object, digits: int = 2) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{digits}f}"
    return str(v)


def _find_waste_block(payload: dict) -> dict | None:
    """The chip-second waterfall inside any payload shape we serve: a
    flight/state snapshot carrying "waste", a bench_utilization result
    (top level), or bench.py's single JSON nesting the utilization
    block."""
    for holder in (payload, payload.get("utilization", {})):
        block = holder.get("waste") if isinstance(holder, dict) else None
        if isinstance(block, dict) and "pools" in block:
            return block
    return None


def _find_requests_block(payload: dict) -> dict | None:
    """The per-service request-path stats inside any payload shape we
    serve: a bench_requests report carrying "requests" (rows keyed by
    service, written by its Sim._request_stats) at top level or nested
    under the utilization block."""
    for holder in (payload, payload.get("utilization", {})):
        block = holder.get("requests") if isinstance(holder, dict) \
            else None
        if isinstance(block, dict) and block and \
                all(isinstance(row, dict) for row in block.values()):
            return block
    return None


def _rejecting_plugin(journal: list[dict], slo_class: str) -> str:
    """Newest pod-rejected record of this workload class → its plugin
    (or the dominant per-node reason): the one-command join from an SLO
    breach to the decision that causes it."""
    for rec in reversed(journal):
        if rec.get("category") != "pod-rejected":
            continue
        attrs = rec.get("attrs", {})
        if attrs.get("class") != slo_class:
            continue
        if attrs.get("plugin"):
            return str(attrs["plugin"])
        counts = attrs.get("reason_counts") or {}
        if counts:
            top = max(counts.items(), key=lambda kv: kv[1])[0]
            return str(top).split(":")[0]
        return attrs.get("reason") or "unknown"
    return ""


def _request_breach_cause(journal: list[dict], service: str
                          ) -> list[str]:
    """Join a request-latency breach to its cause, mirroring the
    breach→rejecting-plugin join: a REQUEST_SHED record for the service
    means the router is saturating (admission queues still full after
    the retry ladder); an autoscaler scale-up for one of the service's
    pools means KV pressure with capacity already on the way; neither
    on record points at the scheduler path instead (new replicas
    pending placement)."""
    def _mine(rec: dict) -> bool:
        subj = str(rec.get("subject", ""))
        return subj.split("/")[-1] == service or service in subj

    shed: dict | None = None
    scale: dict | None = None
    for rec in reversed(journal):
        cat = rec.get("category")
        if shed is None and cat == J.REQUEST_SHED and _mine(rec):
            shed = rec
        elif scale is None and cat == J.AUTOSCALE and _mine(rec) \
                and rec.get("attrs", {}).get("direction") == "up":
            scale = rec
        if shed is not None and scale is not None:
            break
    lines: list[str] = []
    if shed is not None:
        a = shed.get("attrs", {})
        lines.append(
            f"router saturation: {shed.get('subject')} shed "
            f"rid={a.get('rid')} phase={a.get('phase')} after "
            f"{a.get('retries')} retries — replicas full past the "
            "retry ladder")
    if scale is not None:
        a = scale.get("attrs", {})
        lines.append(
            f"scale-up in flight: {scale.get('subject')} "
            f"+{a.get('count')} replica(s) — KV pressure, capacity "
            "catching up")
    if not lines:
        lines.append(
            "no shed or scale-up on record — suspect the scheduler "
            "path: check the serving tier's schedule-latency verdict "
            "and `explain pod` a pending replica")
    return lines


def cmd_slo(payload: dict) -> int:
    """Render the SLO report: per objective/class — value vs target,
    burn rates, budget remaining, breach verdict (journal-joined to the
    rejecting plugin when the payload carries a journal)."""
    block = _find_slo_block(payload)
    if block is None:
        print("payload carries no SLO report — is an engine installed "
              "(Main.attach_slo) / did the bench run with SLOs?",
              file=sys.stderr)
        return 1
    journal = payload.get("journal", [])
    verdicts = block.get("verdicts", [])
    print(f"SLO report (fast window {block.get('fast_window_s')}s, "
          f"slow {block.get('slow_window_s')}s, burn threshold "
          f"{block.get('burn_threshold')}):")
    if not verdicts:
        print("  no verdicts yet (engine has not evaluated a window)")
        return 0
    breached = 0
    for v in verdicts:
        state = "BREACH" if v.get("breached") else "ok"
        cls = v.get("class") or "-"
        line = (f"  [{state}] {v.get('objective')} class={cls}: "
                f"value={_fmt(v.get('value'), 3)} "
                f"target={_fmt(v.get('target'), 3)} "
                f"burn fast/slow={_fmt(v.get('burn_fast'))}"
                f"/{_fmt(v.get('burn_slow'))} "
                f"budget remaining={_fmt(v.get('budget_remaining'))}")
        print(line)
        if v.get("breached"):
            breached += 1
            if v.get("metric") == "nos_tpu_request_latency_seconds":
                for cause in _request_breach_cause(journal, cls):
                    print(f"         {cause}")
                continue
            plugin = _rejecting_plugin(journal, cls)
            if plugin:
                print(f"         rejecting plugin for class {cls}: "
                      f"{plugin} — `explain pod` a pending pod of this "
                      "class for the per-node chain")
    print(f"{breached} breached / {len(verdicts)} verdict(s)")
    return 0


def _print_tier_rows(pending: dict, block: dict | None) -> None:
    """Per-tier scoreboard rows (serving / batch / best-effort):
    pending pods, worst schedule-latency p99 and the smallest SLO
    budget remaining among the tier's classes — the one-shot view that
    answers "is the protected tier healthy and who is waiting behind
    it" (docs/serving.md)."""
    from nos_tpu.utils.pod_util import class_tier

    pend_by_tier: dict[str, int] = {}
    for cls, n in pending.items():
        tier = class_tier(cls)
        pend_by_tier[tier] = pend_by_tier.get(tier, 0) + n
    p99: dict[str, float | None] = {}
    budget: dict[str, float | None] = {}
    breached: dict[str, bool] = {}
    for v in (block or {}).get("verdicts", []):
        if v.get("metric") != "nos_tpu_schedule_latency_seconds":
            continue
        tier = class_tier(str(v.get("class") or ""))
        val, rem = v.get("value"), v.get("budget_remaining")
        if val is not None and (p99.get(tier) is None
                                or val > p99[tier]):
            p99[tier] = val
        if rem is not None and (budget.get(tier) is None
                                or rem < budget[tier]):
            budget[tier] = rem
        breached[tier] = breached.get(tier, False) \
            or bool(v.get("breached"))
    print("tier           pending  p99(s)  budget")
    for tier in ("serving", "batch", "best-effort"):
        state = " [BREACH]" if breached.get(tier) else ""
        print(f"  {tier:<12} {pend_by_tier.get(tier, 0):>7} "
              f"{_fmt(p99.get(tier), 3):>7} "
              f"{_fmt(budget.get(tier)):>7}{state}")


def cmd_top(payload: dict) -> int:
    """One-shot fleet scoreboard from a /snapshot payload: utilization,
    per-pool fragmentation, pending-by-class, SLO budget remaining."""
    state = payload.get("state")
    if not isinstance(state, dict):
        print("payload carries no cluster state — `obs top` wants the "
              "/snapshot endpoint (or its saved JSON), not "
              "/debug/flightrecorder", file=sys.stderr)
        return 1
    from nos_tpu.api import constants as C
    from nos_tpu.kube.client import KIND_NODE, KIND_POD
    from nos_tpu.kube.resources import pod_request
    from nos_tpu.kube.serialize import load_state
    from nos_tpu.topology.profile import free_chip_equivalents
    from nos_tpu.utils.pod_util import workload_class

    from .ledger import stranded_fraction

    api = load_state(state)
    pools: dict[str, dict] = {}
    node_pool: dict[str, str] = {}
    cap_by_node: dict[str, float] = {}
    for node in api.list(KIND_NODE):
        pool = node.metadata.labels.get(C.LABEL_POD_ID, "") or "-"
        node_pool[node.metadata.name] = pool
        agg = pools.setdefault(pool, {"hosts": 0, "chips": 0.0,
                                      "used": 0.0})
        agg["hosts"] += 1
        try:
            cap = float(
                node.metadata.labels.get(C.LABEL_CHIP_COUNT, "0") or 0)
        except ValueError:
            cap = 0.0
        cap_by_node[node.metadata.name] = cap
        agg["chips"] += cap
    pending: dict[str, int] = {}
    used_by_node: dict[str, float] = {}
    for pod in api.list(KIND_POD):
        if not pod.spec.node_name:
            cls = workload_class(pod)
            pending[cls] = pending.get(cls, 0) + 1
            continue
        used_by_node.setdefault(pod.spec.node_name, 0.0)
        used_by_node[pod.spec.node_name] += \
            free_chip_equivalents(pod_request(pod))
    # per-pool free-by-host + the offline stranded set (hosts already
    # running something — free capacity a whole-host/aligned-window
    # demand cannot use without a re-carve).  The ARITHMETIC is the
    # ledger's shared stranded-free helper; the live scheduler derives
    # its stranded set from rejection verdicts instead
    # (docs/observability.md, "The waterfall").
    free_by_pool: dict[str, dict[str, float]] = {}
    busy_by_pool: dict[str, set[str]] = {}
    for name, pool in node_pool.items():
        used = used_by_node.get(name, 0.0)
        pools[pool]["used"] += used
        free_by_pool.setdefault(pool, {})[name] = \
            max(0.0, cap_by_node.get(name, 0.0) - used)
        if used > 0:
            busy_by_pool.setdefault(pool, set()).add(name)

    total_chips = sum(p["chips"] for p in pools.values())
    total_used = sum(p["used"] for p in pools.values())
    util = total_used / total_chips if total_chips else 0.0
    print(f"fleet: {sum(p['hosts'] for p in pools.values())} host(s), "
          f"{total_chips:g} chips, utilization {util:.3f}")
    print("pool             hosts  chips   used   free  util  frag")
    for pool in sorted(pools):
        p = pools[pool]
        free = max(0.0, p["chips"] - p["used"])
        putil = p["used"] / p["chips"] if p["chips"] else 0.0
        frag = stranded_fraction(free_by_pool.get(pool, {}),
                                 busy_by_pool.get(pool, set()))
        print(f"{pool:<16} {p['hosts']:>5} {p['chips']:>6g} "
              f"{p['used']:>6.1f} {free:>6.1f} {putil:>5.2f} "
              f"{max(0.0, frag):>5.2f}")
    waste = _find_waste_block(payload)
    if waste is not None and waste.get("pools"):
        print("waste waterfall (chip-seconds, share of capacity):")
        for pool in sorted(waste["pools"]):
            wp = waste["pools"][pool]
            fr = wp.get("fractions", {})
            top = sorted(((c, f) for c, f in fr.items()
                          if c != "productive" and f > 0.0),
                         key=lambda kv: -kv[1])[:3]
            steps = "  ".join(f"{c}={f * 100:.1f}%" for c, f in top) \
                or "no waste recorded"
            print(f"  {pool:<14} productive="
                  f"{fr.get('productive', 0.0) * 100:.1f}%  {steps}")
        print("  (`obs waste` ranks the sources and names culprits)")
    if pending:
        print("pending by class:")
        for cls in sorted(pending):
            print(f"  {cls:<20} {pending[cls]}")
    else:
        print("pending by class: none")
    reqs_block = _find_requests_block(payload)
    if reqs_block:
        trace_s = payload.get("trace_seconds")
        print("requests by service:")
        print("  service             req/s  ttft-p99  p99(s)  kv-occ"
              "  shed")
        for key in sorted(reqs_block):
            row = reqs_block[key]
            rate = None
            if isinstance(trace_s, (int, float)) and trace_s > 0:
                rate = float(row.get("completed", 0)) / trace_s
            print(f"  {key:<18} {_fmt(rate):>6} "
                  f"{_fmt(row.get('ttft_p99_s'), 3):>9} "
                  f"{_fmt(row.get('p99_s'), 3):>7} "
                  f"{_fmt(row.get('occupancy_mean_max')):>7} "
                  f"{row.get('shed', 0):>5}")
    block = _find_slo_block(payload)
    _print_tier_rows(pending, block)
    if block is not None and block.get("verdicts"):
        print("SLO budget remaining:")
        for v in block["verdicts"]:
            state_s = "BREACH" if v.get("breached") else "ok"
            print(f"  {v.get('objective')}/{v.get('class') or '-':<16} "
                  f"{_fmt(v.get('budget_remaining'))} [{state_s}]")
    return 0


def _newest(journal: list[dict], category: str,
            subject: str | None = None,
            attr_match: dict | None = None) -> dict | None:
    """Newest journal record of `category` matching subject/attrs."""
    for rec in reversed(journal):
        if rec.get("category") != category:
            continue
        if subject is not None and rec.get("subject") != subject:
            continue
        attrs = rec.get("attrs", {})
        if attr_match and any(attrs.get(k) != v
                              for k, v in attr_match.items()):
            continue
        return rec
    return None


def _waste_culprit(journal: list[dict], category: str,
                   evidence: dict) -> list[str]:
    """Join one waste category's culprit evidence to its journal
    record — the same flight-recorder-first workflow as `explain`/`slo`
    (each category's evidence keys are written by its owning call
    site)."""
    lines: list[str] = []
    if category == "frag_stranded" and evidence.get("class"):
        # Ranked culprits: when several classes strand the same pool the
        # evidence carries them ordered by stranded chip-seconds (the
        # scheduler's per-class integral) — the class that has waited
        # with the most blocked chips the longest leads, NOT whichever
        # rejection happens to be newest.  Old snapshots without the
        # ranking degrade to the single-class join.
        ranked = evidence.get("classes") or [
            {"class": evidence["class"],
             "rejected_nodes": evidence.get("rejected_nodes", "?")}]
        cls = str(ranked[0].get("class", evidence["class"]))
        displaced = (f" (displaced: {evidence['displaced_cause']})"
                     if evidence.get("displaced_cause") else "")
        lines.append(f"culprit class {cls}: rejected on "
                     f"{evidence.get('rejected_nodes', '?')} "
                     f"node(s){displaced}")
        for row in ranked[1:]:
            lines.append(
                f"also stranding: class {row.get('class', '?')} "
                f"({row.get('stranded_chip_seconds', '?')} stranded "
                "chip-s)")
        rec = _newest(journal, J.POD_REJECTED, attr_match={"class": cls})
        if rec is not None:
            attrs = rec.get("attrs", {})
            counts = attrs.get("reason_counts") or {}
            why = (max(counts.items(), key=lambda kv: kv[1])[0]
                   if counts else attrs.get("message", ""))
            lines.append(f"newest rejection ({rec['subject']}): {why}")
            lines.append(f"next: `obs explain pod {rec['subject']}`")
        # Join to the defrag plane: the proposal that would (or did)
        # unlock this frag source, so the operator's next move is
        # named instead of implied.
        prop = _newest(journal, J.DEFRAG_APPLIED,
                       attr_match={"demand_class": cls}) \
            or _newest(journal, J.DEFRAG_APPLIED) \
            or _newest(journal, J.DEFRAG_PROPOSED) \
            or _newest(journal, J.DEFRAG_REJECTED)
        if prop is not None:
            attrs = prop.get("attrs", {})
            verb = {J.DEFRAG_APPLIED: "applied",
                    J.DEFRAG_PROPOSED: "proposed",
                    J.DEFRAG_REJECTED: "rejected"}[prop["category"]]
            lines.append(
                f"defrag: proposal {prop['subject']} ({verb}) — "
                f"hosts {attrs.get('hosts', '?')}, "
                f"{attrs.get('unlocked_chips', '?')} chips unlocked, "
                f"payback {attrs.get('payback', attrs.get('reason', '?'))}")
        else:
            lines.append("defrag: no proposal on record — enable "
                         "defrag_enabled (PartitionerConfig) to reclaim "
                         "this automatically")
    elif category in ("gang_wait", "drain") and evidence.get("gang"):
        gang = str(evidence["gang"])
        verb = ("assembly stalled" if category == "gang_wait"
                else "window bought by drain eviction")
        if evidence.get("displaced_cause"):
            # a displaced victim failing to rebind is a recovery
            # problem, not ordinary gang assembly — name the kill
            verb += f" (displaced: {evidence['displaced_cause']})"
        lines.append(f"culprit gang {gang}: {verb}")
        rec = _newest(journal, J.GANG_REJECTED, subject=gang)
        if rec is not None:
            attrs = rec.get("attrs", {})
            lines.append(
                f"newest gang verdict: {attrs.get('message', '?')} "
                f"(members: {attrs.get('members_total', '?')})")
    elif category == "actuation":
        kind = str(evidence.get("kind", "") or "?")
        lines.append(f"culprit plan: kind={kind} "
                     f"plan_id={evidence.get('plan_id', '?')} "
                     f"(node {evidence.get('node', '?')})")
        rec = _newest(journal, J.PLAN_CYCLE, subject=kind)
        if rec is not None:
            attrs = rec.get("attrs", {})
            lines.append(f"newest plan cycle: pods={attrs.get('pods')} "
                         f"actuated={attrs.get('actuated')} — "
                         "`obs explain plan` for the budget breakdown")
    elif category == "quarantine" and evidence.get("node"):
        node = str(evidence["node"])
        lines.append(f"culprit node {node}: "
                     f"{evidence.get('reason', '?')}")
        rec = _newest(journal, J.QUARANTINED, subject=node)
        if rec is not None:
            lines.append(f"quarantined (seq {rec['seq']}): "
                         f"{rec.get('attrs', {}).get('reason', '?')}")
    elif category == "provisioning":
        node = str(evidence.get("node", "") or "?")
        lines.append(f"culprit node {node}: create requested from "
                     f"{evidence.get('machine_class', '?')}/"
                     f"{evidence.get('zone', '?')}, not usable yet "
                     "(cloud is slow or stocked out — NOT idle slack)")
        stock = _newest(journal, J.PROVISION_STOCKOUT)
        if stock is not None:
            lines.append(f"newest breaker transition: {stock['subject']} "
                         f"-> {stock.get('attrs', {}).get('state', '?')}")
        rec = _newest(journal, J.PROVISION_REQUESTED, subject=node) \
            or _newest(journal, J.PROVISION_REQUESTED)
        if rec is not None:
            attrs = rec.get("attrs", {})
            lines.append(f"newest create request ({rec['subject']}): "
                         f"pool {attrs.get('pool', '?')} op "
                         f"{attrs.get('op', '?')}")
        lines.append("next: `obs capacity` for breaker states and "
                     "in-flight creates")
    elif category == "quota_stranded" and evidence.get("class"):
        cls = str(evidence["class"])
        lines.append(f"culprit class {cls}: "
                     f"{evidence.get('blocked_chips', '?')} chip(s) of "
                     "demand blocked by borrowing limits")
        rec = _newest(journal, J.QUOTA_HOL_CLAIM) or _newest(
            journal, J.POD_REJECTED, attr_match={"class": cls})
        if rec is not None:
            lines.append(f"newest quota decision ({rec['category']}): "
                         f"{rec['subject']}")
    return lines


def cmd_waste(payload: dict) -> int:
    """Render the chip-second waste waterfall: per-pool category
    breakdown with the conservation verdict, then the fleet's top waste
    sources each joined to its journal evidence."""
    from .ledger import conservation_ok, waste_ranking

    block = _find_waste_block(payload)
    if block is None:
        print("payload carries no waste waterfall — fetch "
              "/debug/flightrecorder (or /snapshot) from a live main, "
              "or pass a bench_utilization/bench.py result JSON",
              file=sys.stderr)
        return 1
    journal = payload.get("journal", [])
    pools = block.get("pools", {})
    if not pools:
        print("waste waterfall: no pools observed yet (has a scheduler "
              "cycle run?)")
        return 0
    conserved = conservation_ok(block)
    print("chip-second waste waterfall "
          f"(conservation: {'ok' if conserved else 'VIOLATED'}):")
    for pool in sorted(pools):
        p = pools[pool]
        cap_s = p.get("capacity_chip_seconds", 0.0)
        print(f"pool {pool}: {_fmt(p.get('capacity_chips'), 0)} chips x "
              f"{_fmt(p.get('elapsed_s'), 1)}s = {cap_s:.1f} chip-s "
              f"(delta {p.get('conservation_delta', 0.0):+.2e})")
        rows = sorted(p.get("chip_seconds", {}).items(),
                      key=lambda kv: -kv[1])
        for cat, secs in rows:
            frac = p.get("fractions", {}).get(cat, 0.0)
            print(f"  {cat:<16} {secs:>12.1f}  {frac * 100:>5.1f}%")
    ranked = waste_ranking(block)
    if not ranked:
        print("no waste recorded — every chip-second was productive")
        return 0
    print("top waste sources (fleet):")
    for i, row in enumerate(ranked, 1):
        cat = str(row["category"])
        print(f"  {i}. {cat:<16} {row['chip_seconds']:>12.1f} chip-s "
              f"{row['fraction'] * 100:>5.1f}%")
        evidence: dict = {}
        for pool in pools.values():
            ev = pool.get("evidence", {}).get(cat)
            if ev:
                evidence = ev
                break
        for line in _waste_culprit(journal, cat, evidence):
            print(f"     {line}")
    flip = block.get("quota_last_flip")
    if flip:
        print(f"newest quota flip: {flip.get('pod')} "
              f"({'borrow' if flip.get('borrowed') else 'reclaim'}, "
              f"namespace {flip.get('namespace')})")
    return 0 if conserved else 1


def cmd_capacity(payload: dict) -> int:
    """Render the capacity plane's state: per-pool inventory vs the
    durable size record, stockout breaker states, in-flight creates,
    and the provisioning counters — the surface the troubleshooting
    runbook sends operators to when pending demand coexists with an
    `idle_no_demand` (or `provisioning`) deficit."""
    block = payload.get("capacity")
    if not isinstance(block, dict):
        print("payload carries no capacity block — the provisioner is "
              "disabled (off means off) or this snapshot predates it; "
              "fetch /debug/flightrecorder from the provisioner main",
              file=sys.stderr)
        return 1
    journal = payload.get("journal", [])
    pools = block.get("pools", {})
    print("capacity plane:")
    print(f"  pending demand {_fmt(block.get('pending_demand_chips'), 1)} "
          f"chips | free {_fmt(block.get('free_chips'), 1)} | arriving "
          f"{_fmt(block.get('arriving_chips'), 1)} | deficit "
          f"{_fmt(block.get('deficit_chips'), 1)}")
    for pool in sorted(pools):
        p = pools[pool]
        gap = int(p.get("recorded_size", 0)) - int(p.get("active", 0))
        note = f" ({gap} vacant)" if gap > 0 else ""
        print(f"pool {pool}: {p.get('active', 0)}/"
              f"{p.get('recorded_size', 0)} hosts{note}, "
              f"{p.get('spares', 0)} spare(s), "
              f"{_fmt(p.get('free_chips'), 1)} free chips "
              f"[{p.get('machine_class', '?')}/{p.get('zone', '?')}]")
    breakers = block.get("breakers", {})
    if breakers:
        print("stockout breakers:")
        for key in sorted(breakers):
            b = breakers[key]
            retry = (f", probe in {_fmt(b.get('retry_in_s'), 1)}s"
                     if b.get("state") == "open" else "")
            print(f"  {key}: {b.get('state', '?')} "
                  f"(streak {b.get('streak', 0)}{retry})")
    pending = block.get("pending_creates", [])
    if pending:
        print("in-flight creates:")
        for row in pending:
            print(f"  {row.get('name', '?')} -> pool "
                  f"{row.get('pool', '?')} "
                  f"[{row.get('machine_class', '?')}/"
                  f"{row.get('zone', '?')}] {row.get('status', '?')} "
                  f"for {_fmt(row.get('age_s'), 1)}s")
    counters = block.get("counters", {})
    if counters:
        print("counters: " + ", ".join(
            f"{k}={counters[k]}" for k in sorted(counters)))
    # journal joins: the newest breaker transition and failure tell the
    # operator WHY capacity is not arriving, not just that it is not
    stock = _newest(journal, J.PROVISION_STOCKOUT)
    if stock is not None:
        print(f"newest breaker transition: {stock['subject']} -> "
              f"{stock.get('attrs', {}).get('state', '?')} "
              f"(seq {stock['seq']})")
    failed = _newest(journal, J.PROVISION_FAILED)
    if failed is not None:
        print(f"newest abandoned create: {failed['subject']} "
              f"({failed.get('attrs', {}).get('reason', '?')})")
    borrow = _newest(journal, J.SPARE_BORROWED)
    if borrow is not None:
        attrs = borrow.get("attrs", {})
        print(f"newest cross-pool borrow: {borrow['subject']} -> pool "
              f"{attrs.get('pool', '?')} index "
              f"{attrs.get('host_index', '?')}")
    return 0


def selftest() -> int:
    """In-process zero-cluster check: spans nest and propagate, the
    journal stays bounded and ordered, explain reconstructs a rejection
    chain naming the plugin, the sampler stays bounded and rolls the
    max window, and an injected latency regression flips an SLO breach
    that recovers.  Prints ok/FAIL, returns rc."""
    from nos_tpu.exporter.metrics import Registry
    from .journal import (
        POD_BOUND, POD_REJECTED, SLO_BREACH, SLO_RECOVERED,
        DecisionJournal,
    )
    from .slo import LATENCY, SLOEngine, SLOObjective
    from .timeseries import TimeSeriesSampler
    from .trace import RingExporter, Tracer

    failures: list[str] = []
    now = [0.0]

    def clock() -> float:
        now[0] += 0.5
        return now[0]

    tracer = Tracer(clock=clock, ring=RingExporter(maxlen=4))
    journal = DecisionJournal(maxlen=8, clock=clock)

    # span nesting + context propagation
    with tracer.span("outer", stage="selftest") as outer:
        with tracer.span("inner") as inner:
            if inner.trace_id != outer.trace_id:
                failures.append("child span did not inherit trace id")
            if inner.parent_id != outer.span_id:
                failures.append("child span did not link to parent")
            journal.record(POD_REJECTED, "default/victim",
                           reason="selftest",
                           message="no fit anywhere",
                           nodes={"host-0": "NodeResourcesFit: "
                                            "insufficient nos.tpu/slice-2x2"},
                           reason_counts={})
    if journal.events()[-1].trace_id != outer.trace_id:
        failures.append("journal record did not capture trace context")

    # ring bound
    for i in range(10):
        with tracer.span(f"churn-{i}"):
            pass
    if len(tracer.ring) != 4:
        failures.append(f"ring not bounded: {len(tracer.ring)} != 4")
    if tracer.ring.dropped != 8:
        failures.append(f"ring dropped miscounted: {tracer.ring.dropped}")

    # journal bound + total order
    for i in range(20):
        journal.record(POD_BOUND, f"default/p{i}", node="host-0")
    if len(journal) != 8:
        failures.append(f"journal not bounded: {len(journal)} != 8")
    seqs = [r.seq for r in journal.events()]
    if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
        failures.append(f"journal order broken: {seqs}")

    # explain reconstructs the rejection (fresh journal: the churn above
    # evicted the rejection record — that eviction is itself the test)
    journal2 = DecisionJournal(maxlen=8, clock=clock)
    journal2.record(POD_REJECTED, "default/stuck",
                    reason="", message="no fit",
                    nodes={"host-0": "NodeResourcesFit: insufficient "
                                     "nos.tpu/slice-2x2"},
                    reason_counts={})
    snapshot = {"spans": tracer.ring.dump(), "journal": journal2.dump()}
    text = "\n".join(explain_pod(snapshot, "default/stuck"))
    if "NodeResourcesFit" not in text or "host-0" not in text:
        failures.append(f"explain lost the rejecting plugin:\n{text}")

    # time-series sampler: bounded ring + windowed max reset on tick
    ts_now = [0.0]
    reg = Registry()
    sampler = TimeSeriesSampler(registry=reg, maxlen=4,
                                clock=lambda: ts_now[0])
    reg.observe("nos_tpu_selftest_seconds", 5.0)
    for i in range(6):
        ts_now[0] += 1.0
        point = sampler.tick()
    if len(sampler) != 4:
        failures.append(f"sampler not bounded: {len(sampler)} != 4")
    if point.get("nos_tpu_selftest_seconds_max") != 0.0:
        failures.append("windowed max did not reset on sampler tick")

    # SLO engine: a latency regression breaches, recovery journals
    slo_now = [0.0]
    slo_clock = lambda: slo_now[0]  # noqa: E731
    reg2 = Registry()
    journal3 = DecisionJournal(maxlen=64, clock=slo_clock)
    engine = SLOEngine(
        TimeSeriesSampler(registry=reg2, clock=slo_clock),
        [SLOObjective(name="selftest-latency", kind=LATENCY,
                      metric="nos_tpu_selftest_latency_seconds",
                      target=0.05, each_label="class")],
        fast_window_s=10.0, slow_window_s=30.0, clock=slo_clock)
    from . import scoped

    with scoped(journal=journal3):
        for phase, latency in ((40, 0.01), (40, 2.0), (80, 0.01)):
            for _ in range(phase):
                slo_now[0] += 1.0
                reg2.observe("nos_tpu_selftest_latency_seconds", latency,
                             labels={"class": "selftest"})
                engine.tick()
    cats = [r.category for r in journal3.events()
            if r.category in (SLO_BREACH, SLO_RECOVERED)]
    if cats != [SLO_BREACH, SLO_RECOVERED]:
        failures.append(
            f"SLO breach/recovery sequence wrong: {cats}")
    breach = next((r for r in journal3.events()
                   if r.category == SLO_BREACH), None)
    if breach is not None and breach.attrs.get("slo_class") != "selftest":
        failures.append("SLO breach lost the breaching class")
    # quantile estimator sanity on the registry itself
    q99 = reg2.quantile("nos_tpu_selftest_latency_seconds", 0.5,
                        labels={"class": "selftest"})
    if q99 is None:
        failures.append("registry quantile returned None with samples")

    # chip-second ledger: exact conservation under category churn, hold
    # lifecycle bounded, stranded-free helper arithmetic
    from .ledger import (
        ChipSecondLedger, conservation_ok, stranded_free, waste_ranking,
    )

    led_now = [0.0]
    ledger = ChipSecondLedger(clock=lambda: led_now[0])
    ledger.set_hold("host-0", "quarantine", owner="slice", reason="test")
    ledger.observe({"pod-0": {"capacity": 16.0,
                              "categories": {"productive": 12.0,
                                             "frag_stranded": 4.0}}})
    led_now[0] += 10.0
    ledger.observe({"pod-0": {"capacity": 16.0,
                              "categories": {"productive": 16.0}}})
    led_now[0] += 5.0
    ledger.observe({"pod-0": {"capacity": 16.0, "categories": {}}})
    ledger.clear_hold("host-0", "quarantine", owner="slice")
    report = ledger.report()
    pool = report["pools"]["pod-0"]
    if pool["chip_seconds"].get("productive") != 12.0 * 10 + 16.0 * 5:
        failures.append(f"ledger productive accrual wrong: {pool}")
    if not conservation_ok(report):
        failures.append(f"ledger conservation violated: {pool}")
    if ledger.hold_count() != 0:
        failures.append("ledger hold lifecycle leaked")
    if waste_ranking(report)[0]["category"] != "frag_stranded":
        failures.append("waste ranking did not rank the frag step first")
    if stranded_free({"a": 3.0, "b": 5.0}, {"b"}) != 5.0:
        failures.append("stranded_free arithmetic broken")

    if failures:
        print("obs selftest: FAIL", file=sys.stderr)
        for f in failures:
            print(f"  - {f}", file=sys.stderr)
        return 1
    print("obs selftest: ok (spans, journal, explain, timeseries, slo, "
          "ledger)")
    return 0


def _watch_top(args: argparse.Namespace, endpoint: str,
               sleep=None) -> int:
    """`obs top --watch N`: periodic scoreboard refresh from a live
    snapshot source, clearing the screen between frames (one-shot
    behavior is unchanged without --watch).  `--frames K` bounds the
    loop for tests/scripts; ^C exits cleanly either way."""
    if sleep is None:
        import time as _time

        # interactive CLI pacing, not decision-plane code: the frames
        # themselves come from the live endpoint, nothing here feeds a
        # deterministic seed
        sleep = _time.sleep  # noslint: N002 — operator-facing watch loop, not deterministic code
    frame = 0
    rc = 0
    try:
        while True:
            try:
                snapshot = _load_snapshot(args, endpoint=endpoint)
            except (OSError, json.JSONDecodeError) as exc:
                print(f"cannot read snapshot: {exc}", file=sys.stderr)
                return 1
            frame += 1
            # ANSI clear + home, like watch(1); a dumb pipe just sees
            # frames separated by the escape (harmless in logs)
            print("\x1b[2J\x1b[H", end="")
            print(f"obs top --watch {args.watch:g} "
                  f"(frame {frame}{f'/{args.frames}' if args.frames else ''})")
            rc = cmd_top(snapshot)
            if args.frames and frame >= args.frames:
                return rc
            sleep(args.watch)
    except KeyboardInterrupt:
        return rc


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m nos_tpu.obs",
        description=__doc__.split("\n")[0])
    parser.add_argument("--selftest", action="store_true",
                        help="run the in-process subsystem check")
    sub = parser.add_subparsers(dest="command")

    p_explain = sub.add_parser("explain", help="reconstruct a causal answer")
    ex_sub = p_explain.add_subparsers(dest="what", required=True)
    p_pod = ex_sub.add_parser("pod", help="why is this pod pending?")
    p_pod.add_argument("key", help="pod as <namespace>/<name>")
    p_plan = ex_sub.add_parser("plan", help="where did the plan budget go?")
    p_plan.add_argument("--kind", default=None,
                        help="partitioning kind (slice|timeshare)")
    p_dump = sub.add_parser("dump", help="print the raw flight snapshot")
    p_slo = sub.add_parser(
        "slo", help="SLO verdicts: per-class p99, burn rates, budget")
    p_top = sub.add_parser(
        "top", help="one-shot fleet scoreboard (utilization, "
                    "fragmentation, waste waterfall, pending, budget)")
    p_top.add_argument(
        "--watch", type=float, default=0.0, metavar="N",
        help="refresh every N seconds from --url (clears the screen "
             "between frames; one-shot without it)")
    p_top.add_argument(
        "--frames", type=int, default=0, metavar="K",
        help="with --watch: stop after K frames (0 = until ^C; "
             "tests/scripts use it)")
    p_waste = sub.add_parser(
        "waste", help="chip-second waste waterfall: per-pool category "
                      "breakdown, conservation verdict, ranked culprits")
    p_capacity = sub.add_parser(
        "capacity", help="capacity plane: pool inventory vs recorded "
                         "size, stockout breakers, in-flight creates")
    for p in (p_pod, p_plan, p_dump, p_slo, p_top, p_waste, p_capacity):
        p.add_argument("--snapshot", default="",
                       help="saved snapshot JSON ('-'=stdin)")
        p.add_argument("--url", default="",
                       help="live health server base URL")

    args = parser.parse_args(argv)
    if args.selftest:
        return selftest()
    if args.command is None:
        parser.print_help()
        return 2
    # `slo` and `waste` fetch the FLIGHT snapshot, not their dedicated
    # blocks: the flight payload embeds the report AND the journal, so
    # the breach→rejecting-plugin and waste→culprit joins work on the
    # live-URL path too
    endpoint = {"top": "/snapshot"}.get(
        args.command, "/debug/flightrecorder")
    if args.command == "top" and args.watch > 0.0:
        return _watch_top(args, endpoint)
    try:
        snapshot = _load_snapshot(args, endpoint=endpoint)
    except json.JSONDecodeError as exc:
        print(f"snapshot is not valid JSON: {exc}", file=sys.stderr)
        return 1
    except OSError as exc:   # unreadable file, unreachable --url
        print(f"cannot read snapshot: {exc}", file=sys.stderr)
        return 1
    if not isinstance(snapshot, dict):
        print("snapshot is not a flight-recorder payload "
              "(expected a JSON object)", file=sys.stderr)
        return 1
    if args.command == "dump":
        print(json.dumps(snapshot, indent=2))
        return 0
    if args.command == "slo":
        return cmd_slo(snapshot)
    if args.command == "top":
        return cmd_top(snapshot)
    if args.command == "waste":
        return cmd_waste(snapshot)
    if args.command == "capacity":
        return cmd_capacity(snapshot)
    if args.what == "pod":
        if "/" not in args.key:
            print("pod key must be <namespace>/<name>", file=sys.stderr)
            return 2
        lines = explain_pod(snapshot, args.key)
    else:
        lines = explain_plan(snapshot, kind=args.kind)
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
