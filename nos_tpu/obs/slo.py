"""Declarative SLOs over the sampled registry: error-budget burn rates.

An ``SLOObjective`` states a promise — "serve-class p99 schedule
latency stays under 50 ms", "fleet utilization stays above 0.9",
"rebind evictions stay under 0.2/s" — and the ``SLOEngine`` judges it
the SRE way: not on instantaneous values (one slow pod at 3 a.m. must
not page) but on **error-budget burn rates over two windows**.  With
compliance target ``c`` (default 0.99), the budget is the ``1 - c``
fraction of events allowed to be bad; the burn rate is how many times
faster than that allowance the window actually spent it.  A breach
requires BOTH the fast window (is it happening *now*?) and the slow
window (is it *significant*?) to burn above the threshold — the
multi-window, multi-burn-rate pattern from the SRE workbook.

Objective kinds:

- ``latency`` — events are observations of a histogram metric
  (e.g. ``nos_tpu_schedule_latency_seconds``); bad = above ``target``
  seconds (judged against bucket bounds, conservatively: the largest
  bound <= target).  ``each_label="class"`` fans one objective out to
  a verdict per observed label value — per-class p99 tracking without
  enumerating classes up front.
- ``gauge_floor`` — events are sample points of a gauge (e.g. a
  utilization gauge); bad = sampled below ``target``.
- ``rate_ceiling`` — a counter's per-second increase (e.g. rebind /
  eviction totals); burn = rate / ``target`` directly.

Verdict TRANSITIONS are journaled as ``SLO_BREACH`` /
``SLO_RECOVERED`` (obs/journal.py) with the ambient trace id, so
``python -m nos_tpu.obs slo`` can name the breaching class and — via
the same journal's ``pod-rejected`` records — the rejecting plugin in
one command.  The engine itself is driven from ONE run loop
(``Main.add_loop`` or a bench tick): ``tick()`` samples then
evaluates; it holds no lock of its own and calls the journal only
through its leaf-locked ``record()``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable

from nos_tpu.exporter.metrics import histogram_quantile

from . import journal as J
from .journal import record as journal_record
from .timeseries import SamplePoint, TimeSeriesSampler
from .trace import span as obs_span

#: (start, end) sample points spanning one evaluation window, or None
#: while the window is not yet observable (timeseries.bracket).
Bracket = tuple[SamplePoint, SamplePoint] | None
#: (burn rate, reported value, budget remaining) — Nones when the
#: window has no points or too few events.
BurnTriple = tuple[float | None, float | None, float | None]

LATENCY = "latency"
GAUGE_FLOOR = "gauge_floor"
RATE_CEILING = "rate_ceiling"


@dataclass(frozen=True)
class SLOObjective:
    """One declarative objective (module docstring has the kinds)."""

    name: str
    kind: str                      # latency | gauge_floor | rate_ceiling
    metric: str                    # base metric name (no derived suffix)
    target: float                  # seconds / floor value / per-second rate
    labels: tuple = ()             # series selector ((key, value), ...)
    each_label: str = ""           # fan out per value of this label key
    compliance: float = 0.99      # good-event fraction the SLO promises
    quantile: float = 0.99        # reported quantile (latency kind)
    # Minimum events in a window before it is judged (latency kind): a
    # low-traffic class where ONE slow event is 100% of the window must
    # read "not yet observable", not page at burn 50 (SRE low-traffic
    # rule).
    min_events: int = 1

    def __post_init__(self) -> None:
        if self.kind not in (LATENCY, GAUGE_FLOOR, RATE_CEILING):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if not (0.0 < self.compliance < 1.0):
            raise ValueError("compliance must be in (0, 1)")
        if self.target <= 0.0:
            # a zero ceiling would make burn = rate/0 = inf, and
            # json.dumps renders inf as the non-JSON token Infinity,
            # breaking every strict consumer of /debug/slo and the
            # bench's one-JSON-stdout contract — express zero
            # tolerance as a tiny positive ceiling instead
            raise ValueError("target must be > 0 (zero-tolerance "
                             "ceilings: use a tiny positive target)")
        if isinstance(self.labels, dict):  # ergonomic constructor form
            object.__setattr__(self, "labels",
                               tuple(sorted(self.labels.items())))


def _parse_series(series: str) -> dict[str, str]:
    """Inverse of metrics._series: "k=v,k2=v2" -> dict ("" -> {})."""
    if not series:
        return {}
    out: dict[str, str] = {}
    for part in series.split(","):
        k, _, v = part.partition("=")
        out[k] = v
    return out


def _matches(labels: dict[str, str], selector: tuple) -> bool:
    return all(labels.get(k) == str(v) for k, v in selector)


class SLOEngine:
    """Evaluates objectives against the sampler's windowed points and
    journals verdict transitions.  Single-driver contract: exactly one
    loop calls ``tick()``/``evaluate()`` (state is the breach latch,
    not lock-guarded); readers consume ``report()`` output."""

    def __init__(self, sampler: TimeSeriesSampler,
                 objectives: list[SLOObjective],
                 fast_window_s: float = 60.0,
                 slow_window_s: float = 300.0,
                 burn_threshold: float = 2.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._sampler = sampler
        self._objectives = list(objectives)
        self._fast_s = fast_window_s
        self._slow_s = slow_window_s
        self._burn_threshold = burn_threshold
        self._clock = clock
        # (objective name, fanout label value) -> currently breached
        self._breached: dict[tuple[str, str], bool] = {}
        # remembered (objective, fanout, selector) per judged key, so a
        # fanned-out class whose series VANISH (registry reset, engine
        # re-pointed) is still re-judged — its breach latch resolves to
        # SLO_RECOVERED (no data = not burning) instead of silently
        # disappearing from report() with the latch stuck
        self._judged_ctx: dict[tuple[str, str],
                               tuple[SLOObjective, str, tuple]] = {}
        self._last_verdicts: list[dict] = []

    @property
    def sampler(self) -> TimeSeriesSampler:
        return self._sampler

    @property
    def objectives(self) -> list[SLOObjective]:
        return list(self._objectives)

    def tick(self) -> list[dict]:
        """Sample the registry, then re-judge every objective — the run
        -loop entry point."""
        self._sampler.tick()
        return self.evaluate()

    # -- evaluation ----------------------------------------------------------
    def evaluate(self) -> list[dict]:
        """One verdict dict per (objective x fanned-out label value);
        journals SLO_BREACH / SLO_RECOVERED on transitions, carrying
        the ambient trace id via the slo.evaluate span."""
        fast = self._sampler.bracket(self._fast_s)
        slow = self._sampler.bracket(self._slow_s)
        verdicts: list[dict] = []
        seen: set[tuple[str, str]] = set()
        with obs_span("slo.evaluate", objectives=len(self._objectives)):
            for obj in self._objectives:
                for fanout, selector in self._expand(obj):
                    seen.add((obj.name, fanout))
                    verdicts.append(
                        self._judge(obj, fanout, selector, fast, slow))
            # latched-breached keys whose series vanished this round:
            # re-judge them anyway so the breach resolves (burns read
            # None without data -> not breached -> SLO_RECOVERED) and
            # the episode closes in both journal and report
            for key in [k for k, br in self._breached.items()
                        if k not in seen]:
                if self._breached[key]:
                    obj, fanout, selector = self._judged_ctx[key]
                    verdicts.append(
                        self._judge(obj, fanout, selector, fast, slow))
                else:
                    del self._breached[key]
                    del self._judged_ctx[key]
        self._last_verdicts = verdicts
        return verdicts

    def _expand(self, obj: SLOObjective) -> list[tuple[str, tuple]]:
        """Concrete (fanout value, full selector) pairs for one
        objective: the static selector alone, or one per observed value
        of ``each_label`` in the newest sample."""
        if not obj.each_label:
            return [("", obj.labels)]
        latest = self._sampler.latest()
        if latest is None:
            return []
        suffix = "_count" if obj.kind == LATENCY else ""
        seen: set[str] = set()
        out: list[tuple[str, tuple]] = []
        for series in latest.values.get(obj.metric + suffix, {}):
            labels = _parse_series(series)
            value = labels.get(obj.each_label)
            if value is None or value in seen:
                continue
            if not _matches(labels, obj.labels):
                continue
            seen.add(value)
            out.append((value, obj.labels + ((obj.each_label, value),)))
        return sorted(out)

    def _judge(self, obj: SLOObjective, fanout: str, selector: tuple,
               fast: Bracket, slow: Bracket) -> dict:
        if obj.kind == LATENCY:
            burn_fast, _, _ = self._latency_burn(obj, selector, fast)
            burn_slow, quantile, budget = self._latency_burn(
                obj, selector, slow)
            value = quantile
        elif obj.kind == GAUGE_FLOOR:
            burn_fast, _, _ = self._gauge_burn(obj, selector, fast)
            burn_slow, value, budget = self._gauge_burn(
                obj, selector, slow)
        else:   # RATE_CEILING
            burn_fast, _, _ = self._rate_burn(obj, selector, fast)
            burn_slow, value, budget = self._rate_burn(
                obj, selector, slow)

        # multi-window verdict: breach only when both windows burn.
        # None = window not yet observable (too few points / no events):
        # never a breach, never a recovery trigger either.
        breached = (burn_fast is not None and burn_slow is not None
                    and burn_fast >= self._burn_threshold
                    and burn_slow >= self._burn_threshold)
        verdict = {
            "objective": obj.name,
            "kind": obj.kind,
            "metric": obj.metric,
            "labels": dict(selector),
            "class": fanout or dict(selector).get("class", ""),
            "target": obj.target,
            "value": value,
            "burn_fast": burn_fast,
            "burn_slow": burn_slow,
            "budget_remaining": budget,
            "breached": breached,
        }
        key = (obj.name, fanout)
        self._judged_ctx[key] = (obj, fanout, selector)
        was = self._breached.get(key, False)
        if breached != was:
            self._breached[key] = breached
            journal_record(
                J.SLO_BREACH if breached else J.SLO_RECOVERED,
                obj.name + (f"/{fanout}" if fanout else ""),
                kind=obj.kind, metric=obj.metric,
                slo_class=verdict["class"], target=obj.target,
                value=value, burn_fast=burn_fast, burn_slow=burn_slow,
                budget_remaining=budget)
        return verdict

    # -- per-kind window math ------------------------------------------------
    @staticmethod
    def _each_series_delta(name: str, selector: tuple,
                           start: SamplePoint, end: SamplePoint
                           ) -> list[tuple[str | None, float]]:
        """Per-series (le label, delta) of `name` between the bracket
        ends, matching `selector`.  A negative delta means the registry
        was reset mid-window (process restart): resync to the end
        value instead of reporting negative traffic."""
        out: list[tuple[str | None, float]] = []
        for series, v_end in end.values.get(name, {}).items():
            labels = _parse_series(series)
            le = labels.pop("le", None)
            if not _matches(labels, selector):
                continue
            v_start = start.values.get(name, {}).get(series, 0.0)
            delta = v_end - v_start
            if delta < 0:
                delta = v_end
            out.append((le, delta))
        return out

    def _delta_total(self, name: str, selector: tuple,
                     bracket: Bracket) -> float | None:
        if bracket is None:
            return None
        return sum(d for _, d in self._each_series_delta(
            name, selector, *bracket))

    def _delta_by_le(self, name: str, selector: tuple,
                     bracket: Bracket) -> dict[str, float]:
        assert bracket is not None
        out: dict[str, float] = {}
        for le, delta in self._each_series_delta(name, selector,
                                                 *bracket):
            if le is not None:
                out[le] = out.get(le, 0.0) + delta
        return out

    def _latency_burn(self, obj: SLOObjective, selector: tuple,
                      bracket: Bracket) -> BurnTriple:
        """(burn rate, quantile estimate, budget remaining) for a
        histogram metric over one bracket; Nones when the window has no
        points or no events."""
        if bracket is None:
            return None, None, None
        total = self._delta_total(obj.metric + "_count", selector,
                                  bracket)
        if not total or total < obj.min_events:
            return None, None, None
        by_le = self._delta_by_le(obj.metric + "_bucket", selector,
                                  bracket)
        bounds = sorted((float(le) for le in by_le if le != "+Inf"))
        cumulative = [by_le[f"{b:g}"] for b in bounds]
        # conservative good-event count: observations provably <= target
        # (cumulative at the largest bound <= target)
        good = 0.0
        for b, c in zip(bounds, cumulative):
            if b <= obj.target:
                good = c
            else:
                break
        bad_fraction = max(0.0, 1.0 - good / total)
        allowed = 1.0 - obj.compliance
        burn = bad_fraction / allowed
        budget = 1.0 - burn
        per_bucket = [cumulative[0]] + [
            cumulative[i] - cumulative[i - 1]
            for i in range(1, len(cumulative))]
        quantile = histogram_quantile(tuple(bounds), per_bucket, total,
                                      obj.quantile)
        return burn, quantile, budget

    def _gauge_burn(self, obj: SLOObjective, selector: tuple,
                    bracket: Bracket) -> BurnTriple:
        """Fraction of sample points below the floor, burn-scaled."""
        if bracket is None:
            return None, None, None
        start, end = bracket
        pts = [p for p in self._sampler.points()
               if start.ts <= p.ts <= end.ts]
        total = 0
        bad = 0
        newest: float | None = None
        for p in pts:
            for series, v in p.values.get(obj.metric, {}).items():
                if not _matches(_parse_series(series), selector):
                    continue
                total += 1
                newest = v
                if v < obj.target:
                    bad += 1
        if total == 0:
            return None, None, None
        burn = (bad / total) / (1.0 - obj.compliance)
        return burn, newest, 1.0 - burn

    def _rate_burn(self, obj: SLOObjective, selector: tuple,
                   bracket: Bracket) -> BurnTriple:
        """Counter increase per second vs the ceiling."""
        if bracket is None:
            return None, None, None
        start, end = bracket
        seconds = end.ts - start.ts
        if seconds <= 0:
            return None, None, None
        delta = self._delta_total(obj.metric, selector, bracket)
        rate = (delta or 0.0) / seconds
        burn = rate / obj.target       # target > 0 by __post_init__
        return burn, rate, 1.0 - burn

    # -- surfaces ------------------------------------------------------------
    def report(self) -> dict:
        """The /debug/slo payload: config + the latest verdicts (embeds
        in flight snapshots and the bench JSON as the "slo" block)."""
        return {
            "ts": self._clock(),
            "fast_window_s": self._fast_s,
            "slow_window_s": self._slow_s,
            "burn_threshold": self._burn_threshold,
            "objectives": [
                {"name": o.name, "kind": o.kind, "metric": o.metric,
                 "target": o.target, "labels": dict(o.labels),
                 "each_label": o.each_label, "compliance": o.compliance}
                for o in self._objectives],
            "verdicts": list(self._last_verdicts),
        }


# ---------------------------------------------------------------------------
# Process-global engine (swappable, like obs.trace's tracer): the cmd
# mains install one so /debug/slo and flight snapshots can serve it.
# ---------------------------------------------------------------------------

_engine: SLOEngine | None = None


def get_engine() -> SLOEngine | None:
    return _engine


def set_engine(engine: SLOEngine | None) -> SLOEngine | None:
    global _engine
    prev = _engine
    _engine = engine
    return prev


def default_objectives() -> list[SLOObjective]:
    """The stock objectives a cmd main installs when the operator
    enables SLO evaluation without writing any config: per-class p99
    schedule latency (fanned out over observed classes) and per-pool
    actuation latency.  Targets are deliberately loose defaults —
    docs/observability.md's SLO cookbook shows tightening them per
    class."""
    return [
        SLOObjective(
            name="schedule-latency", kind=LATENCY,
            metric="nos_tpu_schedule_latency_seconds",
            target=30.0, each_label="class"),
        SLOObjective(
            name="actuation-latency", kind=LATENCY,
            metric="nos_tpu_actuation_latency_seconds",
            target=30.0, each_label="pool"),
        # Request data plane (nos_tpu/requests): end-to-end per-request
        # latency, fanned out per service.  Judged next to schedule
        # latency — a deployment without the router simply never
        # observes the metric and the objective reads not-yet-observable.
        SLOObjective(
            name="request-latency", kind=LATENCY,
            metric="nos_tpu_request_latency_seconds",
            target=10.0, labels={"phase": "total"},
            each_label="service", min_events=5),
    ]
