"""`nos explain`: reconstruct causal answers from a flight snapshot.

The two questions operators actually ask:

- **why is this pod still pending?** → `explain_pod`: walks the journal
  newest-first for the pod's rejection records and reconstructs the
  chain — per-node `plugin: reason` verdicts, the quota or gang cause,
  head-of-line deferrals, and any preemption attempted on its behalf.
- **where did this repartition's budget go?** → `explain_plan`: finds
  the newest plan-cycle span tree in the ring and prints the latency
  breakdown (plan vs actuate, fork/commit/revert counts, pipeline-call
  counters), plus the journal's per-node commit decisions.

Both operate on a *flight snapshot* — the plain-dict form produced by
`nos_tpu.obs.flight_snapshot()` and served by the health server at
`/debug/flightrecorder` — so the same code answers in-process (tests),
from a saved JSON file, or from a live endpoint (obs/__main__.py).
"""

from __future__ import annotations

from . import journal as J


def _pod_records(journal: list[dict], key: str) -> list[dict]:
    """Journal records about pod `key`, oldest first: subject match,
    membership in a gang decision's member list, or — because that list
    is capped — a gang decision whose subject the pod's OWN records name
    in their `gang` attr (member 33+ of a big gang keeps its context)."""
    out, seen, gangs = [], set(), set()
    for rec in journal:
        attrs = rec.get("attrs", {})
        # membership only against a LIST-typed members attr: a record
        # carrying a members COUNT (the members_total convention, but
        # guard against future drift) must not crash the flight
        # recorder for every pod in the journal
        members = attrs.get("members", ())
        if not isinstance(members, (list, tuple)):
            members = ()
        if rec["subject"] == key or key in members:
            out.append(rec)
            seen.add(rec["seq"])
            if attrs.get("gang"):
                gangs.add(attrs["gang"])
    if gangs:
        for rec in journal:
            if rec["seq"] not in seen and rec["subject"] in gangs \
                    and rec["category"] in (J.GANG_ADMITTED,
                                            J.GANG_REJECTED):
                out.append(rec)
        out.sort(key=lambda r: r["seq"])
    return out


def _fmt_nodes(nodes: dict, reason_counts: dict,
               total: int | None = None) -> list[str]:
    lines = []
    for node, why in sorted(nodes.items()):
        lines.append(f"    node {node}: rejected by {why}")
    listed = len(nodes)
    if total is None:   # records from before nodes_total existed
        total = sum(reason_counts.values()) if reason_counts else listed
    if total > listed:
        lines.append(f"    ... and {total - listed} more node(s); "
                     "top distinct reasons:")
        for why, count in sorted(reason_counts.items(),
                                 key=lambda kv: -kv[1]):
            lines.append(f"      {count}x {why}")
    return lines


def explain_pod(snapshot: dict, key: str) -> list[str]:
    """Human-readable causal answer for pod `key` ("ns/name").  Returns
    lines; the first states the verdict."""
    journal = snapshot.get("journal", [])
    records = _pod_records(journal, key)
    if not records:
        return [f"pod {key}: no journaled decisions — either it never "
                "reached the scheduler, or the journal has since "
                "evicted them (bounded ring)"]

    lines: list[str] = []
    last = records[-1]
    bound = [r for r in records if r["category"] == J.POD_BOUND]
    # the bind is definitive unless the pod was REJECTED again after it
    # (re-queued after eviction): gang binds journal gang-admitted after
    # every member's pod-bound, so "newest record" is the wrong test
    if bound and not any(r["category"] == J.POD_REJECTED
                         and r["seq"] > bound[-1]["seq"] for r in records):
        node = bound[-1]["attrs"].get("node", "?")
        return [f"pod {key}: BOUND to node {node} "
                f"(seq {bound[-1]['seq']}) — not pending"]

    lines.append(f"pod {key}: last decision: {last['category']} "
                 f"(seq {last['seq']})")

    rejections = [r for r in records if r["category"] == J.POD_REJECTED]
    if rejections:
        rej = rejections[-1]
        attrs = rej["attrs"]
        reason = attrs.get("reason") or "unclassified"
        lines.append(f"  latest rejection [{reason}]: "
                     f"{attrs.get('message', '')}")
        nodes = attrs.get("nodes") or {}
        if nodes:
            lines.extend(_fmt_nodes(nodes, attrs.get("reason_counts", {}),
                                    attrs.get("nodes_total")))

    # quota/preemption context is written in the present tense, so only
    # records from the LATEST scheduling attempt may produce it: anything
    # at or before the previous rejection belongs to an older attempt
    # whose cause may have since resolved (a pod that was the quota
    # head-of-line claimant cycles ago but is now pending on pure
    # capacity must not send the operator to debug quota).
    prev_rej_seq = rejections[-2]["seq"] if len(rejections) > 1 else -1
    recent = [r for r in records if r["seq"] > prev_rej_seq]

    for rec in reversed(recent):
        cat = rec["category"]
        attrs = rec["attrs"]
        if cat == J.QUOTA_HOL_CLAIM:
            lines.append(
                f"  quota: pod is the head-of-line claimant for "
                f"namespace {attrs.get('namespace', '?')} "
                f"(priority {attrs.get('priority', '?')}) — waiting for "
                "ledger headroom; lower-priority pods defer behind it")
            break
        if cat == J.POD_REJECTED and attrs.get("reason") == "quota-hol":
            lines.append(
                "  quota: deferred behind a higher-priority quota "
                "claimant in its namespace (head-of-line)")
            break

    gang = [r for r in records
            if r["category"] in (J.GANG_REJECTED, J.GANG_ADMITTED)]
    if gang:
        g = gang[-1]
        if g["category"] == J.GANG_REJECTED:
            n = g["attrs"].get("members_total",
                               len(g["attrs"].get("members", [])))
            lines.append(f"  gang {g['subject']}: "
                         f"{g['attrs'].get('message', 'did not fit')}"
                         f" (members: {n})")
        else:
            lines.append(f"  gang {g['subject']}: admitted "
                         f"({g['attrs'].get('bound', '?')} bound)")

    preempt = [r for r in recent
               if r["category"] in (J.PREEMPTION, J.PREEMPTION_NONE)]
    if preempt:
        p = preempt[-1]
        if p["category"] == J.PREEMPTION:
            n = p["attrs"].get("victim_count",
                               len(p["attrs"].get("victims", [])))
            lines.append(
                f"  preemption: evicted "
                f"{n} victim(s) on "
                f"{p['attrs'].get('node', '?')} on its behalf — retry "
                "expected next cycle")
        else:
            lines.append(f"  preemption attempted but found no victims: "
                         f"{p['attrs'].get('message', '')}")

    if len(lines) == 1:
        lines.append("  no rejection detail journaled (pod may simply "
                     "be awaiting its first scheduling cycle)")
    return lines


def _span_tree(spans: list[dict], root: dict) -> list[dict]:
    """root + descendants (by parent links), depth-first."""
    children: dict[str, list[dict]] = {}
    for s in spans:
        children.setdefault(s.get("parent_id", ""), []).append(s)
    out: list[dict] = []

    def walk(span: dict, depth: int) -> None:
        span = dict(span)
        span["_depth"] = depth
        out.append(span)
        for child in sorted(children.get(span["span_id"], []),
                            key=lambda s: s["start"]):
            walk(child, depth + 1)

    walk(root, 0)
    return out


def explain_plan(snapshot: dict, kind: str | None = None) -> list[str]:
    """Latency breakdown of the newest plan cycle (optionally of one
    partitioning kind): the span tree with durations and counters, then
    the journal's per-node commit/revert and actuation decisions."""
    spans = snapshot.get("spans", [])
    roots = [s for s in spans
             if s["name"] == "partitioner.plan_cycle"
             and (kind is None or s.get("attrs", {}).get("kind") == kind)]
    if not roots:
        return ["no completed plan cycle in the span ring"
                + (f" for kind {kind!r}" if kind else "")]
    root = max(roots, key=lambda s: s["start"])
    lines = []
    total = root.get("duration") or 0.0
    for s in _span_tree(spans, root):
        pad = "  " * s["_depth"]
        dur = s.get("duration")
        dur_s = f"{dur * 1000:.1f} ms" if dur is not None else "?"
        pct = f" ({dur / total * 100:.0f}%)" if dur and total else ""
        attrs = ", ".join(f"{k}={v}" for k, v in s.get("attrs", {}).items())
        lines.append(f"{pad}{s['name']}: {dur_s}{pct}"
                     + (f" [{attrs}]" if attrs else ""))
        for k, v in sorted(s.get("counts", {}).items()):
            lines.append(f"{pad}  · {k}: {v}")

    # sharded plans: attribute plan time per pool (the plan_shard spans
    # run on worker threads but carry the cycle's trace via context
    # propagation, so they are part of this tree)
    shards = [s for s in _span_tree(spans, root)
              if s["name"] == "plan_shard" and s.get("duration")]
    if shards:
        lines.append("shard time by pool:")
        shard_total = sum(s["duration"] for s in shards)
        for s in sorted(shards, key=lambda s: -(s["duration"] or 0.0)):
            attrs = s.get("attrs", {})
            pct = (f" ({s['duration'] / shard_total * 100:.0f}% of shard "
                   f"time)" if shard_total else "")
            lines.append(
                f"  {attrs.get('pool', '?')}: "
                f"{s['duration'] * 1000:.1f} ms{pct} "
                f"[nodes={attrs.get('nodes', '?')}, "
                f"pods={attrs.get('pods', '?')}]")

    trace_id = root["trace_id"]
    decisions = [r for r in snapshot.get("journal", [])
                 if r.get("trace_id") == trace_id
                 and r["category"] in (J.PLAN_NODE_COMMITTED,
                                       J.PLAN_NODE_REVERTED,
                                       J.NODE_ACTUATED,
                                       J.ACTUATION_FAILED,
                                       J.PLAN_SHARD_MERGED)]
    if decisions:
        lines.append("decisions in this cycle:")
        for r in decisions:
            attrs = ", ".join(f"{k}={v}" for k, v in r["attrs"].items())
            lines.append(f"  {r['category']} {r['subject']}"
                         + (f" ({attrs})" if attrs else ""))
    return lines
