"""The chip-second waste ledger: conservation-checked utilization
accounting.

``utilization 0.95`` says five percent of the fleet's chip-seconds went
*somewhere*; nothing in the metrics plane says where.  The ledger closes
that gap: it integrates fleet capacity over time and attributes every
chip-second to exactly ONE category, so the utilization number becomes a
waterfall — "3.1% fragmentation-stranded, 1.2% gang-assembly wait, 0.4%
actuation downtime" — each step joined to its journal evidence (the
gang whose assembly stalled, the shape class whose rejections define
the frag, the plan id of the actuation window).

Categories (``CATEGORIES``; docs/observability.md has the full
attribution contract):

- ``productive`` — chips consumed by bound, running pods;
- ``frag_stranded`` — free chips on hosts whose free geometry fits no
  pending class, derived from the scheduler's own per-class rejection
  verdicts (never a heuristic re-scan);
- ``gang_wait`` — chips held idle while a multi-host window assembles
  (the gang window lease);
- ``actuation`` — free chips on nodes inside a plan→status-caught-up
  repartition window (the partitioner's actuation clock stamps);
- ``quarantine`` — free chips on quarantined nodes;
- ``quota_stranded`` — free chips pending over-quota demand could use
  but borrowing limits forbid;
- ``drain`` — free chips bought by drain preemption, waiting for the
  leased window's gang;
- ``idle_no_demand`` — free chips with nothing pending to run.

The load-bearing correctness tool is the **conservation invariant**:
per pool, Σ category chip-seconds == ∫ capacity dt exactly, enforced
structurally — ``observe()`` installs a per-pool waterfall whose
categories are normalized to sum to capacity, and both sides of the
equation integrate the same snapshot over the same interval.  The chaos
soak asserts it continuously (under lockcheck/guard_state, like the
SLO sampler) and ``bench_utilization`` gates it per seed.

Design constraints (the DecisionJournal's, deliberately):

1. **Bounded memory** — per-pool/per-category accumulators plus a
   per-node hold map bounded by the cluster size; nothing grows with
   trace length.
2. **Leaf lock** — every mutator takes the ledger lock for the state
   update only and calls nothing under it (metrics are emitted after
   release), so instrumenting a call site can never add a lock-order
   edge (verified under lockcheck in the chaos soak).
3. **Injectable clock** — accrual timestamps come from the ledger's
   clock so chaos seeds and the virtual-clock benches reproduce
   byte-identical waterfalls (noslint N002).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Collection, Mapping

from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.utils.guards import guarded_by

REGISTRY.describe("nos_tpu_chip_seconds_total",
                  "Chip-seconds attributed per waste category and pool "
                  "(conservation: sum over categories == capacity x time)")

# -- categories (the waterfall schema; docs/observability.md) ---------------
PRODUCTIVE = "productive"
FRAG_STRANDED = "frag_stranded"
GANG_WAIT = "gang_wait"
ACTUATION = "actuation"
QUARANTINE = "quarantine"
QUOTA_STRANDED = "quota_stranded"
DRAIN = "drain"
# A host the capacity plane asked the cloud for, between the scale-up
# decision and the node becoming usable (nos_tpu/capacity): its free
# chips are "cloud is slow", NOT idle_no_demand — `obs waste` must be
# able to tell a stocked-out/slow provider from genuine slack.
PROVISIONING = "provisioning"
IDLE_NO_DEMAND = "idle_no_demand"

CATEGORIES: tuple[str, ...] = (
    PRODUCTIVE, FRAG_STRANDED, GANG_WAIT, ACTUATION, QUARANTINE,
    QUOTA_STRANDED, DRAIN, PROVISIONING, IDLE_NO_DEMAND,
)

#: Categories that are *waste* (everything but productive).  Idle with
#: no demand is listed last by convention: it is unattributable slack,
#: not a defect a subsystem owns.
WASTE_CATEGORIES: tuple[str, ...] = tuple(
    c for c in CATEGORIES if c != PRODUCTIVE)

#: Hold kinds an owning subsystem may stamp on a node (attribution of
#: the node's FREE chips, strongest first): quarantine outranks an
#: in-flight actuation, which outranks a drain marker, which outranks
#: the capacity plane's provisioning window (a just-joined host that
#: is simultaneously quarantined or draining is THAT problem first).
HOLD_PRECEDENCE: tuple[str, ...] = (QUARANTINE, ACTUATION, DRAIN,
                                    PROVISIONING)


def stranded_free(free_by_host: Mapping[str, float],
                  stranded_hosts: Collection[str]) -> float:
    """Σ free chips over the hosts flagged stranded — THE shared
    stranded-free computation.  Both consumers use it so the `frag`
    column of ``obs top`` and the ledger's ``frag_stranded`` can never
    drift apart arithmetically; what differs is only how the flag set
    is derived (scheduler rejection verdicts live, the whole-free-window
    heuristic offline — docs/observability.md, "The waterfall")."""
    return sum(f for h, f in free_by_host.items()
               if f > 0.0 and h in stranded_hosts)


def stranded_fraction(free_by_host: Mapping[str, float],
                      stranded_hosts: Collection[str]) -> float:
    """Stranded share of the FREE capacity (0.0 with no free chips)."""
    free = sum(f for f in free_by_host.values() if f > 0.0)
    if free <= 0.0:
        return 0.0
    return stranded_free(free_by_host, stranded_hosts) / free


def pod_chip_equiv(request: Mapping[str, float], chips_per_host: float,
                   hbm_gb_per_chip: float) -> float:
    """Physical chips one pod occupies on ITS host: slice profiles at
    their chip count capped to the host shard (a 4x4 member requests the
    whole shape but owns 8 chips of it), timeshare GB scaled to chips by
    the generation's per-chip HBM.  The ledger's productive accounting
    and the bench's utilization sampling share this currency."""
    from nos_tpu.topology.profile import (
        extract_slice_requests, extract_timeshare_requests,
    )

    chips = sum(min(float(s.chips), chips_per_host) * q
                for s, q in extract_slice_requests(request).items())
    gb = sum(float(g) * q
             for g, q in extract_timeshare_requests(request).items())
    if hbm_gb_per_chip > 0.0:
        chips += gb / hbm_gb_per_chip
    return chips


@guarded_by("_lock", "_holds", "_cur", "_cap", "_since", "_elapsed",
            "_totals", "_cap_seconds", "_evidence", "_overcommit",
            "_last_quota_flip")
class ChipSecondLedger:
    """Per-pool chip-second accounting with exact conservation.

    ``observe(pools)`` is the single accrual entry point (the scheduler
    calls it at cycle end): the PREVIOUS waterfall accrues over the
    elapsed interval, then the new one is installed.  Owning call sites
    stamp per-node **holds** (actuation windows, quarantine, drain)
    between observes; the scheduler's waterfall builder reads them at
    attribution time.  Everything is keyed by pool so the conservation
    invariant is checkable per failure domain.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic
                 ) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        # (node, hold kind, owner) -> detail: owner disambiguates the
        # slice and timeshare planes both holding one hybrid host
        self._holds: dict[tuple[str, str, str], dict[str, object]] = {}
        # current per-pool waterfall (chips), capacity and accrual stamp
        self._cur: dict[str, dict[str, float]] = {}
        self._cap: dict[str, float] = {}
        self._since: dict[str, float] = {}
        # integrals
        self._elapsed: dict[str, float] = {}
        self._totals: dict[str, dict[str, float]] = {}
        self._cap_seconds: dict[str, float] = {}
        # newest culprit evidence per pool x category (kept after the
        # window passes so the report can always name the culprit)
        self._evidence: dict[str, dict[str, dict[str, object]]] = {}
        self._overcommit = 0
        self._last_quota_flip: dict[str, object] | None = None

    # -- holds (owning call sites) ------------------------------------------
    def set_hold(self, node: str, category: str, owner: str = "",
                 **detail: object) -> None:
        """Stamp a hold on `node`'s free chips.  Idempotent per
        (node, category, owner); detail is category evidence (plan id,
        quarantine reason, draining gang)."""
        with self._lock:
            self._holds[(node, category, owner)] = dict(detail)

    def clear_hold(self, node: str, category: str,
                   owner: str = "") -> None:
        with self._lock:
            self._holds.pop((node, category, owner), None)

    def holds(self) -> dict[str, dict[str, dict[str, object]]]:
        """node -> hold kind -> detail (owners merged; a node held by
        any owner reads held)."""
        with self._lock:
            items = list(self._holds.items())
        out: dict[str, dict[str, dict[str, object]]] = {}
        for (node, kind, _owner), detail in items:
            out.setdefault(node, {}).setdefault(kind, detail)
        return out

    def hold_count(self) -> int:
        with self._lock:
            return len(self._holds)

    # -- quota evidence ------------------------------------------------------
    def note_quota_flip(self, pod_key: str, namespace: str,
                        borrowed: bool) -> None:
        """The elasticquota reconciler's borrow/reclaim label flips:
        the newest one is the `quota_stranded` join hint (which team's
        borrowing last moved)."""
        with self._lock:
            self._last_quota_flip = {
                "pod": pod_key, "namespace": namespace,
                "borrowed": borrowed,
            }

    # -- accrual -------------------------------------------------------------
    def observe(self, pools: Mapping[str, Mapping[str, object]]) -> None:
        """Accrue the previous waterfall up to now, then install the
        given one.  ``pools[pool]`` carries ``capacity`` (chips),
        ``categories`` ({category: chips}) and optional ``evidence``
        ({category: {...}}).  Categories are normalized so they sum to
        capacity exactly: a positive residual lands in
        ``idle_no_demand``; an overcommitted sample (Σ > capacity, a
        caller bug) is scaled down and counted — conservation survives
        either way.  Pools absent from the call stop accruing (their
        nodes left the fleet); their integrals are kept."""
        now = self._clock()
        incs: list[tuple[str, str, float]] = []
        with self._lock:
            for pool in list(self._cur):
                self._accrue_pool_locked(pool, now, incs)
            self._cur = {}
            self._cap = {}
            for pool, sample in pools.items():
                capacity = float(sample.get("capacity", 0.0))  # type: ignore[arg-type]
                raw = sample.get("categories") or {}
                cats = {c: float(v) for c, v in raw.items()  # type: ignore[union-attr]
                        if c in CATEGORIES and float(v) > 0.0}
                assigned = sum(cats.values())
                residual = capacity - assigned
                if residual > 0.0:
                    cats[IDLE_NO_DEMAND] = \
                        cats.get(IDLE_NO_DEMAND, 0.0) + residual
                elif residual < -1e-9 and assigned > 0.0:
                    scale = capacity / assigned
                    cats = {c: v * scale for c, v in cats.items()}
                    self._overcommit += 1
                self._cur[pool] = cats
                self._cap[pool] = capacity
                self._since[pool] = now
                evidence = sample.get("evidence") or {}
                if evidence:
                    pool_ev = self._evidence.setdefault(pool, {})
                    for cat, why in evidence.items():  # type: ignore[union-attr]
                        if cat in CATEGORIES and isinstance(why, dict):
                            pool_ev[cat] = dict(why)
        for pool, cat, delta in incs:
            REGISTRY.inc("nos_tpu_chip_seconds_total", delta,
                         labels={"category": cat, "pool": pool})

    def _accrue_pool_locked(self, pool: str, now: float,
                            incs: list[tuple[str, str, float]]) -> None:
        since = self._since.get(pool)
        if since is None or now <= since:
            return
        dt = now - since
        self._since[pool] = now
        totals = self._totals.setdefault(pool, {})
        for cat, chips in self._cur.get(pool, {}).items():
            if chips <= 0.0:
                continue
            totals[cat] = totals.get(cat, 0.0) + chips * dt
            incs.append((pool, cat, chips * dt))
        self._cap_seconds[pool] = self._cap_seconds.get(pool, 0.0) \
            + self._cap.get(pool, 0.0) * dt
        self._elapsed[pool] = self._elapsed.get(pool, 0.0) + dt

    # -- reads ---------------------------------------------------------------
    def conservation(self) -> dict[str, dict[str, float]]:
        """Per pool: Σ category chip-seconds vs ∫ capacity dt and their
        delta — the invariant the soak and benches assert is |delta|
        within ε (a few float ulps of the magnitude)."""
        with self._lock:
            out: dict[str, dict[str, float]] = {}
            for pool, cap_s in self._cap_seconds.items():
                total = sum(self._totals.get(pool, {}).values())
                out[pool] = {
                    "sum_chip_seconds": total,
                    "capacity_chip_seconds": cap_s,
                    "delta": total - cap_s,
                }
            return out

    def report(self) -> dict:
        """The waterfall block served in ``/snapshot`` and
        ``/debug/flightrecorder`` and rendered by ``obs waste``:
        per-pool chip-second totals, fractions of capacity,
        conservation deltas, culprit evidence, plus a fleet rollup."""
        with self._lock:
            pools: dict[str, dict] = {}
            fleet_totals: dict[str, float] = {}
            fleet_cap_s = 0.0
            for pool in sorted(set(self._cap_seconds) | set(self._cur)):
                totals = dict(self._totals.get(pool, {}))
                cap_s = self._cap_seconds.get(pool, 0.0)
                fleet_cap_s += cap_s
                for cat, v in totals.items():
                    fleet_totals[cat] = fleet_totals.get(cat, 0.0) + v
                pools[pool] = {
                    "capacity_chips": self._cap.get(pool, 0.0),
                    "elapsed_s": self._elapsed.get(pool, 0.0),
                    "capacity_chip_seconds": cap_s,
                    "chip_seconds": totals,
                    "fractions": {
                        cat: (v / cap_s if cap_s > 0.0 else 0.0)
                        for cat, v in totals.items()},
                    "conservation_delta":
                        sum(totals.values()) - cap_s,
                    "evidence": {
                        cat: dict(why) for cat, why
                        in self._evidence.get(pool, {}).items()},
                }
            flip = (dict(self._last_quota_flip)
                    if self._last_quota_flip else None)
            overcommit = self._overcommit
        return {
            "categories": list(CATEGORIES),
            "pools": pools,
            "fleet": {
                "capacity_chip_seconds": fleet_cap_s,
                "chip_seconds": fleet_totals,
                "fractions": {
                    cat: (v / fleet_cap_s if fleet_cap_s > 0.0 else 0.0)
                    for cat, v in fleet_totals.items()},
                "conservation_delta":
                    sum(fleet_totals.values()) - fleet_cap_s,
            },
            "overcommit_events": overcommit,
            "quota_last_flip": flip,
        }


def conservation_ok(report: dict, epsilon: float = 1e-6) -> bool:
    """True when every pool of a ``report()`` block conserves
    chip-seconds within ε (relative to the pool's capacity integral,
    with an absolute floor for near-empty pools) — the single predicate
    the benches and CI smoke assert."""
    for pool in report.get("pools", {}).values():
        cap_s = pool.get("capacity_chip_seconds", 0.0)
        tol = max(epsilon, epsilon * cap_s)
        if abs(pool.get("conservation_delta", 0.0)) > tol:
            return False
    return True


def waste_ranking(report: dict) -> list[dict]:
    """Waste categories ranked by fleet chip-seconds, descending —
    ``obs waste``'s top-sources table.  Productive is excluded by
    definition; zero rows are dropped."""
    fleet = report.get("fleet", {})
    totals = fleet.get("chip_seconds", {})
    fractions = fleet.get("fractions", {})
    rows = [
        {"category": cat, "chip_seconds": totals.get(cat, 0.0),
         "fraction": fractions.get(cat, 0.0)}
        for cat in WASTE_CATEGORIES if totals.get(cat, 0.0) > 0.0
    ]
    rows.sort(key=lambda r: -float(r["chip_seconds"]))  # type: ignore[arg-type]
    return rows


# ---------------------------------------------------------------------------
# Process-global ledger (swappable, like obs.journal's journal): always
# present so instrumented call sites never need a None check; benches
# and the chaos soak install a fresh one on their virtual clock.
# ---------------------------------------------------------------------------

_ledger = ChipSecondLedger()


def get_ledger() -> ChipSecondLedger:
    return _ledger


def set_ledger(ledger: ChipSecondLedger) -> ChipSecondLedger:
    global _ledger
    prev = _ledger
    _ledger = ledger
    return prev
