"""Windowed time-series sampling of the metrics registry.

The SLO engine (obs/slo.py) needs *windowed* views — "what did the
per-class latency histogram do over the last 60 s vs the last 5 min" —
but the Registry only holds lifetime aggregates.  The
``TimeSeriesSampler`` bridges them: a run loop (or a bench tick) calls
``tick()``, which snapshots every registry series into one bounded
``SamplePoint`` ring and rolls the registry's max window
(``Registry.reset_window()`` — the ``<name>_max`` gauges are
max-since-last-tick by contract, exporter/metrics.py).

Design constraints mirror the decision journal's (obs/journal.py):

1. **Bounded memory** — a deque(maxlen) of points plus an eviction
   counter; a week-long run keeps the newest ``maxlen`` ticks.
2. **Leaf lock** — ``tick()`` computes the whole point (registry
   snapshot, clock read) BEFORE taking the ring lock and calls nothing
   under it, so sampling can never add a lock-order edge (verified
   under lockcheck in the chaos soak).
3. **Injectable clock** — sample timestamps come from the sampler's
   clock so chaos seeds reproduce byte-identical series (noslint N002).
"""

from __future__ import annotations

import time
from typing import Callable

from nos_tpu.exporter.metrics import REGISTRY, Registry

from ._ring import BoundedRing

REGISTRY.describe("nos_tpu_timeseries_points_dropped_total",
                  "Sample points evicted from the bounded series ring")


class SamplePoint:
    """One tick's view of every registry series: ``values`` is the
    ``Registry.snapshot()`` dict (name -> {series: value}, histograms
    expanded into ``_bucket``/``_sum``/``_count``/``_max``)."""

    __slots__ = ("ts", "values")

    def __init__(self, ts: float, values: dict) -> None:
        self.ts = ts
        self.values = values

    def get(self, name: str, series: str = "") -> float | None:
        return self.values.get(name, {}).get(series)

    def to_dict(self) -> dict:
        return {"ts": self.ts, "values": self.values}


class TimeSeriesSampler(BoundedRing):
    """Bounded ring of registry sample points (see module docstring).

    ``maxlen`` x tick interval is the longest window the SLO engine can
    evaluate; the default 720 points at a 1 s tick covers the 5-minute
    slow window 140x over, at 15 s ticks it covers 3 hours.
    """

    def __init__(self, registry: Registry | None = None,
                 maxlen: int = 720,
                 clock: Callable[[], float] = time.monotonic) -> None:
        super().__init__(maxlen)
        self._registry = registry if registry is not None else REGISTRY
        self._clock = clock

    def tick(self) -> SamplePoint:
        """Sample every series and roll the max window.  The snapshot
        and clock read happen OUTSIDE the ring lock (leaf-lock
        contract); the registry's own lock is released before ours is
        taken, so no lock nesting exists on this path."""
        values = self._registry.snapshot()
        self._registry.reset_window()
        point = SamplePoint(self._clock(), values)
        with self._lock:
            evicted = self._push_locked(point)
        if evicted:
            # into the SAMPLED registry: a sampler over a private
            # registry must surface its truncation in that registry's
            # own exposition, not pollute the process-global one
            self._registry.inc("nos_tpu_timeseries_points_dropped_total")
        return point

    # -- windowed reads ------------------------------------------------------
    def points(self) -> list[SamplePoint]:
        """All retained points, oldest first."""
        with self._lock:
            return list(self._items)

    def latest(self) -> SamplePoint | None:
        with self._lock:
            return self._items[-1] if self._items else None

    def bracket(self, window_s: float) -> tuple[SamplePoint, SamplePoint] | None:
        """(start, end) points spanning AT LEAST ``window_s`` seconds
        ending at the newest sample: start is the newest point at or
        before ``end.ts - window_s``.  None until the ring has actually
        covered a full window — a half-filled window must read as "not
        yet observable", never as a verdict (the SLO engine's cold-start
        rule: no paging while the series is still filling)."""
        with self._lock:
            pts = list(self._items)
        if len(pts) < 2:
            return None
        end = pts[-1]
        cutoff = end.ts - window_s
        start: SamplePoint | None = None
        for p in pts:
            if p.ts <= cutoff:
                start = p
            else:
                break
        if start is None or start is end:
            return None
        return start, end
