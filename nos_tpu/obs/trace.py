"""Causal tracing for the decision plane: spans over the scheduler →
partitioner → actuator pipeline.

The reference `nos` ships Prometheus gauges but no way to see *where a
repartition's latency budget went*: the plan handshake, the planner's
geometry search, and per-node actuation all hide inside one
`plan_seconds` observation.  This module is a deliberately small span
API — not an OpenTelemetry dependency — instrumenting the decision path
end to end:

- **Span**: named interval with attributes, monotonically-increasing
  counters (`bump`), and parent/trace linkage.  Time comes from the
  tracer's injectable clock, never from a raw `time.*` call at the
  instrumentation site (noslint N002 covers `nos_tpu/obs/`).
- **Context propagation** via `contextvars`: the active span follows the
  call stack (and survives nested framework calls) without threading a
  span argument through every signature.  Threads started mid-span do
  NOT inherit it (a fresh thread starts a fresh trace root) — run loops
  are independent traces by design.
- **RingExporter**: bounded in-memory ring of finished spans — the
  flight-recorder half of `python -m nos_tpu.obs` (see obs/explain.py);
  `dump()`/`to_json()` are the snapshot format served by the health
  server's `/debug/flightrecorder` endpoint.
- **Histograms**: every finished span observes
  `nos_tpu_span_seconds{span=<name>}` in the existing
  exporter/metrics.py registry, so p50/p99-style latency per decision
  stage is scrapeable without the ring.

Overhead is the design constraint: a span is one small object, two
clock reads, and a deque append; the hot pipeline (Filter per pod x
node) is instrumented with `bump()` counters on the *enclosing* span —
a ContextVar read plus a dict increment — and only creates real child
spans in `detailed` mode (tests, post-mortem captures).  The bench_plan
`--smoke` gate runs with tracing enabled.
"""

from __future__ import annotations

import contextvars
import itertools
import time
from types import TracebackType
from typing import Callable

from nos_tpu.exporter.metrics import REGISTRY

from ._ring import BoundedRing

REGISTRY.describe("nos_tpu_span_seconds",
                  "Decision-path span latency (count/sum/max per span)")
REGISTRY.describe("nos_tpu_trace_spans_dropped_total",
                  "Finished spans evicted from the bounded ring exporter")

#: The active span of this execution context (contextvars: follows the
#: call stack, isolated per thread).  None = no trace in progress.
_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "nos_tpu_obs_span", default=None)


class Span:
    """One named interval on the decision path.  Mutable while open:
    `set()` attaches attributes, `bump()` increments counters (the
    cheap aggregate instrumentation for hot loops).  Finished spans are
    immutable by convention — the ring exporter serializes them."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end", "attrs", "counts", "status")

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_id: str, start: float,
                 attrs: dict | None = None) -> None:
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.start = start
        self.end: float | None = None
        self.attrs: dict = attrs or {}
        self.counts: dict[str, int] = {}
        self.status = "ok"

    @property
    def duration(self) -> float | None:
        return None if self.end is None else self.end - self.start

    def set(self, key: str, value: object) -> None:
        self.attrs[key] = value

    def bump(self, key: str, n: int = 1) -> None:
        self.counts[key] = self.counts.get(key, 0) + n

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration": self.duration,
            "status": self.status,
            "attrs": dict(self.attrs),
            "counts": dict(self.counts),
        }


class RingExporter(BoundedRing):
    """Bounded ring of finished spans (newest last) — see BoundedRing
    for the memory-bound contract."""

    def __init__(self, maxlen: int = 2048) -> None:
        super().__init__(maxlen)

    def export(self, span: Span) -> None:
        with self._lock:
            evicted = self._push_locked(span)
        if evicted:
            REGISTRY.inc("nos_tpu_trace_spans_dropped_total")


class _SpanHandle:
    """Context manager binding one span into the ambient context."""

    __slots__ = ("_tracer", "_span", "_token")

    def __init__(self, tracer: "Tracer", span: Span) -> None:
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._token = _current.set(self._span)
        return self._span

    def __exit__(self, exc_type: type[BaseException] | None,
                 exc: BaseException | None,
                 tb: TracebackType | None) -> bool:
        _current.reset(self._token)
        span = self._span
        span.end = self._tracer.clock()
        if exc_type is not None:
            span.status = f"error:{exc_type.__name__}"
        self._tracer.ring.export(span)
        REGISTRY.observe("nos_tpu_span_seconds", span.duration or 0.0,
                         labels={"span": span.name})
        return False


class _NoopHandle:
    """Shared do-nothing handle for the disabled tracer fast path."""

    __slots__ = ()

    def __enter__(self) -> None:
        return None

    def __exit__(self, *exc: object) -> bool:
        return False


_NOOP = _NoopHandle()


class Tracer:
    """Span factory with an injectable clock and a bounded ring.

    `detailed=False` (the default) keeps the hot pipeline cheap: inner
    instrumentation points (`detail_span`) collapse to counter bumps on
    the enclosing span.  `detailed=True` materializes them as real child
    spans — used by tests and targeted post-mortem captures, not in the
    steady state."""

    def __init__(self, clock: Callable[[], float] = time.monotonic,
                 ring: RingExporter | None = None,
                 enabled: bool = True, detailed: bool = False) -> None:
        self.clock = clock
        # `is not None`, not `or`: an empty RingExporter is falsy
        # (__len__), and `or` would silently swap in a fresh ring
        self.ring = ring if ring is not None else RingExporter()
        self.enabled = enabled
        self.detailed = detailed
        # Per-tracer, not module-global: a fresh Tracer with an injected
        # clock must yield byte-identical recordings across runs of the
        # same chaos seed (count.__next__ is GIL-atomic, like the clock)
        self._ids = itertools.count(1)

    def span(self, name: str, **attrs: object) -> "_SpanHandle | _NoopHandle":
        """Open a span as the child of the ambient span (if any)."""
        if not self.enabled:
            return _NOOP
        parent = _current.get()
        if parent is None:
            trace_id = f"t{next(self._ids)}"
            parent_id = ""
        else:
            trace_id = parent.trace_id
            parent_id = parent.span_id
        span = Span(name, trace_id, f"s{next(self._ids)}", parent_id,
                    self.clock(), attrs or None)
        return _SpanHandle(self, span)

    def detail_span(self, name: str,
                    **attrs: object) -> "_SpanHandle | _NoopHandle":
        """A real child span in detailed mode; one counter bump on the
        enclosing span otherwise (hot-loop instrumentation)."""
        if self.detailed and self.enabled:
            return self.span(name, **attrs)
        parent = _current.get()
        if parent is not None:
            parent.bump(name)
        return _NOOP


# ---------------------------------------------------------------------------
# Process-global tracer (swappable: tests install instrumented instances)
# ---------------------------------------------------------------------------

_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Install `tracer` as the process tracer; returns the previous one
    so callers (tests, the chaos soak) can restore it."""
    global _tracer
    prev = _tracer
    _tracer = tracer
    return prev


def span(name: str, **attrs: object) -> "_SpanHandle | _NoopHandle":
    """`with span("scheduler.run_cycle", pods=n) as sp:` — the module-
    level convenience over the current process tracer."""
    return _tracer.span(name, **attrs)


def detail_span(name: str, **attrs: object) -> "_SpanHandle | _NoopHandle":
    return _tracer.detail_span(name, **attrs)


def current_span() -> Span | None:
    return _current.get()


def bump(key: str, n: int = 1) -> None:
    """Increment a counter on the ambient span, if any.  The hot-path
    instrumentation primitive: one ContextVar read + one dict add."""
    s = _current.get()
    if s is not None:
        s.bump(key, n)
