"""In-process metrics: counters/gauges/timers with a Prometheus text dump.

The analog of controller-runtime's default Prometheus registry that every
reference main exposes through kube-rbac-proxy
(config/gpupartitioner/prometheus/monitor.yaml:1-20).  Components call
`inc`/`set`/`observe` on the process-global REGISTRY; the cmd/_runtime
health server serves it at /metrics in the Prometheus exposition format.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

from nos_tpu.utils.guards import guarded_by


@guarded_by("_lock", "_counters", "_gauges", "_timers", "_help")
class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = defaultdict(float)
        self._gauges: dict[tuple[str, tuple], float] = {}
        # histogram-lite: count + sum + max per series
        self._timers: dict[tuple[str, tuple], list[float]] = {}
        self._help: dict[str, str] = {}

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
        return name, tuple(sorted((labels or {}).items()))

    def describe(self, name: str, help_text: str) -> None:
        """Register a metric's HELP text.  Idempotent for the same text
        (module re-import, double build_api) but a CONFLICTING
        re-registration raises: two call sites claiming one series name
        with different meanings is the double-registration bug class
        noslint N003 bans statically — this guard catches the dynamic
        remainder (name built at runtime, plugin registering late)."""
        with self._lock:
            existing = self._help.get(name)
            if existing is not None and existing != help_text:
                raise ValueError(
                    f"metric {name!r} already registered with different "
                    f"help text ({existing!r} != {help_text!r}); one "
                    "describe per metric — see docs/static-analysis.md")
            self._help[name] = help_text

    def inc(self, name: str, value: float = 1.0,
            labels: dict | None = None) -> None:
        with self._lock:
            self._counters[self._key(name, labels)] += value

    def set(self, name: str, value: float,
            labels: dict | None = None) -> None:
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, seconds: float,
                labels: dict | None = None) -> None:
        with self._lock:
            agg = self._timers.setdefault(self._key(name, labels),
                                          [0.0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += seconds
            agg[2] = max(agg[2], seconds)

    def time(self, name: str, labels: dict | None = None):
        """with REGISTRY.time("nos_tpu_plan_seconds"): ..."""
        return _Timer(self, name, labels)

    def snapshot(self) -> dict:
        """All series as a plain dict (the metricsexporter payload)."""
        with self._lock:
            out: dict[str, dict] = {}
            for (name, labels), v in self._counters.items():
                out.setdefault(name, {})[_series(labels)] = v
            for (name, labels), v in self._gauges.items():
                out.setdefault(name, {})[_series(labels)] = v
            for (name, labels), (cnt, total, mx) in self._timers.items():
                series = _series(labels)
                out.setdefault(name + "_count", {})[series] = cnt
                out.setdefault(name + "_sum", {})[series] = total
                out.setdefault(name + "_max", {})[series] = mx
            return out

    def render(self) -> str:
        """Prometheus text exposition."""
        lines: list[str] = []
        with self._lock:
            items = []
            for (name, labels), v in sorted(self._counters.items()):
                items.append((name, "counter", labels, v))
            for (name, labels), v in sorted(self._gauges.items()):
                items.append((name, "gauge", labels, v))
            for (name, labels), (cnt, total, mx) in sorted(
                    self._timers.items()):
                items.append((name + "_count", "counter", labels, cnt))
                items.append((name + "_sum", "counter", labels, total))
                items.append((name + "_max", "gauge", labels, mx))
            seen_types: set[str] = set()
            for name, typ, labels, v in items:
                if name not in seen_types:
                    seen_types.add(name)
                    base = name.removesuffix("_count").removesuffix(
                        "_sum").removesuffix("_max")
                    if base in self._help:
                        lines.append(f"# HELP {name} {self._help[base]}")
                    lines.append(f"# TYPE {name} {typ}")
                label_s = ""
                if labels:
                    inner = ",".join(
                        f'{k}="{_escape_label(val)}"' for k, val in labels)
                    label_s = "{" + inner + "}"
                lines.append(f"{name}{label_s} {v}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


def _escape_label(val) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition is invalid."""
    return str(val).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _series(labels: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in labels) or ""


class _Timer:
    def __init__(self, reg: Registry, name: str, labels: dict | None):
        self._reg, self._name, self._labels = reg, name, labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._reg.observe(self._name, time.perf_counter() - self._t0,
                          self._labels)
        return False


REGISTRY = Registry()
