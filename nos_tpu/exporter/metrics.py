"""In-process metrics: counters/gauges/histograms with a Prometheus dump.

The analog of controller-runtime's default Prometheus registry that every
reference main exposes through kube-rbac-proxy
(config/gpupartitioner/prometheus/monitor.yaml:1-20).  Components call
`inc`/`set`/`observe` on the process-global REGISTRY; the cmd/_runtime
health server serves it at /metrics in the Prometheus exposition format.

`observe` records a NATIVE histogram: per-series bucket counts (default
log-spaced bounds from 1 ms to 60 s, overridable per metric via
``describe(..., buckets=...)`` or the first ``observe(...,
buckets=...)``), plus count/sum and a **windowed** max.  ``render()``
emits Prometheus-conventional ``_bucket{le=...}`` / ``_sum`` /
``_count`` series under ``# TYPE <name> histogram``; ``quantile()``
serves p50/p99-style questions in-process without a scrape stack
(docs/observability.md, "Histograms and quantiles").

Windowed-max semantics: ``<name>_max`` is the largest observation since
the last ``reset_window()`` (the SLO sampler calls it every tick,
obs/timeseries.py), not since process start — a one-off startup spike
must not dominate the gauge for the process lifetime.

Derived-series namespace: a histogram ``foo`` owns ``foo_bucket``,
``foo_sum``, ``foo_count`` and ``foo_max``.  Registering a scalar metric
under any of those names (or a histogram whose derived names collide
with an existing scalar) raises instead of silently merging the series.
"""

from __future__ import annotations

import threading
import time
from bisect import bisect_left
from collections import defaultdict

from nos_tpu.utils.guards import guarded_by

#: Default histogram bounds: log-spaced from 1 ms to 60 s — schedule
#: latencies (ms for serving classes) through repartition walls (tens of
#: seconds) land in distinct buckets.  Upper bound open (+Inf implicit).
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: Suffixes a histogram metric derives; the scalar namespace must not
#: collide with them (see _guard_* below).
_DERIVED_SUFFIXES = ("_bucket", "_sum", "_count", "_max")


def histogram_quantile(bounds: tuple[float, ...], bucket_counts,
                       count: float, q: float,
                       observed_max: float = 0.0) -> float | None:
    """Prometheus-style quantile estimate from per-bucket (NON-cumulative)
    counts: linear interpolation inside the bucket holding rank q*count.
    The +Inf bucket has no upper bound — the estimate there is the best
    known ceiling, max(last bound, observed max).  None with no samples.

    Shared by Registry.quantile (lifetime counts) and the SLO engine
    (windowed bucket deltas, obs/slo.py).
    """
    if count <= 0:
        return None
    rank = q * count
    cumulative = 0.0
    for i, n in enumerate(bucket_counts):
        if n <= 0:
            continue
        if cumulative + n >= rank:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (rank - cumulative) / n
            return lo + (hi - lo) * min(1.0, max(0.0, frac))
        cumulative += n
    # rank falls in the +Inf bucket
    return max(bounds[-1] if bounds else 0.0, observed_max)


@guarded_by("_lock", "_counters", "_gauges", "_timers", "_help",
            "_buckets", "_scalar_names")
class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = defaultdict(float)
        self._gauges: dict[tuple[str, tuple], float] = {}
        # histogram per series: [count, sum, windowed max, bucket counts]
        # (bucket counts NON-cumulative, parallel to _buckets[name])
        self._timers: dict[tuple[str, tuple], list] = {}
        self._help: dict[str, str] = {}
        # histogram bucket bounds per metric NAME (all series of one
        # metric share bounds — label consistency, N003's twin)
        self._buckets: dict[str, tuple[float, ...]] = {}
        # scalar (counter/gauge) metric names, for the derived-series
        # collision guard
        self._scalar_names: set[str] = set()

    @staticmethod
    def _key(name: str, labels: dict | None) -> tuple[str, tuple]:
        return name, tuple(sorted((labels or {}).items()))

    def describe(self, name: str, help_text: str,
                 buckets: tuple[float, ...] | list[float] | None = None
                 ) -> None:
        """Register a metric's HELP text (and, for histograms, its bucket
        bounds).  Idempotent for the same text (module re-import, double
        build_api) but a CONFLICTING re-registration raises: two call
        sites claiming one series name with different meanings is the
        double-registration bug class noslint N003 bans statically —
        this guard catches the dynamic remainder (name built at runtime,
        plugin registering late)."""
        with self._lock:
            existing = self._help.get(name)
            if existing is not None and existing != help_text:
                raise ValueError(
                    f"metric {name!r} already registered with different "
                    f"help text ({existing!r} != {help_text!r}); one "
                    "describe per metric — see docs/static-analysis.md")
            self._help[name] = help_text
            if buckets is not None:
                self._guard_histogram_locked(name)
                self._register_buckets_locked(name, buckets)

    def _register_buckets_locked(self, name: str, buckets) -> tuple:
        """Validate + pin bucket bounds for `name` (caller holds the
        lock).  Conflicting bounds raise — all series and all call sites
        of one histogram share one bucket layout."""
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(b2 <= b1 for b1, b2
                             in zip(bounds, bounds[1:])):
            raise ValueError(
                f"metric {name!r}: buckets must be non-empty and "
                f"strictly increasing, got {bounds}")
        existing = self._buckets.get(name)
        if existing is not None and existing != bounds:
            raise ValueError(
                f"metric {name!r} already has buckets {existing}, "
                f"conflicting registration {bounds} — one bucket layout "
                "per histogram")
        self._buckets[name] = bounds
        return bounds

    def _guard_scalar_locked(self, name: str) -> None:
        """A counter/gauge name must not shadow a histogram or any of
        its derived series (`foo_count` vs histogram `foo`) — the old
        snapshot()/render() silently merged them."""
        if name in self._buckets:
            raise ValueError(
                f"metric {name!r} is already a histogram — scalar and "
                "histogram kinds cannot share a name")
        for suffix in _DERIVED_SUFFIXES:
            if name.endswith(suffix) \
                    and name[: -len(suffix)] in self._buckets:
                raise ValueError(
                    f"scalar metric {name!r} collides with histogram "
                    f"{name[: -len(suffix)]!r}'s derived {suffix} "
                    "series — pick a non-derived name")
        self._scalar_names.add(name)

    def _guard_histogram_locked(self, name: str) -> None:
        if name in self._scalar_names:
            raise ValueError(
                f"metric {name!r} is already a counter/gauge — scalar "
                "and histogram kinds cannot share a name")
        for suffix in _DERIVED_SUFFIXES:
            if (name + suffix) in self._scalar_names:
                raise ValueError(
                    f"histogram {name!r} would derive {name + suffix!r}, "
                    "which is already a scalar metric — pick another "
                    "name")

    def inc(self, name: str, value: float = 1.0,
            labels: dict | None = None) -> None:
        with self._lock:
            self._guard_scalar_locked(name)
            self._counters[self._key(name, labels)] += value

    def set(self, name: str, value: float,
            labels: dict | None = None) -> None:
        with self._lock:
            self._guard_scalar_locked(name)
            self._gauges[self._key(name, labels)] = value

    def observe(self, name: str, seconds: float,
                labels: dict | None = None,
                buckets: tuple[float, ...] | list[float] | None = None
                ) -> None:
        """Record one observation into `name`'s histogram.  `buckets`
        (first call or describe wins; conflicts raise) overrides the
        DEFAULT_BUCKETS layout for this metric."""
        with self._lock:
            bounds = self._buckets.get(name)
            if bounds is None:
                self._guard_histogram_locked(name)
                bounds = self._register_buckets_locked(
                    name, buckets if buckets is not None
                    else DEFAULT_BUCKETS)
            elif buckets is not None:
                self._register_buckets_locked(name, buckets)
            agg = self._timers.get(key := self._key(name, labels))
            if agg is None:
                agg = self._timers[key] = [0.0, 0.0, 0.0,
                                           [0] * len(bounds)]
            agg[0] += 1
            agg[1] += seconds
            agg[2] = max(agg[2], seconds)
            idx = bisect_left(bounds, seconds)
            if idx < len(bounds):
                agg[3][idx] += 1
            # seconds > last bound: lands only in the implicit +Inf
            # bucket, whose cumulative count IS agg[0]

    def quantile(self, name: str, q: float,
                 labels: dict | None = None) -> float | None:
        """In-process quantile estimate (e.g. q=0.99) over `name`'s
        lifetime observations for one label set; None with no samples.
        Linear interpolation inside the owning bucket — the resolution
        is the bucket layout, good enough for SLO verdicts without a
        scrape stack."""
        with self._lock:
            agg = self._timers.get(self._key(name, labels))
            if agg is None:
                return None
            bounds = self._buckets.get(name, DEFAULT_BUCKETS)
            count, _, mx, per_bucket = agg
            return histogram_quantile(bounds, per_bucket, count, q,
                                      observed_max=mx)

    def time(self, name: str, labels: dict | None = None):
        """with REGISTRY.time("nos_tpu_plan_seconds"): ..."""
        return _Timer(self, name, labels)

    def gauge_label_values(self, name: str, key: str) -> list[str]:
        """Distinct values of label ``key`` across ``name``'s EXISTING
        gauge series.  Publishers that derive per-label gauges from live
        state (the scheduler's pending-by-class gauges) use this at
        observe time to find series that must reset to 0 because their
        label value vanished from the live set — in-memory bookkeeping
        of "classes I once published" goes stale across publisher
        restarts and skipped publishes, while the registry's own series
        list cannot."""
        with self._lock:
            values = {dict(labels).get(key)
                      for (n, labels) in self._gauges if n == name}
        return sorted(v for v in values if v is not None)

    def reset_window(self) -> None:
        """Start a new max window: zero every histogram's windowed max
        (the `<name>_max` gauge semantics — see the module docstring).
        Called by the SLO sampler each tick; counts/sums/buckets are
        cumulative and unaffected."""
        with self._lock:
            for agg in self._timers.values():
                agg[2] = 0.0

    def snapshot(self) -> dict:
        """All series as a plain dict (the metricsexporter payload).
        Histogram `foo` contributes `foo_count` / `foo_sum` / `foo_max`
        plus `foo_bucket` whose series carry a trailing `le=` label
        (cumulative counts, `le=+Inf` == count)."""
        with self._lock:
            out: dict[str, dict] = {}
            for (name, labels), v in self._counters.items():
                out.setdefault(name, {})[_series(labels)] = v
            for (name, labels), v in self._gauges.items():
                out.setdefault(name, {})[_series(labels)] = v
            for (name, labels), agg in self._timers.items():
                cnt, total, mx, per_bucket = agg
                series = _series(labels)
                out.setdefault(name + "_count", {})[series] = cnt
                out.setdefault(name + "_sum", {})[series] = total
                out.setdefault(name + "_max", {})[series] = mx
                bounds = self._buckets.get(name, DEFAULT_BUCKETS)
                bucket_out = out.setdefault(name + "_bucket", {})
                cumulative = 0
                for le, n in zip(bounds, per_bucket):
                    cumulative += n
                    bucket_out[_series_le(labels, _le_str(le))] = cumulative
                bucket_out[_series_le(labels, "+Inf")] = cnt
            return out

    def render(self) -> str:
        """Prometheus text exposition: counters, gauges, then
        histograms (``# TYPE <name> histogram`` with `_bucket{le=}` /
        `_sum` / `_count`, plus the windowed `_max` gauge)."""
        lines: list[str] = []
        with self._lock:
            seen_types: set[str] = set()

            def head(name: str, typ: str, help_name: str | None = None
                     ) -> None:
                if name in seen_types:
                    return
                seen_types.add(name)
                help_text = self._help.get(help_name or name)
                if help_text is not None:
                    lines.append(f"# HELP {name} {help_text}")
                lines.append(f"# TYPE {name} {typ}")

            for (name, labels), v in sorted(self._counters.items()):
                head(name, "counter")
                lines.append(f"{name}{_render_labels(labels)} {v}")
            for (name, labels), v in sorted(self._gauges.items()):
                head(name, "gauge")
                lines.append(f"{name}{_render_labels(labels)} {v}")
            for (name, labels), agg in sorted(self._timers.items()):
                cnt, total, mx, per_bucket = agg
                bounds = self._buckets.get(name, DEFAULT_BUCKETS)
                head(name, "histogram")
                cumulative = 0
                for le, n in zip(bounds, per_bucket):
                    cumulative += n
                    lset = labels + (("le", _le_str(le)),)
                    lines.append(
                        f"{name}_bucket{_render_labels(lset)} {cumulative}")
                lset = labels + (("le", "+Inf"),)
                lines.append(f"{name}_bucket{_render_labels(lset)} "
                             f"{int(cnt)}")
                lines.append(f"{name}_sum{_render_labels(labels)} {total}")
                lines.append(f"{name}_count{_render_labels(labels)} "
                             f"{int(cnt)}")
            # windowed max rides as its own gauge metric, after the
            # histogram block so TYPE lines never interleave one metric
            for (name, labels), agg in sorted(self._timers.items()):
                head(name + "_max", "gauge", help_name=name)
                lines.append(f"{name}_max{_render_labels(labels)} "
                             f"{agg[2]}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Drop all series DATA.  Schema registrations (help text,
        bucket layouts, scalar/histogram kinds) survive: they describe
        what a metric IS, and a post-reset emitter must not be able to
        silently re-register an old name with a different shape."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._timers.clear()


def _escape_label(val) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition is invalid."""
    return str(val).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _le_str(bound: float) -> str:
    """Canonical le= rendering: no trailing zeros, ints stay ints."""
    return f"{bound:g}"


def _render_labels(labels: tuple) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(val)}"' for k, val in labels)
    return "{" + inner + "}"


def _series(labels: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in labels) or ""


def _series_le(labels: tuple, le: str) -> str:
    base = _series(labels)
    return f"{base},le={le}" if base else f"le={le}"


class _Timer:
    def __init__(self, reg: Registry, name: str, labels: dict | None):
        self._reg, self._name, self._labels = reg, name, labels

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._reg.observe(self._name, time.perf_counter() - self._t0,
                          self._labels)
        return False


REGISTRY = Registry()
