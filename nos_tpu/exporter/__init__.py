"""Telemetry exporter: cluster snapshot + component toggles + metrics.

The analog of the reference's metricsexporter binary
(cmd/metricsexporter/metricsexporter.go:33-91, payload schema
cmd/metricsexporter/metrics/metrics.go:24-42): collect a one-shot
description of the cluster — node/chip inventory per partitioning kind,
component toggles — plus this process's metric series, and POST it to an
endpoint or write it to a file (python -m nos_tpu.cmd.metricsexporter).
"""

from __future__ import annotations

import time as _time

from nos_tpu.api import constants as C
from nos_tpu.exporter.metrics import REGISTRY, Registry
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD

__all__ = ["REGISTRY", "Registry", "collect"]


def collect(api: APIServer, components: dict[str, bool] | None = None,
            registry: Registry | None = None) -> dict:
    """The metricsexporter payload (metrics.go:24-42 analog): anonymous
    cluster shape + enabled components + in-process metric series."""
    nodes = api.list(KIND_NODE)
    by_kind: dict[str, dict[str, float]] = {}
    for node in nodes:
        kind = node.metadata.labels.get(C.LABEL_PARTITIONING, "none")
        agg = by_kind.setdefault(kind, {"nodes": 0, "chips": 0.0})
        agg["nodes"] += 1
        agg["chips"] += float(
            node.metadata.labels.get(C.LABEL_CHIP_COUNT, "0") or 0)
    pods = api.list(KIND_POD)
    return {
        "timestamp": _time.time(),
        "cluster": {
            "nodes_total": len(nodes),
            "pods_total": len(pods),
            "partitioning": by_kind,
        },
        "components": components or {},
        "metrics": (registry or REGISTRY).snapshot(),
    }
