"""Sharded training: state creation and the pjit train step.

The end-to-end FSDP/TP/SP training loop the partitioner's carved slices are
validated against (BASELINE config #4).  Pattern: eval_shape the full train
state (params stay boxed as nn.Partitioned so logical axis names ride along
— including through optax, whose mu/nu trees mirror the boxed params), turn
the logical specs into NamedShardings via the mesh rules, then jit state
creation and the train step with explicit in/out shardings.  XLA inserts
all-gathers/reduce-scatters for the fsdp axis, all-reduces for tp, and the
ring collectives for sp.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax.training.train_state import TrainState
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from nos_tpu.models.llama import Llama, LlamaConfig
from nos_tpu.parallel.mesh import DEFAULT_RULES


def cross_entropy_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token loss: logits [B, S, V] vs tokens [B, S] (shift inside)."""
    logits = logits[:, :-1].astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def default_optimizer(lr: float = 3e-4, weight_decay: float = 0.1,
                      warmup: int = 100, clip: float = 1.0):
    sched = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup, 10_000, end_value=lr * 0.1)
    return optax.chain(
        optax.clip_by_global_norm(clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


class ShardedTrainer:
    """Builds sharded state + train step for a Llama model over a mesh."""

    def __init__(self, cfg: LlamaConfig, mesh: Mesh,
                 rules=DEFAULT_RULES, optimizer=None,
                 example_tokens: jax.Array | None = None,
                 batch_size: int = 8, seq_len: int | None = None) -> None:
        self.cfg = cfg
        self.mesh = mesh
        self.rules = rules
        self.model = Llama(cfg, mesh=mesh if cfg.attn_impl == "ring" else None)
        self.tx = optimizer or default_optimizer()
        seq_len = seq_len or min(cfg.max_seq_len, 2048)
        self.example_tokens = (
            example_tokens if example_tokens is not None
            else jnp.zeros((batch_size, seq_len), jnp.int32))
        self.batch_sharding = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))
        self.state_shardings = self._infer_state_shardings()

    # -- state --------------------------------------------------------------
    def _create_state(self, rng) -> TrainState:
        variables = self.model.init(rng, self.example_tokens)
        return TrainState.create(
            apply_fn=self.model.apply, params=variables["params"], tx=self.tx)

    def _infer_state_shardings(self):
        with self.mesh, nn.logical_axis_rules(self.rules):
            abstract = jax.eval_shape(
                self._create_state, jax.random.PRNGKey(0))
        logical = nn.get_partition_spec(abstract)
        # keep the unboxed skeleton: abstract_state() reuses it instead of
        # re-tracing the whole model init on the resume hot path
        self._abstract = nn.meta.unbox(abstract)
        return nn.logical_to_mesh_sharding(logical, self.mesh, self.rules)

    def abstract_state(self) -> TrainState:
        """The state's shape/dtype/sharding skeleton WITHOUT materializing
        arrays — the restore target for models/checkpoint.py (resuming
        from a checkpoint must not pay a full init's HBM + compute)."""
        return jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            self._abstract, self.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

    def init_state(self, seed: int = 0) -> TrainState:
        def make(rng):
            with self.mesh, nn.logical_axis_rules(self.rules):
                return self._create_state(rng)
        return jax.jit(make, out_shardings=self.state_shardings)(
            jax.random.PRNGKey(seed))

    # -- step ---------------------------------------------------------------
    def _step(self, state: TrainState, tokens: jax.Array):
        with self.mesh, nn.logical_axis_rules(self.rules):
            def loss_fn(params):
                # Fused chunked head+loss: the full [B, S, vocab] fp32
                # logits never materialize (llama._chunked_xent).
                return state.apply_fn({"params": params}, tokens,
                                      targets=tokens)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            new_state = state.apply_gradients(grads=grads)
            return new_state, loss

    def train_step(self) -> Callable:
        """The jitted SPMD train step: (state, tokens [B, S]) ->
        (state, loss)."""
        return jax.jit(
            self._step,
            in_shardings=(self.state_shardings, self.batch_sharding),
            out_shardings=(self.state_shardings, None),
            donate_argnums=(0,),
        )

    # -- inference ----------------------------------------------------------
    def forward(self) -> Callable:
        """Jitted forward pass: (params, tokens) -> logits."""
        def fwd(params, tokens):
            with self.mesh, nn.logical_axis_rules(self.rules):
                return self.model.apply({"params": params}, tokens)
        return jax.jit(
            fwd,
            in_shardings=(self.state_shardings.params, self.batch_sharding),
        )
