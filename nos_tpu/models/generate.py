"""Autoregressive generation for the Llama family.

The reference is an infrastructure project with no model code at all
(SURVEY.md §2.8) — the model family here exists to validate carved
slices end-to-end, and a serving-shaped entry point belongs with it:
the sharing demo (demos/tpu-sharing-comparison) measures inference
latency, and `generate` is the loop a user would actually serve.

TPU-first shape discipline: the whole decode runs inside ONE jit with a
`lax.scan` over steps and a fixed-width token buffer — no per-token
retrace, no dynamic shapes.  Each step re-runs the forward over the full
buffer and reads the logits at the current position (O(L·S²) total).
That trades FLOPs for simplicity and for exercising exactly the
flash-attention path the training stack uses; a KV-cache decode is a
future optimization, not a correctness feature, and the interface
(`generate(params, prompt, steps)`) will not change when it lands.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from nos_tpu.models.llama import Llama


def generate(model: Llama, params, prompt: jax.Array, steps: int,
             temperature: float = 0.0,
             rng: jax.Array | None = None) -> jax.Array:
    """Append `steps` sampled tokens to `prompt` [B, P] -> [B, P+steps].

    temperature 0 = greedy; otherwise softmax sampling at the given
    temperature.  Jit-compatible: wrap in jax.jit with
    `static_argnums=(0, 3, 4)` (temperature is branched on at trace
    time) or use `make_generate`.
    """
    batch, prompt_len = prompt.shape
    total = prompt_len + steps
    if total > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + steps ({steps}) = {total} exceeds "
            f"max_seq_len {model.cfg.max_seq_len}: positions past it are "
            f"out of distribution for RoPE")
    if rng is None:
        rng = jax.random.PRNGKey(0)

    buf = jnp.pad(prompt.astype(jnp.int32), ((0, 0), (0, steps)))

    def step(carry, _):
        buf, pos, rng = carry
        logits = model.apply(params, buf)           # [B, total, V]
        # logits at pos-1 predict the token at pos
        last = jax.lax.dynamic_slice_in_dim(
            logits, pos - 1, 1, axis=1)[:, 0, :]    # [B, V]
        if temperature > 0.0:
            rng, sub = jax.random.split(rng)
            nxt = jax.random.categorical(sub, last / temperature, axis=-1)
        else:
            nxt = jnp.argmax(last, axis=-1)
        nxt = nxt.astype(jnp.int32)
        buf = buf.at[:, pos].set(nxt)
        return (buf, pos + 1, rng), nxt

    (buf, _, _), _ = jax.lax.scan(
        step, (buf, jnp.int32(prompt_len), rng), None, length=steps)
    return buf


def make_generate(model: Llama, steps: int, temperature: float = 0.0):
    """Jitted generate closed over the model and step count:
    (params, prompt [B, P], rng?) -> [B, P+steps]."""
    def fn(params, prompt, rng=None):
        return generate(model, params, prompt, steps,
                        temperature=temperature, rng=rng)

    return jax.jit(fn)
