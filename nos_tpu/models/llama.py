"""Llama-style decoder-only transformer — the flagship validation workload.

The partitioner's job is to carve TPU slices that multi-host JAX jobs can
use; this model is the job (BASELINE config #4: Llama-3-8B FSDP training on
a v5e-32).  Architecture: RMSNorm, rotary embeddings, grouped-query
attention, SwiGLU MLP — written TPU-first:

- bf16 activations, fp32 params/softmax; matmuls hit the MXU via
  einsum/dot with fp32 accumulation.
- every weight/activation carries flax *logical* axis names mapped to mesh
  axes (dp/fsdp/tp/sp) by nos_tpu.parallel.mesh.DEFAULT_RULES — XLA inserts
  the collectives.
- layers run under nn.scan + nn.remat: one compiled block, activations
  rematerialized in backward (HBM for FLOPs).
- attention is pluggable: "dense" (XLA), "flash" (pallas kernel,
  nos_tpu/ops/attention.py), "ring" (sequence-parallel over the sp axis,
  nos_tpu/parallel/ring.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import ad_checkpoint
from jax.sharding import Mesh

from nos_tpu.ops.attention import flash_attention, repeat_kv
from nos_tpu.parallel.ring import dense_attention, ring_attention


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16     # activation dtype
    param_dtype: Any = jnp.float32
    attn_impl: str = "dense"      # "dense" | "flash" | "ring"
    # Sequence chunk for the fused head+loss path (__call__ with targets):
    # the [B, S, vocab] fp32 logits never materialize — each chunk's
    # logits/softmax live only inside its remat region.  0 disables.
    loss_chunk: int = 512
    remat: bool = True
    # What the backward may keep instead of recomputing ("nothing" = full
    # remat; "attn" saves the attention op's output so the flash kernel is
    # never re-run in backward; "dots" saves all non-batch matmul outputs).
    remat_policy: str = "nothing"
    scan_layers: bool = True
    # Fuse the q/k/v projections into one [E, H+2Hkv, D] matmul and the
    # MLP gate/up into one [E, 2I] matmul: fewer, wider MXU dispatches and
    # one HBM read of x instead of three.  Measured a wash on the v5e at
    # the bench shapes (the post-matmul slices force relayouts), so both
    # default off.  Caveat under tp>1: the q/k/v split points (H, H+Hkv)
    # are generally not shard boundaries of the combined heads axis, so
    # slicing forces per-layer resharding — keep fusion off for
    # tensor-parallel runs unless resharding is measured cheaper than the
    # extra HBM reads.
    fused_qkv: bool = False
    fused_gate_up: bool = False


# Llama-3-8B (meta-llama/Meta-Llama-3-8B) — the BASELINE config #4 workload.
LLAMA3_8B = LlamaConfig(
    vocab_size=128256, hidden_size=4096, intermediate_size=14336,
    num_layers=32, num_heads=32, num_kv_heads=8, head_dim=128,
)

# Small configs for tests and the single-chip bench.
TINY = LlamaConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=16, max_seq_len=128,
    dtype=jnp.float32,
)

# head_dim 128 (8 heads instead of 16x64) keeps the identical param count
# while meeting the pallas kernel's lane-width requirement, so the flagship
# bench exercises the flash path on TPU.
BENCH_350M = LlamaConfig(
    vocab_size=32000, hidden_size=1024, intermediate_size=2816,
    num_layers=24, num_heads=8, num_kv_heads=4, head_dim=128,
    max_seq_len=2048,
)

# The measured-best BENCH_350M *training* configuration — the single
# source of truth consumed by bench_compute.py, cmd/train.py's defaults
# and docs/performance.md, so the flagship bench and the production
# entrypoint cannot drift apart.  flash kernels (autotuned blocks),
# "rots" selective remat (post-rope q/k + v + attention/MLP matmul
# outputs saved: the backward recomputes neither the qkv projections nor
# rope, the two dominant recompute costs the step breakdown attributed
# to "mats"), scanned layers (one compiled block; rope rides through the
# scan as an nn.broadcast input, see Llama.__call__).
BENCH_350M_TRAIN = LlamaConfig(
    vocab_size=32000, hidden_size=1024, intermediate_size=2816,
    num_layers=24, num_heads=8, num_kv_heads=4, head_dim=128,
    max_seq_len=2048,
    attn_impl="flash", remat_policy="rots", scan_layers=True,
)


# Lazy thunks: checkpoint_policies lookups stay cheap at import time and
# save_only_these_names constructs a fresh policy per model build.
# Full no-remat needs ~2x the HBM (measured 30.4 GB vs the v5e's 15.75 at
# 350M/batch 8); "mats" saves the expensive-to-recompute matmul outputs
# while still rematting the cheap elementwise/norm chain.
_REMAT_POLICIES = {
    "nothing": lambda: jax.checkpoint_policies.nothing_saveable,
    "dots": lambda: jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "attn": lambda: jax.checkpoint_policies.save_only_these_names("attn_out"),
    "mlp": lambda: jax.checkpoint_policies.save_only_these_names(
        "mlp_gate", "mlp_up", "mlp_gate_up"),
    "mats": lambda: jax.checkpoint_policies.save_only_these_names(
        "attn_out", "mlp_gate", "mlp_up", "mlp_gate_up"),
    # everything matmul-shaped saved; backward recomputes only the cheap
    # elementwise/norm chain
    "all_mats": lambda: jax.checkpoint_policies.save_only_these_names(
        "attn_q", "attn_k", "attn_v", "attn_qkv", "attn_out",
        "mlp_gate", "mlp_up", "mlp_gate_up"),
    # the post-rope q/k (+ v) instead of the projection outputs: the
    # backward recomputes neither the qkv matmuls nor rope — the two
    # dominant recompute costs the step breakdown attributes to "mats".
    # "attn_qkv" is in the list for the fused_qkv branch, where the
    # unfused "attn_v" name is never emitted: without it the backward
    # would re-run the whole fused projection just to rebuild v.
    "rots": lambda: jax.checkpoint_policies.save_only_these_names(
        "attn_q_rot", "attn_k_rot", "attn_v", "attn_qkv", "attn_out",
        "mlp_gate", "mlp_up", "mlp_gate_up"),
}


def stack_layer_params(params: dict, num_layers: int,
                       prefix: str = "layer_") -> dict:
    """Restack an UNROLLED model's per-layer param subtrees
    (``layer_0`` ... ``layer_{n-1}``) into the scanned layout (one
    ``layers`` subtree with a leading layer axis), so scan-vs-unrolled
    equivalence can be checked at IDENTICAL parameters (bench_compute
    --smoke and tests/test_compute.py).  Expects unboxed params."""
    layers = [params[f"{prefix}{i}"] for i in range(num_layers)]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)
    out = {k: v for k, v in params.items() if not k.startswith(prefix)}
    out["layers"] = stacked
    return out


def rope_tables(positions: jax.Array, dim: int, theta: float):
    """cos/sin tables [B, S, 1, dim/2], computed once per forward and
    shared by every layer (they depend only on positions)."""
    freqs = 1.0 / (theta ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    angles = positions[:, :, None, None].astype(jnp.float32) * freqs
    return jnp.cos(angles), jnp.sin(angles)


def _rope(x: jax.Array, rope: tuple[jax.Array, jax.Array]) -> jax.Array:
    """Rotary position embedding over the last dim of [B, S, H, D].

    Rotate-half convention (pairs are (i, i+D/2), as in the HF Llama
    layout) rather than the interleaved (2i, 2i+1) one: the halves are
    contiguous lane slices, where interleaving costs strided VPU
    access + a stack/reshape in every layer's forward AND its remat
    recompute — measured +0.9 MFU points on the v5e at the bench shapes.
    The convention is framework-internal (every consumer shares this
    function); checkpoints are not interchangeable across conventions."""
    cos, sin = rope
    d2 = x.shape[-1] // 2
    x1 = x[..., :d2].astype(jnp.float32)
    x2 = x[..., d2:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale", nn.with_logical_partitioning(nn.initializers.ones,
                                                  ("embed",)),
            (x.shape[-1],), jnp.float32)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
        y = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)
        return (y * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, x, rope):
        cfg = self.cfg
        dense = lambda feats, logical, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), logical))
        if cfg.fused_qkv:
            nh, nkv = cfg.num_heads, cfg.num_kv_heads
            qkv = dense((nh + 2 * nkv, cfg.head_dim),
                        ("embed", "heads", "head_dim"), "qkv_proj")(x)
            qkv = ad_checkpoint.checkpoint_name(qkv, "attn_qkv")
            q = qkv[:, :, :nh]
            k = qkv[:, :, nh:nh + nkv]
            v = qkv[:, :, nh + nkv:]
        else:
            q = dense((cfg.num_heads, cfg.head_dim),
                      ("embed", "heads", "head_dim"), "q_proj")(x)
            k = dense((cfg.num_kv_heads, cfg.head_dim),
                      ("embed", "kv_heads", "head_dim"), "k_proj")(x)
            v = dense((cfg.num_kv_heads, cfg.head_dim),
                      ("embed", "kv_heads", "head_dim"), "v_proj")(x)
            # Only the unfused branch names the slices: in the fused
            # branch "attn_qkv" is already saved and naming the q/k/v
            # views too would store the same bytes twice under all_mats.
            q = ad_checkpoint.checkpoint_name(q, "attn_q")
            k = ad_checkpoint.checkpoint_name(k, "attn_k")
            v = ad_checkpoint.checkpoint_name(v, "attn_v")
        q = nn.with_logical_constraint(q, ("batch", "seq", "heads", "head_dim"))
        k = nn.with_logical_constraint(k, ("batch", "seq", "kv_heads", "head_dim"))
        q = ad_checkpoint.checkpoint_name(_rope(q, rope), "attn_q_rot")
        k = ad_checkpoint.checkpoint_name(_rope(k, rope), "attn_k_rot")
        n_rep = cfg.num_heads // cfg.num_kv_heads
        k, v = repeat_kv(k, n_rep), repeat_kv(v, n_rep)

        if cfg.attn_impl == "ring":
            if self.mesh is None:
                raise ValueError("ring attention needs a mesh")
            out = ring_attention(self.mesh, q, k, v, causal=True)
        elif cfg.attn_impl == "flash":
            out = flash_attention(q, k, v, True)
        else:
            out = dense_attention(q, k, v, causal=True)
        out = nn.with_logical_constraint(
            out, ("batch", "seq", "heads", "head_dim"))
        # Named so remat_policy="attn" can save exactly this tensor:
        # recomputing the O(S^2) attention op in backward is the one remat
        # expense the analytic MFU never credits.
        out = ad_checkpoint.checkpoint_name(out, "attn_out")
        proj = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="o_proj",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(),
                ("heads", "head_dim", "embed")))
        return proj(out)


class MLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, logical, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), logical))
        if cfg.fused_gate_up:
            gate_up = dense(2 * cfg.intermediate_size, ("embed", "mlp"),
                            "gate_up_proj")(x)
            gate_up = ad_checkpoint.checkpoint_name(gate_up, "mlp_gate_up")
            gate = gate_up[..., :cfg.intermediate_size]
            up = gate_up[..., cfg.intermediate_size:]
        else:
            gate = dense(cfg.intermediate_size, ("embed", "mlp"),
                         "gate_proj")(x)
            up = dense(cfg.intermediate_size, ("embed", "mlp"), "up_proj")(x)
            # Named so selective remat can save them: recomputing gate/up
            # is ~half the per-layer matmul FLOPs, the dominant remat
            # expense.
            gate = ad_checkpoint.checkpoint_name(gate, "mlp_gate")
            up = ad_checkpoint.checkpoint_name(up, "mlp_up")
        h = nn.silu(gate) * up
        h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))
        return dense(cfg.hidden_size, ("mlp", "embed"), "down_proj")(h)


class Block(nn.Module):
    cfg: LlamaConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, x, rope):
        cfg = self.cfg
        x = x + Attention(cfg, self.mesh, name="attn")(
            RMSNorm(cfg.norm_eps, name="attn_norm")(x), rope)
        x = x + MLP(cfg, name="mlp")(
            RMSNorm(cfg.norm_eps, name="mlp_norm")(x))
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


def _chunked_xent(x, embed, tokens, chunk, dtype):
    """Next-token cross entropy with the head matmul fused into the loss,
    scanned over sequence chunks so the [B, S, vocab] fp32 logits never
    exist at once (at vocab 32k/batch 8 they plus their cotangent are
    ~4 GB — a large share of a v5e's HBM).  Each chunk is a remat region:
    its logits are recomputed from the saved [B, chunk, E] activations in
    backward, costing one extra head matmul per step.

    Position i predicts tokens[i+1]; the last position is masked out."""
    bsz, seq, emb = x.shape
    nch = seq // chunk if chunk else 1
    if nch <= 1 or seq % chunk:
        nch, chunk = 1, seq
    targets = jnp.roll(tokens, -1, axis=1)
    # [nch, B, chunk, ...] scan layout
    xc = x.reshape(bsz, nch, chunk, emb).transpose(1, 0, 2, 3)
    tc = targets.reshape(bsz, nch, chunk).transpose(1, 0, 2)
    pos = jnp.arange(seq).reshape(nch, chunk)

    @jax.checkpoint
    def chunk_loss(xx, tt, pp):
        logits = jnp.einsum("bce,ve->bcv", xx, embed.astype(dtype),
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, tt[..., None], axis=-1)[..., 0]
        w = (pp < seq - 1).astype(jnp.float32)[None, :]
        return jnp.sum((lse - ll) * w)

    def body(carry, args):
        return carry + chunk_loss(*args), None

    total, _ = jax.lax.scan(body, jnp.float32(0), (xc, tc, pos))
    return total / (bsz * (seq - 1))


class Llama(nn.Module):
    """Decoder-only LM.  __call__(tokens [B, S] int32) -> logits
    [B, S, vocab]; with targets, -> scalar next-token loss via the
    chunk-fused head (cfg.loss_chunk)."""

    cfg: LlamaConfig
    mesh: Mesh | None = None

    @nn.compact
    def __call__(self, tokens, targets=None):
        cfg = self.cfg
        embed = self.param(
            "embed", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        x = embed[tokens].astype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32)[None], tokens.shape)
        rope = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

        block = Block
        if cfg.remat:
            block = nn.remat(
                Block, prevent_cse=not cfg.scan_layers,
                policy=_REMAT_POLICIES[cfg.remat_policy]())
        if cfg.scan_layers:
            # rope rides through the scan as an nn.broadcast input, NOT
            # a closure capture: a captured traced array is lifted into
            # the scan body as a per-iteration constant, which (with
            # remat inside the scan) re-staged the cos/sin tables into
            # every layer's forward AND its backward recompute and broke
            # the carry's layout against the stacked params — the
            # interaction that made the bench opt out of scan_layers.
            # As a broadcast input XLA hoists one copy for all layers.
            x, _ = nn.scan(
                lambda mdl, carry, rope_b: (mdl(carry, rope_b), None),
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(block(cfg, self.mesh, name="layers"), x, rope)
        else:
            for i in range(cfg.num_layers):
                x = block(cfg, self.mesh, name=f"layer_{i}")(x, rope)

        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        if targets is not None:
            return _chunked_xent(x, embed, targets, cfg.loss_chunk,
                                 cfg.dtype)
        # Tied embeddings.  The matmul runs in the activation dtype (bf16
        # on the MXU) with fp32 accumulation — upcasting the inputs would
        # force fp32 multiplies at a fraction of peak for ~9% of the
        # model's FLOPs; the loss softmax downstream is fp32 regardless.
        logits = jnp.einsum(
            "bse,ve->bsv", x, embed.astype(cfg.dtype),
            preferred_element_type=jnp.float32)
        return nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))

    def param_count(self) -> int:
        cfg = self.cfg
        per_layer = (
            cfg.hidden_size * cfg.num_heads * cfg.head_dim
            + 2 * cfg.hidden_size * cfg.num_kv_heads * cfg.head_dim
            + cfg.num_heads * cfg.head_dim * cfg.hidden_size
            + 3 * cfg.hidden_size * cfg.intermediate_size
            + 2 * cfg.hidden_size
        )
        return (cfg.vocab_size * cfg.hidden_size
                + cfg.num_layers * per_layer + cfg.hidden_size)
