"""Mixture-of-experts model family (expert parallelism over the `ep` axis).

The reference has no model code; the model families here exist so carved
slices are validated by real multi-host JAX workloads (SURVEY.md §2.8),
and MoE is the workload class that exercises the `ep` mesh axis the way
FSDP/TP/SP are exercised by the dense Llama.

TPU-first dispatch (the GShard/Switch einsum formulation — everything is
a matmul, so the MXU does the routing):

- router logits -> top-k softmax weights per token (fp32);
- fixed per-expert **capacity** C = ceil(tokens/E · capacity_factor);
  one-hot position-in-expert buffers give a dispatch tensor [T, E, C]
  and a combine tensor (dispatch · gate weight);
- `expert_in[e, c, d] = Σ_t dispatch[t, e, c] · x[t, d]` — a matmul;
- per-expert SwiGLU with stacked weights [E, d, f] sharded over `ep`
  (logical axis "experts"), so XLA turns dispatch/combine into
  all-to-alls across the expert shards;
- `y[t, d] = Σ_{e,c} combine[t, e, c] · expert_out[e, c, d]`.

Tokens over a full expert's capacity are dropped (their combine weight
is zero) — standard Switch behavior; capacity_factor controls the drop
rate.  Static shapes throughout: no gather/scatter, no dynamic sizes.
"""

from __future__ import annotations

import dataclasses
import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from nos_tpu.models.llama import (
    Attention, LlamaConfig, RMSNorm, _chunked_xent, rope_tables,
)


@dataclasses.dataclass(frozen=True, unsafe_hash=True)
class MoEConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    # auxiliary load-balancing loss weight (Switch §2.2 style)
    router_aux_weight: float = 0.01


# Small config for tests and the CPU dryrun.
TINY_MOE = MoEConfig(
    vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
    num_heads=4, num_kv_heads=2, head_dim=16, max_seq_len=128,
    dtype=jnp.float32, num_experts=4, top_k=2,
)


class MoEMLP(nn.Module):
    """Top-k routed SwiGLU experts with einsum dispatch/combine."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        bsz, seq, d = x.shape
        tokens = bsz * seq
        num_e, k = cfg.num_experts, cfg.top_k
        capacity = max(1, math.ceil(tokens * k / num_e
                                    * cfg.capacity_factor))

        xt = x.reshape(tokens, d)

        router = nn.DenseGeneral(
            num_e, axis=-1, use_bias=False, dtype=jnp.float32,
            param_dtype=jnp.float32, name="router",
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(), ("embed", "experts")))
        logits = router(xt.astype(jnp.float32))           # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k choices, each a one-hot over experts
        gate_vals, gate_idx = jax.lax.top_k(probs, k)     # [T, k]
        # renormalize the kept gates (Mixtral convention)
        gate_vals = gate_vals / jnp.maximum(
            jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

        # position of each (token, choice) within its expert's buffer:
        # cumulative count of prior assignments to the same expert
        choice_onehot = jax.nn.one_hot(gate_idx, num_e,
                                       dtype=jnp.float32)  # [T, k, E]
        flat = choice_onehot.reshape(tokens * k, num_e)     # choice-major? no:
        # token-major flattening keeps earlier tokens earlier in buffers
        position = (jnp.cumsum(flat, axis=0) - flat)        # [T*k, E]
        pos_in_expert = jnp.sum(position * flat, axis=-1).astype(jnp.int32)
        kept = pos_in_expert < capacity                      # [T*k]
        pos_onehot = jax.nn.one_hot(pos_in_expert, capacity,
                                    dtype=jnp.float32) * kept[:, None]

        # dispatch[t*k, e, c]; fold the k choices back onto tokens
        dispatch_k = flat[:, :, None] * pos_onehot[:, None, :]
        dispatch = dispatch_k.reshape(tokens, k, num_e, capacity)
        combine = jnp.sum(
            dispatch * gate_vals.reshape(tokens, k, 1, 1), axis=1)  # [T,E,C]
        dispatch = jnp.sum(dispatch, axis=1)                         # [T,E,C]

        # expert buffers: [E, C, D] — a matmul over tokens
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(cfg.dtype), xt.astype(cfg.dtype),
            preferred_element_type=jnp.float32).astype(cfg.dtype)
        expert_in = nn.with_logical_constraint(
            expert_in, ("experts", "capacity", "embed"))

        def expert_param(name, shape, logical):
            return self.param(
                name, nn.with_logical_partitioning(
                    nn.initializers.lecun_normal(), logical), shape,
                cfg.param_dtype)

        f = cfg.intermediate_size
        w_gate = expert_param("w_gate", (num_e, d, f),
                              ("experts", "embed", "mlp"))
        w_up = expert_param("w_up", (num_e, d, f),
                            ("experts", "embed", "mlp"))
        w_down = expert_param("w_down", (num_e, f, d),
                              ("experts", "mlp", "embed"))

        h = nn.silu(jnp.einsum(
            "ecd,edf->ecf", expert_in, w_gate.astype(cfg.dtype),
            preferred_element_type=jnp.float32).astype(cfg.dtype))
        h = h * jnp.einsum(
            "ecd,edf->ecf", expert_in, w_up.astype(cfg.dtype),
            preferred_element_type=jnp.float32).astype(cfg.dtype)
        h = nn.with_logical_constraint(h, ("experts", "capacity", "mlp"))
        expert_out = jnp.einsum(
            "ecf,efd->ecd", h, w_down.astype(cfg.dtype),
            preferred_element_type=jnp.float32).astype(cfg.dtype)

        y = jnp.einsum(
            "tec,ecd->td", combine.astype(cfg.dtype), expert_out,
            preferred_element_type=jnp.float32).astype(cfg.dtype)

        # Switch-style load-balance auxiliary (Switch §2.2 eq. 4):
        # alpha * E * sum_i f_i * P_i — equals 1.0 under uniform routing
        # for any E, so the pressure does not weaken as experts are added.
        top1 = jax.nn.one_hot(gate_idx[:, 0], num_e, dtype=jnp.float32)
        aux = num_e * jnp.sum(
            jnp.mean(top1, axis=0) * jnp.mean(probs, axis=0))
        self.sow("losses", "router_aux", cfg.router_aux_weight * aux)

        return y.reshape(bsz, seq, d)


class MoEBlock(nn.Module):
    cfg: MoEConfig

    @nn.compact
    def __call__(self, x, rope):
        cfg = self.cfg
        x = x + Attention(cfg, None, name="attn")(
            RMSNorm(cfg.norm_eps, name="attn_norm")(x), rope)
        x = x + MoEMLP(cfg, name="moe")(
            RMSNorm(cfg.norm_eps, name="moe_norm")(x))
        return nn.with_logical_constraint(x, ("batch", "seq", "embed"))


class MoELlama(nn.Module):
    """Decoder-only MoE LM with the same __call__ contract as Llama:
    (tokens) -> logits, (tokens, targets) -> scalar loss (+ router aux)."""

    cfg: MoEConfig

    @nn.compact
    def __call__(self, tokens, targets=None):
        cfg = self.cfg
        embed = self.param(
            "embed", nn.with_logical_partitioning(
                nn.initializers.normal(0.02), ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        x = embed[tokens].astype(cfg.dtype)
        x = nn.with_logical_constraint(x, ("batch", "seq", "embed"))
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1], dtype=jnp.int32)[None], tokens.shape)
        rope = rope_tables(positions, cfg.head_dim, cfg.rope_theta)

        block = MoEBlock
        if cfg.remat:
            block = nn.remat(MoEBlock, prevent_cse=True)
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(x, rope)

        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        if targets is not None:
            # xent only; the router aux terms are sown into the "losses"
            # collection and summed by moe_loss() (apply with mutable)
            return _chunked_xent(x, embed, targets, cfg.loss_chunk,
                                 cfg.dtype)
        logits = jnp.einsum(
            "bse,ve->bsv", x, embed.astype(cfg.dtype),
            preferred_element_type=jnp.float32)
        return nn.with_logical_constraint(logits, ("batch", "seq", "vocab"))


def make_ep_trainer(model: MoELlama, mesh, example_tokens):
    """Sharded init + jitted adam train step for an MoE model over a mesh
    with an `ep` axis (shared by tests and the driver dryrun — the
    harness must not fork between them).

    Returns (params, opt_state, step) with step(params, opt_state,
    tokens) -> (params, opt_state, loss); tokens must carry
    parallel.mesh.batch_sharding(mesh)."""
    import optax

    from nos_tpu.parallel.mesh import DEFAULT_RULES

    with mesh, nn.logical_axis_rules(DEFAULT_RULES):
        abstract = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(1), example_tokens))
    logical = nn.get_partition_spec(abstract)
    shardings = nn.logical_to_mesh_sharding(logical, mesh, DEFAULT_RULES)

    def init():
        with mesh, nn.logical_axis_rules(DEFAULT_RULES):
            return model.init(jax.random.PRNGKey(1), example_tokens)

    params = jax.jit(init, out_shardings=shardings)()["params"]
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, toks):
        with mesh, nn.logical_axis_rules(DEFAULT_RULES):
            loss, grads = jax.value_and_grad(
                lambda p: moe_loss(model, p, toks))(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    return params, opt_state, step


def moe_loss(model: MoELlama, params, tokens) -> jax.Array:
    """Next-token loss + router load-balance auxiliary (the sown
    "losses" collection summed across layers)."""
    loss, variables = model.apply(
        {"params": params}, tokens, targets=tokens, mutable=["losses"])
    aux_terms = jax.tree_util.tree_leaves(variables.get("losses", {}))
    if aux_terms:
        loss = loss + sum(jnp.sum(t) for t in aux_terms)
    return loss
