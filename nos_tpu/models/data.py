"""Token-batch input pipeline for the training stack.

The reference is an infrastructure project; nos-tpu's model stack exists
to validate carved slices with real training jobs, and a training job
needs an input story.  TPU-first constraints shape the design:

- batches are fixed-shape [batch, seq_len] int32 windows over a flat
  token stream (static shapes: nothing here ever retraces the step);
- the stream is a numpy array or a memmapped token file — HBM never
  holds the corpus, only the in-flight batches;
- epochs are deterministic permutations of the non-overlapping windows
  (seed + epoch => order), so a resumed job (models/checkpoint.py) can
  reproduce the exact batch sequence by fast-forwarding `start_step`;
- `device_iter` double-buffers: the NEXT batch's host->device transfer
  overlaps the CURRENT step's compute (jax dispatch is async), with the
  mesh's canonical batch sharding applied on the way in.
"""

from __future__ import annotations

import pathlib
from typing import Iterator

import jax
import numpy as np


class TokenLoader:
    """Deterministic [batch, seq_len] windows over a flat token stream."""

    def __init__(self, tokens: np.ndarray, batch_size: int, seq_len: int,
                 seed: int = 0) -> None:
        if tokens.ndim != 1:
            raise ValueError(f"token stream must be flat, got shape "
                             f"{tokens.shape}")
        self.tokens = tokens
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.seed = seed
        self.windows_per_epoch = len(tokens) // seq_len
        self.steps_per_epoch = self.windows_per_epoch // batch_size
        self._order_cache: tuple[int, np.ndarray] | None = None
        if self.steps_per_epoch == 0:
            raise ValueError(
                f"stream of {len(tokens)} tokens yields "
                f"{self.windows_per_epoch} windows of {seq_len} — fewer "
                f"than one batch of {batch_size}")

    @classmethod
    def from_memmap(cls, path: str | pathlib.Path, batch_size: int,
                    seq_len: int, dtype=np.uint16,
                    seed: int = 0) -> "TokenLoader":
        """A binary token file (e.g. uint16 little-endian, the common
        packed-corpus format), memory-mapped — the OS pages it."""
        tokens = np.memmap(path, dtype=dtype, mode="r")
        return cls(tokens, batch_size, seq_len, seed=seed)

    @classmethod
    def synthetic(cls, vocab_size: int, num_tokens: int, batch_size: int,
                  seq_len: int, seed: int = 0) -> "TokenLoader":
        """Deterministic fake stream (benchmarks, tests, dryruns)."""
        rng = np.random.default_rng(seed)
        tokens = rng.integers(0, vocab_size, size=num_tokens,
                              dtype=np.int32)
        return cls(tokens, batch_size, seq_len, seed=seed)

    # -- batch addressing ---------------------------------------------------
    def _order(self, epoch: int) -> np.ndarray:
        # one permutation per EPOCH, cached: regenerating it per batch
        # would cost O(windows) RNG work every step on a large corpus
        if self._order_cache is None or self._order_cache[0] != epoch:
            rng = np.random.default_rng((self.seed, epoch))
            self._order_cache = (epoch, rng.permutation(
                self.windows_per_epoch))
        return self._order_cache[1]

    def batch_at(self, step: int) -> np.ndarray:
        """The [batch, seq_len] int32 batch for global step `step` —
        pure addressing, so resume = start iterating at the right step."""
        epoch, within = divmod(step, self.steps_per_epoch)
        order = self._order(epoch)
        idx = order[within * self.batch_size:(within + 1) * self.batch_size]
        out = np.empty((self.batch_size, self.seq_len), np.int32)
        for row, w in enumerate(idx):
            start = int(w) * self.seq_len
            out[row] = self.tokens[start:start + self.seq_len]
        return out

    def batches(self, start_step: int = 0) -> Iterator[np.ndarray]:
        step = start_step
        while True:
            yield self.batch_at(step)
            step += 1

    # -- device feeding -----------------------------------------------------
    def device_iter(self, mesh=None, start_step: int = 0,
                    num_steps: int | None = None) -> Iterator[jax.Array]:
        """Batches on device with the canonical batch sharding, one batch
        prefetched ahead of the consumer (transfer overlaps compute)."""
        from nos_tpu.parallel.mesh import batch_sharding

        sharding = batch_sharding(mesh) if mesh is not None else None

        def put(arr: np.ndarray) -> jax.Array:
            return (jax.device_put(arr, sharding) if sharding is not None
                    else jax.device_put(arr))

        it = self.batches(start_step)
        if num_steps is not None:
            import itertools

            it = itertools.islice(it, num_steps)
        pending = None
        for arr in it:
            nxt = put(arr)     # dispatch transfer before yielding previous
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending
