"""Sharded training-state checkpointing (orbax).

Control-plane checkpoint/resume lives in the API substrate — all durable
state is annotations/CRDs, `kube/serialize.py` is the format, and every
controller restarts stateless (SURVEY.md §5).  This module is the
COMPUTE-side counterpart: save/restore a `ShardedTrainer`'s TrainState
with its NamedShardings intact, so a gang that was preempted (the
capacity scheduler evicts whole gangs) resumes on a re-carved slice from
its last step instead of from scratch.

Orbax handles the sharded array I/O; restore takes the *abstract* state
of a freshly built trainer as the target, so arrays come back with the
new mesh's shardings even if the gang landed on different physical hosts
(same mesh shape).  Saves are synchronous by default — the train loop
decides its own cadence, and a checkpoint that is still in flight when
preemption lands is exactly the failure this exists to prevent.
"""

from __future__ import annotations

import logging
import pathlib

import jax
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


class TrainCheckpointer:
    """Step-numbered TrainState checkpoints under one directory."""

    def __init__(self, directory: str | pathlib.Path,
                 max_to_keep: int = 3) -> None:
        self._mngr = ocp.CheckpointManager(
            pathlib.Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=False),
        )

    def save(self, step: int, state) -> bool:
        import flax.linen as nn

        # store plain arrays: the flax partitioning boxes are metadata the
        # resuming trainer re-derives from its own mesh/rules
        saved = self._mngr.save(step, args=ocp.args.StandardSave(
            nn.meta.unbox(state)))
        self._mngr.wait_until_finished()
        if not saved:
            # orbax declines saves to an already-existing step — silent
            # loss of a checkpoint must not look like success
            logger.warning("checkpoint: step %d already exists, NOT "
                           "overwritten (reusing a checkpoint_dir across "
                           "runs without resume?)", step)
            return False
        logger.info("checkpoint: saved step %d", step)
        return True

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(self, state_like, step: int | None = None):
        """Restore into the structure/shardings of `state_like` —
        preferably `trainer.abstract_state()` (shape/dtype/sharding only,
        no materialized init to pay for and throw away at resume time); a
        concrete TrainState also works (its flax partitioning boxes are
        unboxed to match what save() wrote)."""
        import flax.linen as nn

        step = self._mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")
        abstract = jax.tree_util.tree_map(
            ocp.utils.to_shape_dtype_struct, nn.meta.unbox(state_like))
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(abstract))
        logger.info("checkpoint: restored step %d", step)
        return restored

    def close(self) -> None:
        self._mngr.close()
