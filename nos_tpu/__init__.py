"""nos_tpu — a TPU-native dynamic-partitioning and elastic-quota framework.

A ground-up rebuild of the capabilities of Nebuly `nos` (reference:
/root/reference, a Go Kubernetes operator suite) for Cloud TPU:

- **Dynamic TPU partitioning**: a cluster-scoped planner watches pending pods
  requesting TPU slices and carves TPU pods (v4/v5e/v5p/v6e) into right-sized
  sub-slices (the analog of dynamic MIG partitioning; reference
  internal/partitioning/), actuated by per-node agents through a native
  C++ device shim (the analog of the NVML CGo boundary,
  reference pkg/gpu/nvml/client.go).
- **Fractional chip sharing**: MPS-analog time-shared chip profiles sized in
  HBM gigabytes (reference pkg/gpu/slicing/).
- **Elastic resource quotas**: ElasticQuota / CompositeElasticQuota with
  min/max, quota borrowing, over-quota preemption and guaranteed-over-quota
  fair sharing, denominated in `google.com/tpu` chips and TPU memory
  (reference pkg/scheduler/plugins/capacityscheduling/).
- **Gang scheduling**: all-or-nothing PodGroup admission across multi-host
  slices with ICI-contiguity topology filtering (new; no reference analog).
- **JAX compute path**: mesh/sharding utilities and reference workloads
  (Llama-style FSDP training, small inference) that run on carved slices.
"""

__version__ = "0.1.0"


def _install_native() -> None:
    # Back the geometry packer's hot loops with the C++ exact search when
    # the shim is already built (dlopen only — importing the package never
    # spawns a compiler; the build happens when a caller explicitly asks
    # for the native runtime, e.g. default_tpu_runtime()).  Best-effort:
    # every caller of topology.packing falls back to the pure Python
    # search when this fails, mirroring the reference's `nvml` build-tag
    # discipline (default builds run without the native library).
    try:
        from nos_tpu.device.native import install_native_packer

        install_native_packer(build=False)
    # Best-effort native-packer hook: importing nos_tpu must never
    # fail because an optional compiler is missing.
    # noslint: N005 — intentional swallow; every caller falls back to pure Python
    except Exception:  # noqa: BLE001 — import must never fail on this
        pass


_install_native()
