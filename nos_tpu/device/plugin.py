"""Device-plugin re-advertisement.

The reference reloads device inventory by *deleting the device-plugin pod*
and waiting for recreation (pkg/gpu/client.go:37-135 — the "restart hammer").
SURVEY.md §2.8 calls out config-driven re-advertisement as the better
template; here the plugin client recomputes the node's extended-resource
allocatable directly from the runtime's device list and stamps a
generation annotation, giving the decision plane a readiness signal instead
of the reference's blind sleep (mps/partitioner.go:99-100).
"""

from __future__ import annotations

from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_NODE
from nos_tpu.kube.objects import Node
from nos_tpu.utils.retry import retry_on_conflict

from .tpuclient import TpuRuntimeClient


class DevicePluginClient:
    def __init__(self, api: APIServer, node_name: str,
                 runtime: TpuRuntimeClient, manager=None) -> None:
        self._api = api
        self._node_name = node_name
        self._runtime = runtime
        # Optional kubelet-facing gRPC plugin manager
        # (nos_tpu/device/deviceplugin.DevicePluginManager): on a real
        # node the same refresh that updates the node object also
        # re-advertises through the device-plugin API.
        self._manager = manager

    def refresh(self) -> int:
        """Re-advertise slice resources from carved devices; returns the new
        plugin generation."""
        if self._manager is not None:
            self._manager.sync()
        counts: dict[str, int] = {}
        for d in self._runtime.list_devices():
            counts[d.resource_name] = counts.get(d.resource_name, 0) + 1

        new_gen = 0

        def mutate(node: Node) -> None:
            nonlocal new_gen
            for table in (node.status.allocatable, node.status.capacity):
                for res in [r for r in table
                            if r.startswith(C.RESOURCE_SLICE_PREFIX)]:
                    del table[res]
            for res, qty in counts.items():
                node.status.allocatable[res] = float(qty)
            node.status.capacity.update(node.status.allocatable)
            new_gen = int(
                node.metadata.annotations.get(C.ANNOT_PLUGIN_GENERATION, "0")
            ) + 1
            node.metadata.annotations[C.ANNOT_PLUGIN_GENERATION] = str(new_gen)

        retry_on_conflict(self._api, KIND_NODE, self._node_name, mutate,
                          component="device-plugin")
        return new_gen
