"""Native TPU runtime client: ctypes bindings for the C++ shim.

The production implementation of TpuRuntimeClient (the reference's
CGo/NVML analog, pkg/gpu/nvml/client.go, compiled only under the `nvml`
build tag).  The same gating discipline applies here: `available()` reports
whether the shim can be built/loaded, callers fall back to the fake
(nos_tpu/device/fake.py) exactly as the reference's default build runs with
mocks.  Build is `make` in nos_tpu/native (g++, no pybind11 — plain C ABI).
"""

from __future__ import annotations

import ctypes
import logging
import pathlib
import subprocess
import threading

from nos_tpu.topology import Device, DeviceList, FREE, Generation, Placement, Shape, V5E
from nos_tpu.topology.errors import DeviceNotFoundError
from nos_tpu.topology.profile import slice_resource_name

from .tpuclient import TpuRuntimeClient

logger = logging.getLogger(__name__)

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libnos_tpu_shim.so"
_BUILD_LOCK = threading.Lock()
_OUT_CAP = 1 << 20


def build_shim(force: bool = False) -> pathlib.Path | None:
    """Compile the shim if needed; returns the .so path or None."""
    with _BUILD_LOCK:
        if _SO_PATH.exists() and not force:
            return _SO_PATH
        try:
            subprocess.run(
                ["make", "-s", "libnos_tpu_shim.so"],
                cwd=_NATIVE_DIR, check=True, capture_output=True, text=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            logger.warning("native shim build failed: %s", detail)
            return None
        return _SO_PATH if _SO_PATH.exists() else None


_lib = None
_lib_failed = False


def _load():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    so = build_shim()
    if so is None:
        _lib_failed = True
        return None
    lib = ctypes.CDLL(str(so))
    lib.nos_runtime_new.restype = ctypes.c_void_p
    lib.nos_runtime_new.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int]
    lib.nos_runtime_free.argtypes = [ctypes.c_void_p]
    lib.nos_runtime_chips_per_host.argtypes = [ctypes.c_void_p]
    lib.nos_runtime_chips_per_host.restype = ctypes.c_int
    lib.nos_runtime_create_slices.restype = ctypes.c_int
    lib.nos_runtime_create_slices.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.nos_runtime_delete_slice.restype = ctypes.c_int
    lib.nos_runtime_delete_slice.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.nos_runtime_list.restype = ctypes.c_int
    lib.nos_runtime_list.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.nos_runtime_delete_all_except.restype = ctypes.c_int
    lib.nos_runtime_delete_all_except.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class NativeSliceError(Exception):
    pass


class NativeTpuRuntime(TpuRuntimeClient):
    """TpuRuntimeClient backed by the C++ shim."""

    def __init__(self, generation: Generation = V5E) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native shim unavailable (g++ build failed?) — use "
                "FakeTpuRuntime or check nos_tpu/native")
        self._lib = lib
        self._gen = generation
        dims = list(generation.host_block.dims) + [1] * (
            3 - len(generation.host_block.dims))
        arr = (ctypes.c_int * 3)(*dims)
        self._h = lib.nos_runtime_new(
            generation.name.encode(), arr, len(generation.host_block.dims))
        if not self._h:
            raise RuntimeError("nos_runtime_new failed")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.nos_runtime_free(h)
            self._h = None

    # -- TpuRuntimeClient ---------------------------------------------------
    def topology(self) -> tuple[str, Shape]:
        return self._gen.name, self._gen.host_block

    def _parse_list(self) -> list[tuple[str, int, Shape, bool, Placement]]:
        buf = ctypes.create_string_buffer(_OUT_CAP)
        rc = self._lib.nos_runtime_list(self._h, buf, _OUT_CAP)
        if rc < 0:
            raise NativeSliceError(f"nos_runtime_list rc={rc}")
        out = []
        text = buf.value.decode()
        if not text:
            return out
        for line in text.split("\n"):
            did, unit, shape_s, multi, off_s, dims_s = line.split(",")
            shape = Shape.parse(shape_s).canonical()
            pl = Placement(
                shape=shape,
                offset=tuple(int(v) for v in off_s.split(";")),
                dims=tuple(int(v) for v in dims_s.split(";")),
            )
            out.append((did, int(unit), shape, multi == "1", pl))
        return out

    def list_devices(self) -> DeviceList:
        out = DeviceList()
        for did, unit, shape, _multi, _pl in self._parse_list():
            out.append(Device(slice_resource_name(shape), did, FREE, unit))
        return out

    def placements(self) -> dict[str, Placement]:
        return {did: pl for did, _, _, _, pl in self._parse_list()}

    def create_slices(self, unit_index: int, shapes: list[Shape]) -> list[str]:
        flat = []
        for s in shapes:
            c = s.canonical()
            dims = list(c.dims) + [1] * (3 - len(c.dims))
            flat.extend(dims)
        arr = (ctypes.c_int * len(flat))(*flat)
        buf = ctypes.create_string_buffer(_OUT_CAP)
        rc = self._lib.nos_runtime_create_slices(
            self._h, unit_index, arr, len(shapes), buf, _OUT_CAP)
        if rc == -1:
            raise NativeSliceError(
                f"cannot place {[s.name for s in shapes]} on unit "
                f"{unit_index}")
        if rc < 0:
            raise NativeSliceError(f"create_slices rc={rc}")
        return buf.value.decode().split("\n") if buf.value else []

    def delete_slice(self, device_id: str) -> None:
        rc = self._lib.nos_runtime_delete_slice(self._h, device_id.encode())
        if rc != 0:
            raise DeviceNotFoundError(device_id)

    def delete_all_except(self, keep: set[str]) -> list[str]:
        buf = ctypes.create_string_buffer(_OUT_CAP)
        rc = self._lib.nos_runtime_delete_all_except(
            self._h, "\n".join(sorted(keep)).encode(), buf, _OUT_CAP)
        if rc < 0:
            raise NativeSliceError(f"delete_all_except rc={rc}")
        return buf.value.decode().split("\n") if buf.value else []
