"""Native TPU runtime client: ctypes bindings for the C++ shim.

The production implementation of TpuRuntimeClient (the reference's
CGo/NVML analog, pkg/gpu/nvml/client.go, compiled only under the `nvml`
build tag).  The same gating discipline applies here: `available()` reports
whether the shim can be built/loaded, callers fall back to the fake
(nos_tpu/device/fake.py) exactly as the reference's default build runs with
mocks.  Build is `make` in nos_tpu/native (g++, no pybind11 — plain C ABI).
"""

from __future__ import annotations

import ctypes
import functools
import logging
import pathlib
import subprocess
import threading

from nos_tpu.topology import Device, DeviceList, FREE, Generation, Placement, Shape, V5E
from nos_tpu.topology.errors import DeviceNotFoundError
from nos_tpu.topology.profile import slice_resource_name

from .tpuclient import TpuRuntimeClient

logger = logging.getLogger(__name__)

_NATIVE_DIR = pathlib.Path(__file__).resolve().parent.parent / "native"
_SO_PATH = _NATIVE_DIR / "libnos_tpu_shim.so"
_BUILD_LOCK = threading.Lock()
_OUT_CAP = 1 << 20


def build_shim(force: bool = False) -> pathlib.Path | None:
    """Compile the shim if needed; returns the .so path or None.  Always
    runs make (a no-op when fresh) so a prebuilt .so from an older
    tpu_shim.cc is rebuilt, not loaded stale."""
    with _BUILD_LOCK:
        try:
            cmd = ["make", "-s"] + (["-B"] if force else []) \
                + ["libnos_tpu_shim.so"]
            # _BUILD_LOCK exists to serialize this exact slow call.
            # noslint: N004 — one compiler at a time is the lock's purpose; callers opt in
            subprocess.run(cmd, cwd=_NATIVE_DIR, check=True,
                           capture_output=True, text=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            # noslint: N004 — failure path of the serialized build; nothing to convoy
            logger.warning("native shim build failed: %s", detail)
            return None
        return _SO_PATH if _SO_PATH.exists() else None


_lib = None
_lib_failed = False


def _load(allow_build: bool = True):
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    if allow_build:
        so = build_shim()
    else:
        # import-time path: dlopen an existing artifact only, never spawn
        # a compiler; leave _lib_failed unlatched so a later explicit
        # caller may still build.
        so = _SO_PATH if _SO_PATH.exists() else None
    if so is None:
        if allow_build:
            _lib_failed = True
        return None
    try:
        lib = _bind(so)
    except (OSError, AttributeError) as e:
        # e.g. a stale prebuilt .so missing a newer symbol: force-rebuild
        # once (when building is allowed), then give up cleanly so callers
        # fall back to the Python paths.
        logger.warning("native shim load failed: %s", e)
        lib = None
        if allow_build:
            so = build_shim(force=True)
            try:
                # dlopen caches by path STRING (verified empirically: a
                # rebuilt .so at the same path returns the stale handle
                # even with a new inode), so the rebuilt library must be
                # bound from a fresh name to displace the stale mapping.
                # The temp copy is unlinked immediately — the mapping
                # stays valid on Linux after unlink.
                if so is not None:
                    import os
                    import shutil
                    import tempfile

                    fd, tmp = tempfile.mkstemp(
                        suffix=".so", prefix="nos_tpu_shim_")
                    os.close(fd)
                    try:
                        shutil.copy2(so, tmp)
                        lib = _bind(pathlib.Path(tmp))
                    finally:
                        try:
                            os.unlink(tmp)
                        except OSError:
                            pass
            except (OSError, AttributeError) as e2:
                logger.warning("native shim unusable after rebuild: %s", e2)
        if lib is None:
            if allow_build:
                _lib_failed = True
            return None
    _lib = lib
    _install_packer_seam()
    return _lib


def _bind(so: pathlib.Path):
    lib = ctypes.CDLL(str(so))
    lib.nos_runtime_new.restype = ctypes.c_void_p
    lib.nos_runtime_new.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int), ctypes.c_int]
    lib.nos_runtime_free.argtypes = [ctypes.c_void_p]
    lib.nos_runtime_chips_per_host.argtypes = [ctypes.c_void_p]
    lib.nos_runtime_chips_per_host.restype = ctypes.c_int
    lib.nos_runtime_create_slices.restype = ctypes.c_int
    lib.nos_runtime_create_slices.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.c_char_p, ctypes.c_int]
    lib.nos_runtime_delete_slice.restype = ctypes.c_int
    lib.nos_runtime_delete_slice.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.nos_runtime_list.restype = ctypes.c_int
    lib.nos_runtime_list.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.nos_runtime_delete_all_except.restype = ctypes.c_int
    lib.nos_runtime_delete_all_except.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.nos_pack.restype = ctypes.c_int
    lib.nos_pack.argtypes = [
        ctypes.POINTER(ctypes.c_int), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int]
    lib.nos_fit_batch.restype = ctypes.c_int
    lib.nos_fit_batch.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double),
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint64)]
    # two-party GIL-release handshake (tests/test_native.py): binding it
    # here also makes a stale prebuilt .so missing the symbol rebuild
    lib.nos_gil_handshake.restype = ctypes.c_int
    lib.nos_gil_handshake.argtypes = [
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_double]
    # incremental decision plane (ISSUE 18): window-busy sort, Score
    # argmin, victim prescreen — declared here so a stale .so missing
    # any of them raises AttributeError and triggers the forced rebuild
    lib.nos_window_busy.restype = ctypes.c_int
    lib.nos_window_busy.argtypes = [
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_longlong]
    lib.nos_score_batch.restype = ctypes.c_int
    lib.nos_score_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_longlong, ctypes.c_longlong,
        ctypes.POINTER(ctypes.c_longlong)]
    lib.nos_victim_prescreen.restype = ctypes.c_int
    lib.nos_victim_prescreen.argtypes = [
        ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_longlong), ctypes.c_longlong,
        ctypes.c_longlong, ctypes.c_longlong, ctypes.POINTER(ctypes.c_uint8)]
    return lib


def _install_packer_seam() -> None:
    """Whenever the shim is successfully loaded — lazily by any caller —
    also back topology.packing's hot loops with the C++ search."""
    from nos_tpu.topology import packing

    packing.set_native_packer(native_packer)


def available(build: bool = True) -> bool:
    return _load(allow_build=build) is not None


@functools.lru_cache(maxsize=65536)
def _native_pack_cached(block: Shape, key: tuple, occupied: int,
                        require_full: bool):
    lib = _load()
    ndims = len(block.dims)
    bdims = list(block.dims) + [1] * (3 - ndims)
    n = len(key)
    shapes_flat: list[int] = []
    counts: list[int] = []
    for shape, cnt in key:
        dims = list(shape.canonical().dims) + [1] * (
            3 - len(shape.canonical().dims))
        shapes_flat.extend(dims)
        counts.append(cnt)
    buf = ctypes.create_string_buffer(_OUT_CAP)
    rc = lib.nos_pack(
        (ctypes.c_int * 3)(*bdims), ndims,
        (ctypes.c_int * max(1, len(shapes_flat)))(*shapes_flat),
        (ctypes.c_int * max(1, n))(*counts), n,
        ctypes.c_uint64(occupied), int(require_full), buf, _OUT_CAP)
    if rc == -1:
        return None
    if rc < 0:
        raise NativeSliceError(f"nos_pack rc={rc}")
    out = []
    text = buf.value.decode()
    lines = text.split("\n") if text else []
    for line in lines:
        dims_s, off_s = line.split(",")
        dims = tuple(int(v) for v in dims_s.split(";"))[:ndims]
        offset = tuple(int(v) for v in off_s.split(";"))[:ndims]
        # canonical shape == sorted oriented dims, by definition
        out.append(Placement(Shape(tuple(sorted(dims))), offset, dims))
    return tuple(out)


_pack_failed_keys: set[tuple] = set()


def native_packer(block: Shape, key: tuple, occupied: int,
                  require_full: bool):
    """set_native_packer-compatible bridge to the C++ exact search
    (nos_pack in tpu_shim.cc).  Memoised with the same key discipline as
    the Python packer's cache; returns NotImplemented if the shim cannot
    be loaded so the caller falls back to the Python search.  Inputs the
    shim cannot represent (blocks over 64 chips — its occupancy bitmask
    limit, tpu_shim.cc nos_pack) are rejected up front, and failures are
    latched per key so a hot-path caller neither re-enters the native
    search nor re-logs the fallback warning."""
    if block.chips > 64 or len(block.dims) > 3:
        return NotImplemented
    if _load() is None:
        return NotImplemented
    full_key = (block, key, occupied, require_full)
    if full_key in _pack_failed_keys:
        return NotImplemented
    try:
        return _native_pack_cached(block, key, occupied, require_full)
    except NativeSliceError as e:
        logger.warning("native packer failed (%s); falling back", e)
        if len(_pack_failed_keys) >= 65536:  # same bound as the lru above
            _pack_failed_keys.clear()
        _pack_failed_keys.add(full_key)
        return NotImplemented


# Bit 63 of a nos_fit_batch miss mask flags the chip-equivalent guard
# (tpu_shim.cc): the resource indices occupy bits 0..62.
FIT_MISS_CHIP_GUARD = 1 << 63
FIT_MAX_RESOURCES = 63


def fit_batch_available(build: bool = False) -> bool:
    """Whether the native batch fit screen can run (shim loadable)."""
    return _load(allow_build=build) is not None


def fit_batch_raw(free_arr: "ctypes.Array[ctypes.c_double]",
                  req_arr: "ctypes.Array[ctypes.c_double]",
                  cap_arr: "ctypes.Array[ctypes.c_double]",
                  used_arr: "ctypes.Array[ctypes.c_double]",
                  chips_arr: "ctypes.Array[ctypes.c_double]",
                  n_nodes: int, n_classes: int, n_res: int,
                  out_arr: "ctypes.Array[ctypes.c_uint8]",
                  miss_arr: "ctypes.Array[ctypes.c_uint64] | None" = None,
                  ) -> bool:
    """Zero-copy variant of fit_batch for hot callers that pre-build
    (and reuse) the ctypes buffers — the planner compiles its class
    request matrix ONCE per plan and pays only one node row per
    candidate.  Returns False when the shim is unavailable/rejects."""
    lib = _load(allow_build=False)      # never compile from a hot path
    if lib is None or n_res > FIT_MAX_RESOURCES:
        return False
    rc = lib.nos_fit_batch(free_arr, req_arr, cap_arr, used_arr,
                           chips_arr, n_nodes, n_classes, n_res,
                           out_arr, miss_arr)
    return rc == 0


def fit_batch(free_flat: list[float], req_flat: list[float],
              node_cap_chips: list[float], node_used_chips: list[float],
              class_chips: list[float], n_nodes: int, n_classes: int,
              n_res: int, want_miss: bool = True
              ) -> tuple[bytes, list[int] | None] | None:
    """Bridge to nos_fit_batch (tpu_shim.cc): N nodes x M classes
    resource-fit verdicts with NodeResourcesFit's exact semantics.

    Returns (verdict bytes, miss masks) — verdict[i*n_classes+j] == 1
    means class j fits node i; miss masks carry the failing resource
    indices (bit 63 = chip guard) for exact message reconstruction.
    None when the shim is unavailable or rejects the arguments (the
    caller falls back to the Python pipeline).  Like every shim entry
    point this goes through ctypes' CDLL, which RELEASES the GIL for
    the duration of the call — concurrent plan shards screening at
    once genuinely overlap (tests/test_native.py pins the overlap)."""
    if n_res > FIT_MAX_RESOURCES:
        return None
    lib = _load(allow_build=False)      # never compile from a hot path
    if lib is None:
        return None
    cells = n_nodes * n_classes
    out = (ctypes.c_uint8 * max(1, cells))()
    miss = (ctypes.c_uint64 * max(1, cells))() if want_miss else None
    rc = lib.nos_fit_batch(
        (ctypes.c_double * max(1, len(free_flat)))(*free_flat),
        (ctypes.c_double * max(1, len(req_flat)))(*req_flat),
        (ctypes.c_double * max(1, len(node_cap_chips)))(*node_cap_chips),
        (ctypes.c_double * max(1, len(node_used_chips)))(*node_used_chips),
        (ctypes.c_double * max(1, len(class_chips)))(*class_chips),
        n_nodes, n_classes, n_res, out, miss)
    if rc != 0:
        return None
    return bytes(out[:cells]), (list(miss[:cells])
                                if miss is not None else None)


def window_busy_sort(gid_arr: "ctypes.Array[ctypes.c_longlong]",
                     idx_arr: "ctypes.Array[ctypes.c_longlong]",
                     val_arr: "ctypes.Array[ctypes.c_uint8]",
                     n: int) -> bool:
    """In-place lexicographic sort of the (gid, host-index, busy)
    triples via nos_window_busy — the native form of Python's
    `sorted(triples)` over the window-busy map.  Returns False when the
    shim is unavailable/rejects (caller sorts in Python)."""
    lib = _load(allow_build=False)      # never compile from a hot path
    if lib is None:
        return False
    return lib.nos_window_busy(gid_arr, idx_arr, val_arr, n) == 0


def score_batch(avoided, headroom, gids, widx, hidx, rank, wsizes, woff,
                busy_gid, busy_idx, busy_val, n: int, m: int) -> int | None:
    """Bridge to nos_score_batch (tpu_shim.cc): the Score argmin over n
    pre-marshalled candidates against an m-entry sorted window-busy
    table.  Returns the winning candidate index, or None when the shim
    is unavailable or rejects the arguments (caller runs the Python
    min).  GIL released for the duration (ctypes CDLL), so planner
    shards scoring concurrently genuinely overlap."""
    lib = _load(allow_build=False)      # never compile from a hot path
    if lib is None:
        return None
    out = ctypes.c_longlong(-1)
    rc = lib.nos_score_batch(avoided, headroom, gids, widx, hidx, rank,
                             wsizes, woff, busy_gid, busy_idx, busy_val,
                             n, m, ctypes.byref(out))
    if rc != 0 or out.value < 0 or out.value >= n:
        return None
    return out.value


def victim_prescreen(alloc_rows: list[list[float]], req: list[float],
                     cap_chips: list[int], pod_chips: int
                     ) -> list[bool] | None:
    """Bridge to nos_victim_prescreen (tpu_shim.cc): per-node
    empty-node fit verdicts for the preemption walk's persistent
    prescreen (NodeResourcesFit at zero occupancy).  Returns None when
    the shim is unavailable/rejects (caller screens in Python)."""
    lib = _load(allow_build=False)      # never compile from a hot path
    if lib is None:
        return None
    n = len(alloc_rows)
    n_res = len(req)
    flat = [v for row in alloc_rows for v in row]
    out = (ctypes.c_uint8 * max(1, n))()
    rc = lib.nos_victim_prescreen(
        (ctypes.c_double * max(1, len(flat)))(*flat),
        (ctypes.c_double * max(1, n_res))(*req),
        (ctypes.c_longlong * max(1, n))(*cap_chips),
        pod_chips, n, n_res, out)
    if rc != 0:
        return None
    return [bool(v) for v in out[:n]]


def install_native_packer(build: bool = False) -> bool:
    """Back topology.packing's hot loops with the C++ search.  With
    build=False (the nos_tpu-import default) this only dlopens an
    already-built .so — importing the package must never spawn a compiler.
    Any later caller that explicitly asks for the native runtime (e.g.
    default_tpu_runtime) triggers the build, and _load installs the packer
    seam as a side effect at that point."""
    return available(build=build)


class NativeSliceError(Exception):
    pass


class NativeTpuRuntime(TpuRuntimeClient):
    """TpuRuntimeClient backed by the C++ shim.

    With generation=None the runtime *discovers* its topology (PJRT device
    attributes / Cloud TPU env metadata — nos_tpu/device/discovery.py, the
    NVML-enumeration analog of reference pkg/gpu/nvml/client.go:31-518)
    instead of asserting it, and the device table is sized to the observed
    host block, so carved slices name real chips.  Passing a Generation
    keeps the asserted behavior (off-TPU control-plane and tests)."""

    def __init__(self, generation: Generation | None = V5E) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native shim unavailable (g++ build failed?) — use "
                "FakeTpuRuntime or check nos_tpu/native")
        self._lib = lib
        if generation is None:
            from . import discovery

            self._disc = discovery.discover()
            self._gen = self._disc.generation
            self._host_block = self._disc.host_block
        else:
            self._disc = None
            self._gen = generation
            self._host_block = generation.host_block
        dims = list(self._host_block.dims) + [1] * (
            3 - len(self._host_block.dims))
        arr = (ctypes.c_int * 3)(*dims)
        self._h = lib.nos_runtime_new(
            self._gen.name.encode(), arr, len(self._host_block.dims))
        if not self._h:
            raise RuntimeError("nos_runtime_new failed")

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.nos_runtime_free(h)
            self._h = None

    # -- TpuRuntimeClient ---------------------------------------------------
    def topology(self) -> tuple[str, Shape]:
        return self._gen.name, self._host_block

    @property
    def topology_source(self) -> str:
        """How the topology was learned: "device" (PJRT), "env" (Cloud TPU
        VM metadata), or "configured" (asserted by the constructor)."""
        from . import discovery

        return self._disc.source if self._disc else discovery.SOURCE_CONFIGURED

    @property
    def discovered(self):
        return self._disc

    def _parse_list(self) -> list[tuple[str, int, Shape, bool, Placement]]:
        buf = ctypes.create_string_buffer(_OUT_CAP)
        rc = self._lib.nos_runtime_list(self._h, buf, _OUT_CAP)
        if rc < 0:
            raise NativeSliceError(f"nos_runtime_list rc={rc}")
        out = []
        text = buf.value.decode()
        if not text:
            return out
        for line in text.split("\n"):
            did, unit, shape_s, multi, off_s, dims_s = line.split(",")
            shape = Shape.parse(shape_s).canonical()
            pl = Placement(
                shape=shape,
                offset=tuple(int(v) for v in off_s.split(";")),
                dims=tuple(int(v) for v in dims_s.split(";")),
            )
            out.append((did, int(unit), shape, multi == "1", pl))
        return out

    def list_devices(self) -> DeviceList:
        out = DeviceList()
        for did, unit, shape, _multi, _pl in self._parse_list():
            out.append(Device(slice_resource_name(shape), did, FREE, unit))
        return out

    def placements(self) -> dict[str, Placement]:
        return {did: pl for did, _, _, _, pl in self._parse_list()}

    def create_slices(self, unit_index: int, shapes: list[Shape]) -> list[str]:
        flat = []
        for s in shapes:
            c = s.canonical()
            dims = list(c.dims) + [1] * (3 - len(c.dims))
            flat.extend(dims)
        arr = (ctypes.c_int * len(flat))(*flat)
        buf = ctypes.create_string_buffer(_OUT_CAP)
        rc = self._lib.nos_runtime_create_slices(
            self._h, unit_index, arr, len(shapes), buf, _OUT_CAP)
        if rc == -1:
            from nos_tpu.topology.errors import PlacementInfeasibleError
            raise PlacementInfeasibleError(
                f"cannot place {[s.name for s in shapes]} on unit "
                f"{unit_index}")
        if rc < 0:
            raise NativeSliceError(f"create_slices rc={rc}")
        return buf.value.decode().split("\n") if buf.value else []

    def delete_slice(self, device_id: str) -> None:
        rc = self._lib.nos_runtime_delete_slice(self._h, device_id.encode())
        if rc != 0:
            raise DeviceNotFoundError(device_id)

    def delete_all_except(self, keep: set[str]) -> list[str]:
        buf = ctypes.create_string_buffer(_OUT_CAP)
        rc = self._lib.nos_runtime_delete_all_except(
            self._h, "\n".join(sorted(keep)).encode(), buf, _OUT_CAP)
        if rc < 0:
            raise NativeSliceError(f"delete_all_except rc={rc}")
        return buf.value.decode().split("\n") if buf.value else []
