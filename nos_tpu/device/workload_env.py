"""Workload-side device environment: honor what the scheduler granted.

The control plane's grants reach the container as environment variables
(the device plugin's Allocate response — `NOS_TPU_SLICE_IDS` — plus the
pod's own resource requests mirrored by the operator); this module is
what the workload calls BEFORE its first jax import so the process
actually respects them:

- a **timeshare** grant (`nos.tpu/tpu-<N>gb`) caps jax's HBM usage at
  the granted fraction via XLA_PYTHON_CLIENT_MEM_FRACTION — without it,
  jax preallocates ~75% of HBM and the co-located sharers the timeshare
  plan promised would OOM each other (the MPS-resource-limit analog).
  The chip's HBM size comes from topology discovery (env metadata, no
  jax), so the fraction is right on every generation;
- a **slice** grant's device ids are surfaced to the workload
  (TPU_VISIBLE_SLICE_IDS) for job-side tooling and debugging, and the
  granted chips' local ids (exported per-profile by the device plugin's
  Allocate response from the carved placements — NOS_TPU_VISIBLE_CHIPS_*)
  become the libtpu visibility env: TPU_VISIBLE_CHIPS, plus
  TPU_PROCESS_BOUNDS/TPU_CHIPS_PER_PROCESS_BOUNDS when the granted set
  is one contiguous sub-mesh.  This is the TPU_VISIBLE_CHIPS analog of
  MIG device visibility (reference pkg/gpu/nvml/client.go:286-340): a
  jax process started after apply() sees ONLY the granted chips instead
  of grabbing every chip on the host.

  CAVEAT — numbering convention: the grant's chip ids are row-major
  placement cells in the host block (topology.packing.placement_cells),
  and libtpu is ASSUMED to number local chips the same way.  That holds
  for the documented Cloud TPU host layouts, but it is not provable on
  this repo's single-chip CI host, so confinement is belt-and-braces:
  call `validate_confinement()` after the first jax import — it checks
  the visible device COUNT and (where PJRT exposes coords) that the
  visible devices' local coordinates are exactly the granted cells, and
  raises before any work runs on wrongly-shared chips if the host's
  enumeration disagrees.

Analog of what the NVIDIA stack does implicitly through MPS
active-thread percentage and MIG device visibility; on TPU the runtime
has no such enforcement layer, so the framework provides the cooperative
one and the sharing demo (demos/tpu-sharing-comparison) measures its
behavior.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

# One env var per granted profile (the device plugin appends the profile
# suffix so a container holding several profiles does not have their
# grants clobber each other in the kubelet's env merge); the bare key is
# accepted too.  The workload's cap is the SUM of every grant.
ENV_TIMESHARE_GB = "NOS_TPU_TIMESHARE_GB"
ENV_SLICE_IDS = "NOS_TPU_SLICE_IDS"
ENV_VISIBLE_CHIPS = "NOS_TPU_VISIBLE_CHIPS"
ENV_HOST_BOUNDS = "NOS_TPU_HOST_BOUNDS"


def granted_chip_ids(environ) -> list[int] | None:
    """Union of every per-profile visibility grant (local chip ids,
    row-major in the host block — topology.packing.placement_cells).
    Any corrupt token voids the WHOLE grant (returns None): confining
    the process to a silently under-sized subset of its grant is worse
    than not confining it (mirrors the plugin side's 'never claim
    visibility we cannot derive')."""
    chips: set[int] = set()
    for key, value in environ.items():
        if key == ENV_VISIBLE_CHIPS or key.startswith(
                ENV_VISIBLE_CHIPS + "_"):
            for part in str(value).split(","):
                try:
                    chips.add(int(part))
                except ValueError:
                    logger.warning(
                        "corrupt visibility grant %s=%r: not confining",
                        key, value)
                    return None
    return sorted(chips)


def _chip_bounds(chips: list[int], host_bounds: str) -> tuple[int, ...] | None:
    """Bounding box of the granted chips in the host block; None unless
    the chips exactly fill it (only a contiguous sub-mesh can be
    described to libtpu as process bounds)."""
    try:
        bdims = [int(d) for d in host_bounds.split("x")]
    except ValueError:
        return None
    total = 1
    for d in bdims:
        total *= d
    if not bdims or any(d < 1 for d in bdims) \
            or any(c < 0 or c >= total for c in chips):
        return None
    coords = []
    for c in chips:
        coord = []
        for d in reversed(bdims):
            coord.append(c % d)
            c //= d
        coords.append(tuple(reversed(coord)))
    lo = [min(c[i] for c in coords) for i in range(len(bdims))]
    hi = [max(c[i] for c in coords) for i in range(len(bdims))]
    box = tuple(h - l + 1 for l, h in zip(lo, hi))
    size = 1
    for d in box:
        size *= d
    return box if size == len(chips) else None


def granted_timeshare_gb(environ) -> float:
    total = 0.0
    for key, value in environ.items():
        if key == ENV_TIMESHARE_GB or key.startswith(
                ENV_TIMESHARE_GB + "_"):
            try:
                total += float(value)
            except ValueError:
                logger.warning("ignoring unparseable %s=%r", key, value)
    return total
# Leave headroom below the granted fraction: XLA's allocator needs slack
# for fragmentation, and N sharers at exactly 1/N would collectively
# exceed HBM.
SAFETY = 0.9


def apply(environ=os.environ,
          hbm_gb_per_chip: int | None = None) -> dict[str, str]:
    """Derive jax/XLA env settings from the scheduler's grants; returns
    what was set.  Must run before the first jax import."""
    applied: dict[str, str] = {}
    if hbm_gb_per_chip is None:
        # jax-free discovery (env metadata / configured fallback): an
        # 8 GB grant must cap 8/95 on v5p, not 8/16
        from nos_tpu.device import discovery

        hbm_gb_per_chip = discovery.discover(
            allow_jax=False, environ=environ).generation.hbm_gb_per_chip
    gb = granted_timeshare_gb(environ)
    if gb > 0:
        fraction = min(gb / hbm_gb_per_chip * SAFETY, 0.95)
        applied["XLA_PYTHON_CLIENT_MEM_FRACTION"] = f"{fraction:.3f}"
        # growing allocation within the cap plays nicer with sharers
        # than preallocating the whole fraction up front
        applied["XLA_PYTHON_CLIENT_PREALLOCATE"] = "false"
    slice_ids = environ.get(ENV_SLICE_IDS, "")
    if slice_ids:
        # the carved devices this pod owns (device-plugin Allocate env),
        # surfaced for job-side tooling/debugging — see module docstring
        applied["TPU_VISIBLE_SLICE_IDS"] = slice_ids
    chips = granted_chip_ids(environ)
    visibility_keys = ("TPU_VISIBLE_CHIPS", "TPU_PROCESS_BOUNDS",
                      "TPU_CHIPS_PER_PROCESS_BOUNDS")
    if chips and not any(k in environ for k in visibility_keys):
        # chip-visibility enforcement: confine the jax process to the
        # granted chips (libtpu honors these before backend init).  The
        # three keys are emitted all-or-none, and never when ANY of them
        # pre-exists — mixing a grant's bounds with an operator's own
        # visibility settings would describe a contradictory topology.
        applied["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chips)
        box = _chip_bounds(chips, environ.get(ENV_HOST_BOUNDS, ""))
        if box is not None:
            padded = tuple(box) + (1,) * (3 - len(box))
            applied["TPU_PROCESS_BOUNDS"] = "1,1,1"
            applied["TPU_CHIPS_PER_PROCESS_BOUNDS"] = \
                ",".join(str(d) for d in padded)
    for key, value in applied.items():
        environ.setdefault(key, value)
        logger.info("workload env: %s=%s", key, environ[key])
    return applied


class ConfinementError(RuntimeError):
    """The jax process does NOT match its visibility grant — running on
    would share chips with another slice's workload."""


def _local_coords(cells: list[int], bounds: str) -> set[tuple[int, ...]] | None:
    """Row-major cell ids -> host-local coordinates; None on bad input."""
    try:
        bdims = [int(d) for d in bounds.split("x")]
    except ValueError:
        return None
    total = 1
    for d in bdims:
        total *= d
    if not bdims or any(d < 1 for d in bdims) \
            or any(c < 0 or c >= total for c in cells):
        return None
    out = set()
    for c in cells:
        coord = []
        for d in reversed(bdims):
            coord.append(c % d)
            c //= d
        out.add(tuple(reversed(coord)))
    return out


def check_confinement(granted: list[int], devices,
                      host_bounds: str) -> None:
    """Pure core of validate_confinement (tested without a TPU).
    `devices` is the jax.devices() list of the CONFINED process; each
    device's `.coords` (PJRT, global pod coordinates) is compared — after
    rebasing to the host-local origin — against the granted cells'
    coordinates in the host block.  Raises ConfinementError on count or
    coordinate mismatch; silently returns when the runtime exposes no
    coords (count is then the only check available).

    GUARANTEE IS SHAPE-ONLY: both the visible coords and the granted
    cells are rebased to their own origins before comparison, so the
    check is **translation-invariant** — a process wrongly confined to a
    *different same-shape sub-block* of the host passes.  This is
    inherent, not an oversight: libtpu renumbers visible chips from a
    local origin, so the absolute position of the visible block is
    unverifiable from inside the process.  The check proves "I see
    exactly N chips arranged exactly like my grant", not "I see the
    grant's exact cells" — cross-slice isolation against a buggy or
    adversarial granter still rests on the device plugin handing out
    disjoint cell sets (deviceplugin allocation), and operators must
    not read a pass as proof of absolute placement."""
    if len(devices) != len(granted):
        raise ConfinementError(
            f"visibility grant promised {len(granted)} chip(s) "
            f"{granted} but jax sees {len(devices)} — libtpu did not "
            f"honor TPU_VISIBLE_CHIPS, or the grant was clobbered")
    want = _local_coords(granted, host_bounds)
    coords = []
    for d in devices:
        c = getattr(d, "coords", None)
        if c is None:
            return              # runtime exposes no coords: count-only
        coords.append(tuple(c))
    if want is None or not coords:
        return
    ndim = len(next(iter(want)))
    if any(len(c) < ndim for c in coords) \
            or len({len(c) for c in coords}) != 1:
        raise ConfinementError(
            f"visible device coords {coords} have rank below the host "
            f"bounds {host_bounds!r} rank — cannot verify confinement; "
            f"refusing to run on an unverifiable chip set")
    origin = tuple(min(c[i] for c in coords) for i in range(len(coords[0])))
    got = {tuple(c[i] - origin[i] for i in range(ndim))
           for c in coords}
    # rebase the granted cells to their own origin too: the grant may be
    # an interior sub-block (e.g. cells {2,3}) while the visible devices
    # are renumbered from the host origin
    want_origin = tuple(min(c[i] for c in want) for i in range(ndim))
    want_rebased = {tuple(c[i] - want_origin[i] for i in range(ndim))
                    for c in want}
    if got != want_rebased:
        raise ConfinementError(
            f"visible device coords {sorted(got)} != granted cells "
            f"{sorted(want_rebased)} (host bounds {host_bounds!r}): "
            f"libtpu's local chip numbering disagrees with the row-major "
            f"placement convention on this host — STOP, the process may "
            f"be confined to another slice's chips")


def validate_confinement(environ=os.environ) -> bool:
    """Post-jax-init check that the process really is confined to its
    grant (module docstring CAVEAT).  Returns True when a grant was
    present and verified, False when there was nothing to check; raises
    ConfinementError on mismatch."""
    granted = granted_chip_ids(environ)
    if not granted:
        return False
    import jax

    if jax.default_backend() != "tpu":
        return False    # visibility envs only bind libtpu; nothing to check
    # local_devices, NOT devices: the grant is per-host, and after
    # jax.distributed.initialize a multi-host gang's global device list
    # spans every member — a correctly-confined member would fail the
    # count check against it.
    check_confinement(granted, jax.local_devices(),
                      environ.get(ENV_HOST_BOUNDS, ""))
    return True
