"""Workload-side device environment: honor what the scheduler granted.

The control plane's grants reach the container as environment variables
(the device plugin's Allocate response — `NOS_TPU_SLICE_IDS` — plus the
pod's own resource requests mirrored by the operator); this module is
what the workload calls BEFORE its first jax import so the process
actually respects them:

- a **timeshare** grant (`nos.tpu/tpu-<N>gb`) caps jax's HBM usage at
  the granted fraction via XLA_PYTHON_CLIENT_MEM_FRACTION — without it,
  jax preallocates ~75% of HBM and the co-located sharers the timeshare
  plan promised would OOM each other (the MPS-resource-limit analog).
  The chip's HBM size comes from topology discovery (env metadata, no
  jax), so the fraction is right on every generation;
- a **slice** grant's device ids are surfaced to the workload
  (TPU_VISIBLE_SLICE_IDS) for job-side tooling and debugging.  Chip-level
  visibility enforcement (the TPU_VISIBLE_CHIPS analog of MIG device
  visibility) needs the agent to export the slice's chip coordinates —
  not wired yet, and not claimed.

Analog of what the NVIDIA stack does implicitly through MPS
active-thread percentage and MIG device visibility; on TPU the runtime
has no such enforcement layer, so the framework provides the cooperative
one and the sharing demo (demos/tpu-sharing-comparison) measures its
behavior.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

# One env var per granted profile (the device plugin appends the profile
# suffix so a container holding several profiles does not have their
# grants clobber each other in the kubelet's env merge); the bare key is
# accepted too.  The workload's cap is the SUM of every grant.
ENV_TIMESHARE_GB = "NOS_TPU_TIMESHARE_GB"
ENV_SLICE_IDS = "NOS_TPU_SLICE_IDS"


def granted_timeshare_gb(environ) -> float:
    total = 0.0
    for key, value in environ.items():
        if key == ENV_TIMESHARE_GB or key.startswith(
                ENV_TIMESHARE_GB + "_"):
            try:
                total += float(value)
            except ValueError:
                logger.warning("ignoring unparseable %s=%r", key, value)
    return total
# Leave headroom below the granted fraction: XLA's allocator needs slack
# for fragmentation, and N sharers at exactly 1/N would collectively
# exceed HBM.
SAFETY = 0.9


def apply(environ=os.environ,
          hbm_gb_per_chip: int | None = None) -> dict[str, str]:
    """Derive jax/XLA env settings from the scheduler's grants; returns
    what was set.  Must run before the first jax import."""
    applied: dict[str, str] = {}
    if hbm_gb_per_chip is None:
        # jax-free discovery (env metadata / configured fallback): an
        # 8 GB grant must cap 8/95 on v5p, not 8/16
        from nos_tpu.device import discovery

        hbm_gb_per_chip = discovery.discover(
            allow_jax=False, environ=environ).generation.hbm_gb_per_chip
    gb = granted_timeshare_gb(environ)
    if gb > 0:
        fraction = min(gb / hbm_gb_per_chip * SAFETY, 0.95)
        applied["XLA_PYTHON_CLIENT_MEM_FRACTION"] = f"{fraction:.3f}"
        # growing allocation within the cap plays nicer with sharers
        # than preallocating the whole fraction up front
        applied["XLA_PYTHON_CLIENT_PREALLOCATE"] = "false"
    slice_ids = environ.get(ENV_SLICE_IDS, "")
    if slice_ids:
        # the carved devices this pod owns (device-plugin Allocate env),
        # surfaced for job-side tooling/debugging — see module docstring
        applied["TPU_VISIBLE_SLICE_IDS"] = slice_ids
    for key, value in applied.items():
        environ.setdefault(key, value)
        logger.info("workload env: %s=%s", key, environ[key])
    return applied
