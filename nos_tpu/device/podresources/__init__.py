"""Kubelet pod-resources gRPC client: the production PodResourcesClient.

The reference dials the kubelet's pod-resources unix socket to learn
which devices are allocated to running pods
(pkg/resource/lister.go:28-38, client.go:39-87); this is the same
client for `google.com/tpu` and the nos.tpu slice/timeshare profile
resources.  The proto subset lives in podresources.proto (generated
podresources_pb2.py is committed; regenerate with
`protoc --python_out=. podresources.proto`).

Everything above the PodResourcesClient seam keeps running against
FakePodResources off-cluster (the reference's mock discipline).
"""

from __future__ import annotations

import logging

from nos_tpu.device.tpuclient import PodResourcesClient

logger = logging.getLogger(__name__)

DEFAULT_SOCKET = "/var/lib/kubelet/pod-resources/kubelet.sock"
_LIST_METHOD = "/v1.PodResourcesLister/List"

# Resource prefixes whose device ids name TPU hardware.
TPU_RESOURCE_PREFIXES = ("nos.tpu/", "google.com/tpu")


class KubeletPodResourcesClient(PodResourcesClient):
    """PodResourcesClient over the kubelet gRPC socket."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET,
                 timeout_s: float = 5.0,
                 resource_prefixes=TPU_RESOURCE_PREFIXES) -> None:
        import grpc

        from . import podresources_pb2

        self._pb = podresources_pb2
        self._timeout = timeout_s
        self._prefixes = tuple(resource_prefixes)
        target = socket_path if "://" in socket_path \
            else f"unix://{socket_path}"
        self._channel = grpc.insecure_channel(target)
        self._list = self._channel.unary_unary(
            _LIST_METHOD,
            request_serializer=podresources_pb2.ListPodResourcesRequest
            .SerializeToString,
            response_deserializer=podresources_pb2.ListPodResourcesResponse
            .FromString,
        )

    def close(self) -> None:
        self._channel.close()

    def list_pod_resources(self):
        """Raw ListPodResourcesResponse (all resources, all pods)."""
        return self._list(self._pb.ListPodResourcesRequest(),
                          timeout=self._timeout)

    def used_device_ids(self) -> set[str]:
        out: set[str] = set()
        resp = self.list_pod_resources()
        for pod in resp.pod_resources:
            for container in pod.containers:
                for dev in container.devices:
                    if dev.resource_name.startswith(self._prefixes):
                        out.update(dev.device_ids)
        return out


__all__ = ["DEFAULT_SOCKET", "KubeletPodResourcesClient",
           "TPU_RESOURCE_PREFIXES"]
