"""Fake device layer for tests and the hardware-free simulator.

The analog of the mockery-generated nvml/mig/resource mocks (reference
pkg/test/mocks/**) — but stateful: FakeTpuRuntime actually maintains carved
devices with placements and enforces packing feasibility, so agent tests
exercise the same geometry constraints the native shim would.
"""

from __future__ import annotations

import itertools
import threading

from nos_tpu.topology import (
    Device, DeviceList, FREE, Placement, Shape, V5E, Generation,
    extend,
)
from nos_tpu.topology.profile import slice_resource_name

from nos_tpu.topology.errors import PlacementInfeasibleError

from .tpuclient import PodResourcesClient, TpuRuntimeClient


class SliceCreationError(Exception):
    pass


class FakeTpuRuntime(TpuRuntimeClient):
    def __init__(self, generation: Generation = V5E,
                 fail_creates: bool = False) -> None:
        self._gen = generation
        self._lock = threading.RLock()
        self._devices: dict[str, tuple[int, Shape, Placement]] = {}
        self._ids = itertools.count(1)
        self.fail_creates = fail_creates      # fault injection hook
        self.create_calls = 0
        self.delete_calls = 0

    # -- TpuRuntimeClient ---------------------------------------------------
    def topology(self) -> tuple[str, Shape]:
        return self._gen.name, self._gen.host_block

    def list_devices(self) -> DeviceList:
        with self._lock:
            out = DeviceList()
            for did, (unit, shape, _) in sorted(self._devices.items()):
                out.append(Device(slice_resource_name(shape), did, FREE, unit))
            return out

    def placements(self) -> dict[str, Placement]:
        with self._lock:
            return {did: pl for did, (_, _, pl) in self._devices.items()}

    def create_slices(self, unit_index: int, shapes: list[Shape]) -> list[str]:
        with self._lock:
            self.create_calls += 1
            if self.fail_creates:
                raise SliceCreationError("injected create failure")
            fixed = [pl for _, (u, _, pl) in self._devices.items()
                     if u == unit_index]
            multi = [s for s in shapes
                     if s.chips > self._gen.chips_per_host]
            if multi:
                # A multi-host shard consumes this host's ENTIRE block as
                # its per-host share (the real runtime joins the host into
                # the slice via the Cloud TPU multi-host config).
                if len(shapes) != 1 or fixed:
                    raise PlacementInfeasibleError(
                        f"multi-host shard {multi[0].name} needs the whole "
                        f"block of unit {unit_index} "
                        f"({len(fixed)} devices present)"
                    )
                shape = multi[0].canonical()
                pl = Placement(
                    shape=shape,
                    offset=(0,) * len(self._gen.host_block.dims),
                    dims=self._gen.host_block.dims,
                )
                did = f"tpu-{unit_index}-{shape.name}-{next(self._ids)}"
                self._devices[did] = (unit_index, shape, pl)
                return [did]
            counts: dict[Shape, int] = {}
            for s in shapes:
                counts[s.canonical()] = counts.get(s.canonical(), 0) + 1
            placements = extend(self._gen.host_block, fixed, counts)
            if placements is None:
                # all-or-nothing: nothing was created, nothing to clean up
                raise PlacementInfeasibleError(
                    f"cannot place {[s.name for s in shapes]} on unit "
                    f"{unit_index} around {len(fixed)} existing devices"
                )
            created = []
            for pl in placements:
                did = f"tpu-{unit_index}-{pl.shape.name}-{next(self._ids)}"
                self._devices[did] = (unit_index, pl.shape, pl)
                created.append(did)
            return created

    def delete_slice(self, device_id: str) -> None:
        with self._lock:
            self.delete_calls += 1
            if device_id not in self._devices:
                from nos_tpu.topology.errors import DeviceNotFoundError
                raise DeviceNotFoundError(device_id)
            del self._devices[device_id]

    def delete_all_except(self, keep: set[str]) -> list[str]:
        with self._lock:
            doomed = [d for d in self._devices if d not in keep]
            for d in doomed:
                del self._devices[d]
            return doomed


class FakePodResources(PodResourcesClient):
    """Used-device tracking; the simulator marks devices used/free as pods
    bind/terminate (standing in for the kubelet pod-resources socket)."""

    def __init__(self) -> None:
        self._used: dict[str, set[str]] = {}      # pod key -> device ids

    def allocate(self, pod_key: str, device_ids: set[str]) -> None:
        self._used[pod_key] = set(device_ids)

    def release(self, pod_key: str) -> None:
        self._used.pop(pod_key, None)

    def allocated_pod_keys(self) -> list[str]:
        return list(self._used)

    def used_device_ids(self) -> set[str]:
        out: set[str] = set()
        for ids in self._used.values():
            out |= ids
        return out
