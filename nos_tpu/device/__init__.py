"""Device layer: the native boundary and its fakes.

Reference analogs: pkg/gpu/nvml (CGo boundary), pkg/gpu/mig/client.go,
pkg/resource (kubelet pod-resources).  `default_tpu_runtime` applies the
reference's build-tag discipline at runtime: the C++ shim when it can be
built/loaded, the stateful fake otherwise.
"""

from .tpuclient import PodResourcesClient, SliceDeviceClient, TpuRuntimeClient


def default_tpu_runtime(generation=None) -> TpuRuntimeClient:
    from nos_tpu.topology import V5E

    generation = generation or V5E
    from . import native

    if native.available():
        return native.NativeTpuRuntime(generation)
    from .fake import FakeTpuRuntime

    return FakeTpuRuntime(generation)


__all__ = [
    "TpuRuntimeClient", "PodResourcesClient", "SliceDeviceClient",
    "default_tpu_runtime",
]
