"""Device layer: the native boundary and its fakes.

Reference analogs: pkg/gpu/nvml (CGo boundary), pkg/gpu/mig/client.go,
pkg/resource (kubelet pod-resources).  `default_tpu_runtime` applies the
reference's build-tag discipline at runtime: the C++ shim when it can be
built/loaded, the stateful fake otherwise.
"""

from .tpuclient import PodResourcesClient, SliceDeviceClient, TpuRuntimeClient


def default_tpu_runtime(generation=None) -> TpuRuntimeClient:
    """generation=None means *discover* the topology (PJRT device
    attributes / Cloud TPU env metadata, falling back to configured v5e
    off-TPU) — see nos_tpu/device/discovery.py."""
    from . import native

    if native.available():
        return native.NativeTpuRuntime(generation)
    from .fake import FakeTpuRuntime

    if generation is None:
        import dataclasses

        from . import discovery

        disc = discovery.discover()
        # Preserve the *observed* host block, not the generation's default
        # — otherwise a 4-chip VM would advertise the full 8-chip block
        # and the partitioner could carve nonexistent hardware.
        generation = dataclasses.replace(
            disc.generation, host_block=disc.host_block)
    return FakeTpuRuntime(generation)


__all__ = [
    "TpuRuntimeClient", "PodResourcesClient", "SliceDeviceClient",
    "default_tpu_runtime",
]
