"""Kubelet device-plugin server: advertise carved slice devices for real.

The reference rides the out-of-tree NVIDIA device plugin and reloads it
with a pod-delete hammer (pkg/gpu/client.go:51-135) or an MPS ConfigMap;
nos-tpu ships its OWN plugin because the resources it advertises are the
partitioner's carved slice profiles (`nos.tpu/slice-2x4`, ...), which no
stock plugin knows.  One `SliceDevicePlugin` serves a gRPC DevicePlugin
endpoint per advertised resource name:

- register with the kubelet Registration service on kubelet.sock;
- stream the current device inventory on ListAndWatch, re-sending
  whenever the sliceagent's actuation changes the carved geometry (the
  generation-stamped re-advertise that replaces the reference's restart
  hammer — SURVEY.md §2.8 device data plane);
- answer Allocate with the device ids as env (`NOS_TPU_SLICE_IDS`), so
  the workload can pin its jax process to the carved chips.

The proto subset is deviceplugin.proto (generated deviceplugin_pb2.py
committed; regenerate with `protoc --python_out=. deviceplugin.proto`).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Callable

logger = logging.getLogger(__name__)

KUBELET_SOCKET = "/var/lib/kubelet/device-plugins/kubelet.sock"
PLUGINS_DIR = "/var/lib/kubelet/device-plugins"
API_VERSION = "v1beta1"
ENV_DEVICE_IDS = "NOS_TPU_SLICE_IDS"
# Per-resource-suffixed (kubelet merges Allocate envs across plugins —
# same key would clobber): the granted slices' local chip ids and the
# host block they index into.  device/workload_env.py unions them into
# TPU_VISIBLE_CHIPS / TPU_PROCESS_BOUNDS before the first jax import —
# the TPU analog of MIG device visibility (reference
# pkg/gpu/nvml/client.go:286-340 creates *hard* per-partition devices;
# the reachable TPU mechanism is libtpu's chip-visibility env).
ENV_VISIBLE_CHIPS = "NOS_TPU_VISIBLE_CHIPS"
ENV_HOST_BOUNDS = "NOS_TPU_HOST_BOUNDS"


class SliceDevicePlugin:
    """One DevicePlugin gRPC server advertising one resource name.

    `allocate_envs(device_ids) -> {env: value}` customizes the Allocate
    response; the default hands the granted device ids to the workload
    (NOS_TPU_SLICE_IDS, consumed by device/workload_env.py)."""

    def __init__(self, resource_name: str,
                 list_devices: Callable[[], list[str]],
                 plugins_dir: str = PLUGINS_DIR,
                 kubelet_socket: str = KUBELET_SOCKET,
                 allocate_envs: Callable[[list[str]], dict] | None = None,
                 ) -> None:
        import grpc

        from . import deviceplugin_pb2

        self._pb = deviceplugin_pb2
        self._grpc = grpc
        self.resource_name = resource_name
        self._list_devices = list_devices
        self._plugins_dir = plugins_dir
        self._kubelet_socket = kubelet_socket
        self._allocate_envs = allocate_envs or (
            lambda ids: {ENV_DEVICE_IDS: ",".join(ids)})
        self._endpoint = (
            "nos-tpu-" + resource_name.replace("/", "-") + ".sock")
        self._stop = threading.Event()
        self._changed = threading.Condition()
        self._version = 0        # bumped by notify_changed (missed-wakeup proof)
        self._server = None

    @property
    def socket_path(self) -> str:
        return os.path.join(self._plugins_dir, self._endpoint)

    # -- DevicePlugin service ------------------------------------------------
    def _devices(self):
        return self._pb.ListAndWatchResponse(devices=[
            self._pb.Device(ID=did, health="Healthy")
            for did in sorted(self._list_devices())
        ])

    def _list_and_watch(self, request, context):
        """Stream the inventory; re-send on every notify_changed().  The
        change counter makes notifications level-triggered: one fired
        between the snapshot check and the wait cannot be missed."""
        last = None
        seen_version = -1
        while not self._stop.is_set():
            resp = self._devices()
            snapshot = tuple(d.ID for d in resp.devices)
            if snapshot != last:
                last = snapshot
                yield resp
            with self._changed:
                if seen_version == self._version:
                    self._changed.wait(timeout=5.0)
                seen_version = self._version

    def _allocate(self, request, context):
        responses = []
        for creq in request.container_requests:
            ids = list(creq.devices_IDs)
            responses.append(self._pb.ContainerAllocateResponse(
                envs={k: str(v)
                      for k, v in self._allocate_envs(ids).items()}))
        return self._pb.AllocateResponse(container_responses=responses)

    def _options(self, request, context):
        return self._pb.DevicePluginOptions(
            pre_start_required=False,
            get_preferred_allocation_available=False)

    # -- lifecycle -----------------------------------------------------------
    def serve(self) -> None:
        """Bind the plugin socket and start serving."""
        import concurrent.futures

        grpc, pb = self._grpc, self._pb
        handler = grpc.method_handlers_generic_handler(
            "v1beta1.DevicePlugin", {
                "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
                    self._options,
                    request_deserializer=pb.Empty.FromString,
                    response_serializer=pb.DevicePluginOptions
                    .SerializeToString),
                "ListAndWatch": grpc.unary_stream_rpc_method_handler(
                    self._list_and_watch,
                    request_deserializer=pb.Empty.FromString,
                    response_serializer=pb.ListAndWatchResponse
                    .SerializeToString),
                "Allocate": grpc.unary_unary_rpc_method_handler(
                    self._allocate,
                    request_deserializer=pb.AllocateRequest.FromString,
                    response_serializer=pb.AllocateResponse
                    .SerializeToString),
            })
        self._server = grpc.server(
            concurrent.futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers((handler,))
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        self._server.add_insecure_port(f"unix://{self.socket_path}")
        self._server.start()
        logger.info("device plugin %s serving on %s",
                    self.resource_name, self.socket_path)

    def register(self) -> None:
        """Dial the kubelet Registration service and announce this
        plugin's endpoint + resource name."""
        grpc, pb = self._grpc, self._pb
        channel = grpc.insecure_channel(f"unix://{self._kubelet_socket}")
        try:
            register = channel.unary_unary(
                "/v1beta1.Registration/Register",
                request_serializer=pb.RegisterRequest.SerializeToString,
                response_deserializer=pb.Empty.FromString)
            register(pb.RegisterRequest(
                version=API_VERSION,
                endpoint=self._endpoint,
                resource_name=self.resource_name,
                options=pb.DevicePluginOptions()), timeout=5.0)
            logger.info("device plugin %s registered with kubelet",
                        self.resource_name)
        finally:
            channel.close()

    def notify_changed(self) -> None:
        """Re-advertise: the sliceagent calls this after actuating a plan
        (the generation-stamped reload replacing the restart hammer)."""
        with self._changed:
            self._version += 1
            self._changed.notify_all()

    def stop(self) -> None:
        self._stop.set()
        self.notify_changed()
        if self._server is not None:
            self._server.stop(grace=1.0)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass


class DevicePluginManager:
    """One SliceDevicePlugin per carved resource name, kept in sync with
    the runtime's device list.  The sliceagent's DevicePluginClient calls
    sync() after every actuation: new profiles get a served+registered
    plugin, existing ones re-advertise, vanished ones keep serving an
    empty inventory (kubelet wants the resource to drop to 0, not the
    endpoint to disappear)."""

    def __init__(self, runtime, plugins_dir: str = PLUGINS_DIR,
                 kubelet_socket: str = KUBELET_SOCKET) -> None:
        self._runtime = runtime
        self._plugins_dir = plugins_dir
        self._kubelet_socket = kubelet_socket
        self._plugins: dict[str, SliceDevicePlugin] = {}
        self._registered: set[str] = set()
        self._kubelet_id: tuple | None = None   # (st_dev, st_ino)

    def _kubelet_identity(self) -> tuple | None:
        try:
            st = os.stat(self._kubelet_socket)
            return (st.st_dev, st.st_ino)
        except OSError:
            return None

    def _register(self, resource: str, plugin: SliceDevicePlugin) -> None:
        try:
            plugin.register()
            self._registered.add(resource)
        except Exception as e:  # noqa: BLE001 — kubelet may be restarting
            logger.warning("device plugin %s: registration failed (%s); "
                           "will retry next sync", resource, e)

    def _ids_for(self, resource: str) -> list[str]:
        return [d.device_id for d in self._runtime.list_devices()
                if d.resource_name == resource]

    # -- subclass hooks ------------------------------------------------------
    def _current_resources(self) -> set[str]:
        return {d.resource_name for d in self._runtime.list_devices()}

    def _slice_allocate_envs(self, resource: str, ids: list[str]) -> dict:
        """Device ids plus the granted chips' local ids (visibility
        grant).  Falls back to ids-only when a device's placement is
        unknown — never claim visibility we cannot derive."""
        from nos_tpu.topology.packing import placement_cells

        envs = {ENV_DEVICE_IDS: ",".join(ids)}
        try:
            placements = self._runtime.placements()
            _, block = self._runtime.topology()
            units = {d.device_id: d.unit_index
                     for d in self._runtime.list_devices()}
        except Exception as e:  # noqa: BLE001 — runtime may be restarting
            logger.warning("allocate %s: no placement data (%s)", resource, e)
            return envs
        if len({units.get(did) for did in ids}) > 1:
            # local chip ids are per partition root: a grant spanning
            # units cannot be expressed as one visibility set
            logger.warning("allocate %s: grant spans units; ids-only",
                           resource)
            return envs
        cells: set[int] = set()
        for did in ids:
            pl = placements.get(did)
            if pl is None:
                logger.warning("allocate %s: device %s has no placement",
                               resource, did)
                return envs
            cells.update(placement_cells(block, pl))
        suffix = resource.rsplit("/", 1)[-1].replace("-", "_")
        envs[f"{ENV_VISIBLE_CHIPS}_{suffix}"] = \
            ",".join(str(c) for c in sorted(cells))
        envs[ENV_HOST_BOUNDS] = block.name
        return envs

    def _make_plugin(self, resource: str) -> SliceDevicePlugin:
        return SliceDevicePlugin(
            resource,
            lambda r=resource: self._ids_for(r),
            plugins_dir=self._plugins_dir,
            kubelet_socket=self._kubelet_socket,
            allocate_envs=lambda ids, r=resource:
                self._slice_allocate_envs(r, ids))

    def sync(self) -> None:
        # A recreated kubelet.sock means the kubelet restarted and forgot
        # every plugin registration: re-register them all.
        kubelet_id = self._kubelet_identity()
        if kubelet_id != self._kubelet_id:
            if self._kubelet_id is not None:
                logger.info("kubelet socket changed: re-registering "
                            "%d plugin(s)", len(self._plugins))
            self._kubelet_id = kubelet_id
            self._registered.clear()
        for resource in sorted(self._current_resources()
                               - set(self._plugins)):
            plugin = self._make_plugin(resource)
            plugin.serve()
            self._plugins[resource] = plugin
        for resource, plugin in self._plugins.items():
            if resource not in self._registered:
                self._register(resource, plugin)
            plugin.notify_changed()

    def stop(self) -> None:
        for plugin in self._plugins.values():
            plugin.stop()


class TimeshareReplicaPlugin(SliceDevicePlugin):
    """Fractional-HBM profiles (`nos.tpu/tpu-<N>gb`) as device-plugin
    replicas: the advertised count is how many sharers the timeshare
    plan allows, and Allocate hands the workload its HBM grant — gb x
    the number of granted replicas, under a per-profile env key so a
    container holding several profiles sums its grants
    (device/workload_env.granted_timeshare_gb, which turns the total
    into an XLA memory cap before the first jax import).  This replaces
    the reference's out-of-tree MPS device plugin + per-client
    active-thread/memory limits (SURVEY.md §2.8 device data plane).

    NOT nos_tpu.device.timeshare_plugin.TimeshareDevicePlugin — that one
    patches node allocatable in-sim; this one speaks kubelet gRPC."""

    def __init__(self, resource_name: str, gb: int,
                 num_replicas: Callable[[], int],
                 plugins_dir: str = PLUGINS_DIR,
                 kubelet_socket: str = KUBELET_SOCKET) -> None:
        from nos_tpu.device.workload_env import ENV_TIMESHARE_GB

        suffix = resource_name.rsplit("/", 1)[-1].replace("-", "_")

        def list_devices() -> list[str]:
            n = max(0, int(num_replicas()))
            return [f"{resource_name.rsplit('/', 1)[-1]}::{i}"
                    for i in range(n)]

        super().__init__(
            resource_name, list_devices, plugins_dir=plugins_dir,
            kubelet_socket=kubelet_socket,
            allocate_envs=lambda ids: {
                f"{ENV_TIMESHARE_GB}_{suffix}": gb * len(ids),
                ENV_DEVICE_IDS: ",".join(ids),
            })


class TimesharePluginManager(DevicePluginManager):
    """Device plugins for the timeshare profiles a node advertises: the
    chipagent syncs replica counts from the node's allocatable each tick
    (the timeshare plan's generation-stamped re-advertise flows through
    here to the kubelet)."""

    def __init__(self, api, node_name: str,
                 plugins_dir: str = PLUGINS_DIR,
                 kubelet_socket: str = KUBELET_SOCKET) -> None:
        super().__init__(runtime=None, plugins_dir=plugins_dir,
                         kubelet_socket=kubelet_socket)
        self._api = api
        self._node_name = node_name
        self._counts: dict[str, int] = {}

    def _current_resources(self) -> set[str]:
        from nos_tpu.api import constants as C
        from nos_tpu.kube.client import KIND_NODE

        node = self._api.get(KIND_NODE, self._node_name)
        current: dict[str, int] = {}
        for res, qty in node.status.allocatable.items():
            if C.TIMESHARE_RESOURCE_RE.match(res):
                current[res] = int(qty)
        self._counts = current
        return set(current)

    def _make_plugin(self, resource: str) -> SliceDevicePlugin:
        from nos_tpu.api import constants as C

        gb = int(C.TIMESHARE_RESOURCE_RE.match(resource).group("gb"))
        return TimeshareReplicaPlugin(
            resource, gb=gb,
            num_replicas=lambda r=resource: self._counts.get(r, 0),
            plugins_dir=self._plugins_dir,
            kubelet_socket=self._kubelet_socket)


__all__ = ["API_VERSION", "DevicePluginManager", "ENV_DEVICE_IDS",
           "KUBELET_SOCKET", "PLUGINS_DIR", "SliceDevicePlugin",
           "TimeshareReplicaPlugin", "TimesharePluginManager"]
