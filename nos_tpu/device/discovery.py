"""TPU topology discovery: observe the hardware instead of asserting it.

The analog of the reference's NVML device enumeration
(pkg/gpu/nvml/client.go:31-518, go-nvlib visitors over libnvidia-ml): the
one place the control plane learns what accelerators actually exist on this
host.  Sources, in order of authority:

1. **PJRT device attributes** via jax — `device_kind` names the generation
   ("TPU v5 lite"), per-chip `coords` give the local chip block.  This is
   the libtpu-backed path: jax's TPU backend reads the same topology the
   runtime will execute on, so what we report here is what a carved slice
   will actually run on.
2. **Cloud TPU VM environment metadata** — `TPU_ACCELERATOR_TYPE`
   ("v5litepod-4"), `TPU_TOPOLOGY` ("2x4"), `TPU_WORKER_HOSTNAMES`.  Set by
   the Cloud TPU provisioner on every TPU VM; available even before any
   PJRT client initialises.
3. **The configured generation** — off-TPU fallback, the analog of the
   reference's default no-`nvml`-tag build where the device layer is faked.

`DiscoveredTopology.source` records which path won, and flows into the
bench JSON (`topology_source`) so published numbers are attributable to
observed rather than asserted hardware.
"""

from __future__ import annotations

import logging
import os
import re
import sys
from dataclasses import dataclass

from nos_tpu.topology import Generation, Shape, V4, V5E, V5P, V6E

logger = logging.getLogger(__name__)

SOURCE_DEVICE = "device"
SOURCE_ENV = "env"
SOURCE_CONFIGURED = "configured"


@dataclass(frozen=True)
class DiscoveredTopology:
    """What this host observed about its own accelerators."""

    generation: Generation
    host_block: Shape              # observed local chip block (not asserted)
    num_local_chips: int
    num_hosts: int
    source: str                    # SOURCE_DEVICE | SOURCE_ENV | SOURCE_CONFIGURED
    accelerator_type: str | None = None   # raw label (device_kind or env)
    chip_coords: tuple[tuple[int, ...], ...] = ()   # local chips, global coords
    origin: tuple[int, ...] = ()   # min corner of the local block in pod coords

    def jax_device_for(self, offset: tuple[int, ...]):
        """Map a placement offset within the observed host block back to the
        live jax device at that physical position — the proof that carved
        geometry names real chips.  Only meaningful for SOURCE_DEVICE."""
        import jax

        ndims = len(self.origin)
        want = tuple(self.origin[i] + (offset[i] if i < len(offset) else 0)
                     for i in range(ndims))
        for d in jax.local_devices():
            coords = tuple(getattr(d, "coords", ()))[:ndims]
            if coords == want:
                return d
        raise LookupError(f"no local jax device at coords {want}")


# device_kind (PJRT) -> generation.  Public Cloud TPU device-kind strings.
_KIND_PATTERNS: tuple[tuple[str, Generation], ...] = (
    (r"v6e|v6\s*lite|trillium", V6E),
    (r"v5\s*lite|v5e", V5E),
    (r"v5p|v5$", V5P),      # v5p clients report "TPU v5p" or plain "TPU v5"
    (r"v4", V4),
)

# TPU_ACCELERATOR_TYPE prefixes ("v5litepod-4", "v4-8", "v5p-16", "v6e-8").
_ACCEL_PATTERNS: tuple[tuple[str, Generation], ...] = (
    (r"^v6e", V6E),
    (r"^v5lite", V5E),
    (r"^v5e", V5E),
    (r"^v5p", V5P),
    (r"^v4", V4),
)


def _match(label: str, patterns) -> Generation | None:
    for pat, gen in patterns:
        if re.search(pat, label, re.IGNORECASE):
            return gen
    return None


def _bounding_block(coords: list[tuple[int, ...]], ndims: int
                    ) -> tuple[Shape, tuple[int, ...]]:
    """Smallest axis-aligned block covering the observed chips, clipped to
    the generation's mesh rank (v5e PJRT coords are 3-D with z always 0)."""
    clipped = [c[:ndims] + (0,) * (ndims - len(c)) for c in coords]
    lo = tuple(min(c[i] for c in clipped) for i in range(ndims))
    hi = tuple(max(c[i] for c in clipped) for i in range(ndims))
    return Shape(tuple(h - l + 1 for l, h in zip(lo, hi))), lo


def _discover_from_device() -> DiscoveredTopology | None:
    """PJRT path.  Initialises the jax backend, so only attempted when jax
    is importable; returns None off-TPU (cpu/gpu platforms)."""
    try:
        import jax

        local = jax.local_devices()
    except Exception as e:  # no backend at all, plugin init failure, ...
        logger.debug("jax device discovery unavailable: %s", e)
        return None
    tpus = [d for d in local if d.platform == "tpu"]
    if not tpus:
        return None
    kind = getattr(tpus[0], "device_kind", "") or ""
    gen = _match(kind, _KIND_PATTERNS)
    if gen is None:
        logger.warning("unrecognised TPU device_kind %r; "
                       "topology discovery falling back", kind)
        return None
    coords = [tuple(getattr(d, "coords", ()) or ()) for d in tpus]
    if any(not c for c in coords):
        # pathological PJRT client without coords: still attribute the
        # generation, with a linear block of the right chip count
        block, origin = Shape((len(tpus),) + (1,) * (gen.ndims - 1)), \
            (0,) * gen.ndims
        coords = []
    else:
        block, origin = _bounding_block(coords, gen.ndims)
    n_hosts = max(1, getattr(jax, "process_count", lambda: 1)())
    return DiscoveredTopology(
        generation=gen, host_block=block, num_local_chips=len(tpus),
        num_hosts=n_hosts, source=SOURCE_DEVICE, accelerator_type=kind,
        chip_coords=tuple(c[:gen.ndims] for c in coords), origin=origin)


def _discover_from_env(environ=os.environ) -> DiscoveredTopology | None:
    """Cloud TPU VM metadata path (no PJRT init)."""
    accel = environ.get("TPU_ACCELERATOR_TYPE")
    if not accel:
        return None
    gen = _match(accel, _ACCEL_PATTERNS)
    if gen is None:
        logger.warning("unrecognised TPU_ACCELERATOR_TYPE %r", accel)
        return None
    hosts = [h for h in
             environ.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
    n_hosts = max(1, len(hosts))
    topo_s = environ.get("TPU_TOPOLOGY", "")
    host_block = gen.host_block
    try:
        topo = Shape.parse(topo_s) if topo_s else None
    except ValueError:
        topo = None
    if topo is not None and n_hosts == 1:
        # single-worker slice: the whole advertised topology lives here
        host_block = topo
    return DiscoveredTopology(
        generation=gen, host_block=host_block,
        num_local_chips=host_block.chips, num_hosts=n_hosts,
        source=SOURCE_ENV, accelerator_type=accel,
        origin=(0,) * len(host_block.dims))


def discover(configured: Generation | None = None,
             allow_jax: bool = True,
             environ=os.environ) -> DiscoveredTopology:
    """Observe this host's TPU topology; never raises.

    allow_jax=False skips the PJRT path even when jax is importable —
    control-plane processes that must not initialise an accelerator backend
    (e.g. the cluster-scope partitioner) use the env/configured paths only.
    """
    if allow_jax:
        # Avoid triggering a slow cold jax import for pure control-plane
        # callers that never touched jax; if it's already loaded, the
        # backend query is cheap.
        if "jax" in sys.modules or environ.get("TPU_ACCELERATOR_TYPE"):
            found = _discover_from_device()
            if found is not None:
                return found
    found = _discover_from_env(environ)
    if found is not None:
        return found
    gen = configured or V5E
    return DiscoveredTopology(
        generation=gen, host_block=gen.host_block,
        num_local_chips=gen.host_block.chips, num_hosts=1,
        source=SOURCE_CONFIGURED, accelerator_type=None,
        origin=(0,) * len(gen.host_block.dims))
