"""Device-layer interfaces: the native boundary seam.

Analogs of reference pkg/gpu/nvml/interface.go:23-35 (`nvml.Client` — the CGo
boundary), pkg/gpu/mig/client.go:28-35 (`mig.Client` — node-local
orchestration of NVML ∩ kubelet pod-resources), and pkg/resource/client.go:26-29
(`resource.Client` — kubelet pod-resources gRPC).

Everything above this seam is testable with fakes (nos_tpu/device/fake.py),
exactly as the reference hides NVML behind `nvml.Client` so the whole control
plane runs hardware-free (SURVEY.md §2, §4).  The production implementation
is the C++ shim in nos_tpu/native loaded via ctypes (nos_tpu/device/native.py),
standing in for the Cloud TPU API + libtpu topology introspection.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from nos_tpu.topology import DeviceList, Placement, Shape


class TpuRuntimeClient(ABC):
    """The native boundary: slice device lifecycle on one host."""

    @abstractmethod
    def topology(self) -> tuple[str, Shape]:
        """(accelerator name, host chip block) from libtpu metadata."""

    @abstractmethod
    def list_devices(self) -> DeviceList:
        """All carved slice devices on this host (no used/free knowledge)."""

    @abstractmethod
    def placements(self) -> dict[str, Placement]:
        """device id -> placement within the host block."""

    @abstractmethod
    def create_slices(self, unit_index: int, shapes: list[Shape]) -> list[str]:
        """Carve new slice devices, searching placements around existing
        ones; all-or-nothing with cleanup on failure (the analog of the NVML
        permutation search, reference pkg/gpu/nvml/client.go:286-340)."""

    @abstractmethod
    def delete_slice(self, device_id: str) -> None: ...

    @abstractmethod
    def delete_all_except(self, device_ids: set[str]) -> list[str]:
        """Startup cleanup (reference cmd/migagent/migagent.go:190-199)."""


class PodResourcesClient(ABC):
    """Which device ids are allocated to running pods (kubelet
    pod-resources socket analog, reference pkg/resource/lister.go:28)."""

    @abstractmethod
    def used_device_ids(self) -> set[str]: ...


class SliceDeviceClient:
    """mig.Client analog: runtime devices ∩ pod-resources usage ->
    used/free DeviceList (reference pkg/gpu/mig/client.go:28-174)."""

    def __init__(self, runtime: TpuRuntimeClient,
                 pod_resources: PodResourcesClient) -> None:
        self.runtime = runtime
        self.pod_resources = pod_resources

    def get_devices(self) -> DeviceList:
        from nos_tpu.topology import Device, FREE, USED

        used_ids = self.pod_resources.used_device_ids()
        out = DeviceList()
        for d in self.runtime.list_devices():
            status = USED if d.device_id in used_ids else FREE
            out.append(Device(d.resource_name, d.device_id, status, d.unit_index))
        return out

    def create_slices(self, unit_index: int, shapes: list[Shape]) -> list[str]:
        return self.runtime.create_slices(unit_index, shapes)

    def delete_slice(self, device_id: str) -> None:
        self.runtime.delete_slice(device_id)

    def delete_all_except(self, keep: set[str]) -> list[str]:
        return self.runtime.delete_all_except(keep)
