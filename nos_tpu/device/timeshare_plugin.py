"""Timeshare device plugin: config-driven resource re-advertisement.

Plays the role of the reference's forked NVIDIA device plugin consuming the
MPS sharing ConfigMap (internal/partitioning/mps/partitioner.go:61-114): it
watches the node's `nos.tpu/device-plugin.config` label, loads that key from
the shared ConfigMap, advertises the configured `nos.tpu/tpu-<N>gb`
resources on the node, and stamps the applied key + a generation counter —
the readiness signal that replaces the reference's blind propagation sleep.
"""

from __future__ import annotations

import json
import logging

from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_CONFIGMAP, KIND_NODE
from nos_tpu.kube.objects import Node
from nos_tpu.topology.profile import is_timeshare_resource, timeshare_resource_name
from nos_tpu.utils.retry import retry_on_conflict

logger = logging.getLogger(__name__)


class TimeshareDevicePlugin:
    def __init__(self, api: APIServer, node_name: str,
                 cm_name: str, cm_namespace: str) -> None:
        self._api = api
        self._node_name = node_name
        self._cm_name = cm_name
        self._cm_namespace = cm_namespace

    def chip_config(self, key: str) -> dict[int, dict[str, int]] | None:
        """chip index -> profile -> replicas for a ConfigMap key."""
        cm = self._api.try_get(KIND_CONFIGMAP, self._cm_name, self._cm_namespace)
        if cm is None or key not in cm.data:
            return None
        cfg = json.loads(cm.data[key])
        chips = cfg.get("sharing", {}).get("timeshare", {}).get("chips", {})
        return {int(i): dict(profiles) for i, profiles in chips.items()}

    def tick(self) -> bool:
        """Apply the labeled config if it isn't applied yet; returns True
        if the node was updated."""
        from nos_tpu.partitioning.timeshare.partitioner import config_key

        node = self._api.get(KIND_NODE, self._node_name)
        plan_id = node.metadata.labels.get(C.LABEL_DEVICE_PLUGIN_CONFIG, "")
        if not plan_id:
            return False
        # The label carries the plan id only (63-char label-value limit);
        # the full ConfigMap key is node-local knowledge.
        key = config_key(self._node_name, plan_id)
        if node.metadata.annotations.get(C.ANNOT_PLUGIN_APPLIED_CONFIG) == key:
            return False
        chips = self.chip_config(key)
        if chips is None:
            logger.warning("timeshare plugin: config key %s not found", key)
            return False

        totals: dict[str, float] = {}
        for profiles in chips.values():
            for profile, qty in profiles.items():
                res = timeshare_resource_name(int(profile[:-2]))
                totals[res] = totals.get(res, 0.0) + qty

        def mutate(n: Node) -> None:
            for table in (n.status.allocatable, n.status.capacity):
                for res in [r for r in table if is_timeshare_resource(r)]:
                    del table[res]
            n.status.allocatable.update(totals)
            n.status.capacity.update(totals)
            gen = int(n.metadata.annotations.get(C.ANNOT_PLUGIN_GENERATION, "0"))
            n.metadata.annotations[C.ANNOT_PLUGIN_GENERATION] = str(gen + 1)
            n.metadata.annotations[C.ANNOT_PLUGIN_APPLIED_CONFIG] = key

        retry_on_conflict(self._api, KIND_NODE, self._node_name, mutate,
                          component="timeshare-plugin")
        logger.info("timeshare plugin: node %s applied %s", self._node_name, key)
        return True
