"""Validating admission webhook server (AdmissionReview v1 over HTTPS).

On the in-memory substrate admission runs in-process (APIServer's
register_admission hook); on a real cluster the API server must be told
to consult US — this module is the HTTPS endpoint the chart's
ValidatingWebhookConfiguration points at.  Analog of the reference's
controller-runtime webhook server wiring
(pkg/api/nos.nebuly.com/v1alpha1/elasticquota_webhook.go:43-97 +
config/operator/webhook/manifests.yaml): the operator main serves it
with the same validators `install_quota_webhooks` registers, so the two
substrates enforce identical rules.

Request flow: kube-apiserver POSTs an AdmissionReview whose
`request.object` is the raw kind JSON; we decode it with the same codec
the REST client uses (kube/k8s_codec.from_k8s), run every validator
registered for the kind, and answer allowed=true/false with the
validator's message.  Fail-closed on anything malformed: a review we
cannot parse is denied, not dropped (matching `failurePolicy: Fail` in
the chart).
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from .k8s_codec import from_k8s

logger = logging.getLogger(__name__)


def review_response(uid: str, allowed: bool, message: str = "",
                    patch_ops: list | None = None) -> dict:
    resp: dict = {"uid": uid, "allowed": allowed}
    if message:
        resp["status"] = {"message": message, "code": 403}
    if patch_ops:
        import base64

        resp["patchType"] = "JSONPatch"
        resp["patch"] = base64.b64encode(
            json.dumps(patch_ops).encode()).decode()
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "response": resp}


class AdmissionHandler:
    """Pure request->response admission logic (transport-free, so tests
    can exercise it without TLS plumbing)."""

    def __init__(self, api) -> None:
        self._api = api
        self._validators: dict[str, list[Callable]] = {}
        self._mutators: dict[str, list[Callable]] = {}

    def register(self, kind: str, fn: Callable) -> None:
        self._validators.setdefault(kind, []).append(fn)

    def register_mutating(self, kind: str, fn: Callable) -> None:
        """fn(raw_object_dict) -> RFC 6902 op list | None.  Mutators work
        on the RAW k8s JSON so unmodeled fields are never touched; their
        ops are returned as the AdmissionReview JSONPatch."""
        self._mutators.setdefault(kind, []).append(fn)

    @property
    def kinds(self) -> list[str]:
        return sorted(set(self._validators) | set(self._mutators))

    def handle(self, body: bytes, path: str = "") -> dict:
        """`path` routes the review like the chart wires it: a
        /validate-* URL runs only validators, /mutate-* only mutators;
        "" (in-process use, tests) runs both.  Kind alone must not pick
        the behavior — the apiserver POSTs the SAME kind to both
        endpoints and expects a patch only from the mutating one."""
        run_validators = not path or path.startswith("/validate")
        run_mutators = not path or path.startswith("/mutate")
        uid = ""
        try:
            review = json.loads(body)
            request = review["request"]
            uid = request.get("uid", "")
            kind = request["kind"]["kind"]
            operation = request.get("operation", "CREATE")
            if operation == "DELETE":
                return review_response(uid, True)
            raw = request["object"]
        except Exception as e:  # noqa: BLE001 — malformed review: deny
            logger.warning("admission: malformed review rejected (%s)", e)
            return review_response(uid, False, f"malformed AdmissionReview: {e}")
        validators = (self._validators.get(kind, [])
                      if run_validators else [])
        if validators:
            # Validated kinds are fail-closed: an object the codec cannot
            # decode cannot be validated, so it is denied.  Mutate-only
            # kinds (cluster-wide pod normalization) never decode — the
            # mutators consume the raw JSON, and a decode quirk must not
            # block pod creation.
            try:
                obj = from_k8s(kind, raw)
            except Exception as e:  # noqa: BLE001
                logger.warning("admission: undecodable %s rejected (%s)",
                               kind, e)
                return review_response(uid, False,
                                       f"undecodable {kind}: {e}")
            for fn in validators:
                try:
                    fn(self._api, obj)
                except Exception as e:  # noqa: BLE001 — verdicts + bugs both deny
                    return review_response(uid, False, str(e))
        ops: list = []
        for fn in (self._mutators.get(kind, []) if run_mutators else []):
            try:
                ops.extend(fn(raw) or [])
            except Exception as e:  # noqa: BLE001 — a broken mutator must
                # not block the write (mutating webhooks ship with
                # failurePolicy Ignore; same spirit in-process)
                logger.warning("admission: mutator for %s failed (%s); "
                               "object passed through unchanged", kind, e)
        return review_response(uid, True, patch_ops=ops or None)


class WebhookServer:
    """HTTPS AdmissionReview endpoint wrapping an AdmissionHandler.

    `cert_file`/`key_file` hold the serving cert the chart provisions
    (self-signed generator job; the ValidatingWebhookConfiguration's
    caBundle carries the matching CA).  Serving WITHOUT a cert requires
    an explicit `allow_insecure=True` (tests only): the kube-apiserver
    requires TLS, so a production misconfig with an empty cert dir must
    fail fast instead of silently serving admission over cleartext."""

    def __init__(self, handler: AdmissionHandler, host: str = "0.0.0.0",
                 port: int = 9443, cert_file: str | None = None,
                 key_file: str | None = None,
                 allow_insecure: bool = False) -> None:
        self._handler = handler
        self._host, self._port = host, port
        self._cert, self._key = cert_file, key_file
        self._allow_insecure = allow_insecure
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int:
        """Bound port (resolves 0 to the kernel's pick after start())."""
        return self._httpd.server_address[1] if self._httpd else self._port

    def start(self) -> None:
        if not self._cert and not self._allow_insecure:
            raise ValueError(
                "WebhookServer without a serving cert: the kube-apiserver "
                "requires TLS — set webhook_cert_dir (the chart mounts "
                "tls.crt/tls.key) or pass allow_insecure=True in tests")
        handler = self._handler

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self) -> None:  # noqa: N802 — stdlib naming
                if not (self.path.startswith("/validate")
                        or self.path.startswith("/mutate")):
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                resp = json.dumps(handler.handle(body, self.path)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(resp)))
                self.end_headers()
                self.wfile.write(resp)

            def do_GET(self) -> None:  # noqa: N802
                if self.path in ("/healthz", "/readyz"):
                    self.send_response(200)
                    self.send_header("Content-Length", "2")
                    self.end_headers()
                    self.wfile.write(b"ok")
                else:
                    self.send_error(404)

            def log_message(self, fmt, *args):  # quiet the stdlib logger
                logger.debug("webhook: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        if self._cert:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self._cert, self._key)
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="admission-webhook",
            daemon=True)
        self._thread.start()
        logger.info("admission webhook serving on %s:%d (%s) for %s",
                    self._host, self.port,
                    "https" if self._cert else "http", self._handler.kinds)

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
