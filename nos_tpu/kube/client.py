"""In-memory Kubernetes API substrate.

The reference runs against a real API server (controller-runtime client) and
tests against envtest (SURVEY.md §4).  Here the same role — the durable store
and watch bus through which the decision plane and actuation plane exchange
annotations — is played by an in-memory, thread-safe object store with
watch callbacks and field indexes (analog of the field indexers registered in
reference cmd/gpupartitioner/gpupartitioner.go:270-292).

All durable state lives here (annotations, labels, ConfigMaps, CRD status);
every controller is stateless-restartable, mirroring the reference's
checkpoint/resume story (SURVEY.md §5).
"""

from __future__ import annotations

import copy
import threading
from collections import defaultdict
from typing import Any, Callable

from nos_tpu.utils.guards import guarded_by

from .objects import ConfigMap, Node, Pod

WatchFn = Callable[[str, Any], None]  # (event_type, object) — "ADDED"/"MODIFIED"/"DELETED"


class NotFound(Exception):
    pass


class Conflict(Exception):
    pass


class TransientAPIError(RuntimeError):
    """A server-side failure worth retrying: 5xx / 429 from a real
    apiserver (kube/rest.py), or an injected transient from the chaos
    substrate.  Distinct from plain RuntimeError so permanent request
    errors (400s, validation) are NOT blindly retried."""


class APIServer:
    """Typed object store: kind -> key -> object.

    Keys are "namespace/name" for namespaced kinds, "name" for cluster kinds.
    Reads return deep copies (as a real API server serialises); writes bump
    resource_version and fan out to watchers synchronously.
    """

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._stores: dict[str, dict[str, Any]] = defaultdict(dict)
        # (callback, selector): selector None = deliver everything
        self._watchers: dict[str, list[
            tuple[WatchFn, Callable[[Any], bool] | None]]] = defaultdict(list)
        self._admission: dict[str, list[Callable[["APIServer", Any], None]]] = \
            defaultdict(list)
        self._rv = 0
        self._event_queue: list[tuple[str, str, Any]] = []
        self._delivering = False

    # -- admission (validating webhooks) -----------------------------------
    def register_admission(self, kind: str,
                           fn: Callable[["APIServer", Any], None]) -> None:
        """Register a validating webhook for a kind; `fn(api, obj)` raises
        to deny the write (create/update/patch).  The analog of the
        reference's controller-runtime webhooks
        (pkg/api/nos.nebuly.com/v1alpha1/elasticquota_webhook.go:48-97)."""
        self._admission[kind].append(fn)

    def _admit(self, kind: str, obj: Any) -> None:
        for fn in self._admission.get(kind, []):
            fn(self, obj)

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def _key(obj: Any) -> str:
        ns = getattr(obj.metadata, "namespace", "")
        return f"{ns}/{obj.metadata.name}" if ns else obj.metadata.name

    def _notify(self, kind: str, event: str, obj: Any) -> None:
        """FIFO event delivery.  A watch callback that writes back to the
        store (e.g. KubeletSim's phase patch) re-enters _notify; delivering
        the nested event immediately would hand later-registered watchers
        the *newer* state before the event that caused it, letting a
        cache-maintaining watcher overwrite new state with the stale outer
        payload.  Queue instead: the outermost call drains in order, so
        every watcher sees events in the same store-commit order.  All
        under self._lock (RLock), so ordering is globally consistent."""
        self._event_queue.append((kind, event, copy.deepcopy(obj)))
        if self._delivering:
            return
        self._delivering = True
        # The queue must ALWAYS fully drain before this call returns: a
        # raising watcher must not strand queued events for delivery during
        # some unrelated future write.  Keep delivering, remember the first
        # error, re-raise once the bus is empty.
        first_exc: BaseException | None = None
        try:
            while self._event_queue:
                k, ev, o = self._event_queue.pop(0)
                for fn, selector in list(self._watchers.get(k, [])):
                    # Field-selector analog: the per-watcher deep copy is
                    # the write-path hot spot at fleet scale (every
                    # kubelet sim watches pods), so selector-rejected
                    # events skip it.  Selectors read the queued copy and
                    # MUST NOT mutate it.
                    if selector is not None and not selector(o):
                        continue
                    try:
                        fn(ev, copy.deepcopy(o))
                    except BaseException as e:
                        if first_exc is None:
                            first_exc = e
        finally:
            self._delivering = False
        if first_exc is not None:
            raise first_exc

    def kinds(self) -> list[str]:
        """Kinds with at least one stored object (snapshot enumeration)."""
        with self._lock:
            return [k for k, s in self._stores.items() if s]

    def locked(self):
        """The store's reentrant lock, for callers that must order their
        own lock AFTER it.  Watch callbacks fire with this lock held, so
        a component locking (own -> APIServer) from another thread would
        deadlock against (APIServer -> own) in a callback; taking this
        first (reentrancy keeps nested CRUD calls working) gives both
        paths the same order.  Used by controllers/kubelet.py."""
        return self._lock

    # -- CRUD -------------------------------------------------------------
    def create(self, kind: str, obj: Any) -> Any:
        with self._lock:
            key = self._key(obj)
            store = self._stores[kind]
            if key in store:
                raise Conflict(f"{kind} {key} already exists")
            self._admit(kind, obj)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            store[key] = copy.deepcopy(obj)
            self._notify(kind, "ADDED", store[key])
            return copy.deepcopy(store[key])

    def get(self, kind: str, name: str, namespace: str = "") -> Any:
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            store = self._stores[kind]
            if key not in store:
                raise NotFound(f"{kind} {key}")
            return copy.deepcopy(store[key])

    def try_get(self, kind: str, name: str, namespace: str = "") -> Any | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, kind: str, obj: Any) -> Any:
        with self._lock:
            key = self._key(obj)
            store = self._stores[kind]
            if key not in store:
                raise NotFound(f"{kind} {key}")
            # PUT optimistic concurrency (k8s semantics): an object read
            # at rv N cannot overwrite rv M != N.  rv 0 = unconditional.
            sent_rv = getattr(obj.metadata, "resource_version", 0)
            current_rv = store[key].metadata.resource_version
            if sent_rv and sent_rv != current_rv:
                raise Conflict(
                    f"{kind} {key}: resourceVersion {sent_rv} != "
                    f"{current_rv}")
            self._admit(kind, obj)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            store[key] = copy.deepcopy(obj)
            self._notify(kind, "MODIFIED", store[key])
            return copy.deepcopy(store[key])

    def patch(self, kind: str, name: str, namespace: str = "",
              *, mutate: Callable[[Any], None]) -> Any:
        """Read-modify-write under the store lock (strategic-merge-patch
        analog; the reference patches node annotations this way,
        e.g. internal/partitioning/slicepart partitioner)."""
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            store = self._stores[kind]
            if key not in store:
                raise NotFound(f"{kind} {key}")
            obj = copy.deepcopy(store[key])
            mutate(obj)
            self._admit(kind, obj)
            self._rv += 1
            obj.metadata.resource_version = self._rv
            store[key] = obj
            self._notify(kind, "MODIFIED", copy.deepcopy(obj))
            return copy.deepcopy(obj)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._lock:
            key = f"{namespace}/{name}" if namespace else name
            store = self._stores[kind]
            if key not in store:
                raise NotFound(f"{kind} {key}")
            obj = store.pop(key)
            # deletions are mutations too: rv-memoized views (nominated
            # pods, cycle snapshots) must invalidate on them
            self._rv += 1
            self._notify(kind, "DELETED", obj)

    @property
    def resource_version(self) -> int:
        """Global mutation counter (bumped on every create/put/patch/
        delete): lets read-mostly consumers memoize derived views and
        invalidate EXACTLY when anything changed (the scheduler's cycle
        snapshot, the capacity plugin's nominated-pods list)."""
        with self._lock:
            return self._rv

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None,
             filter_fn: Callable[[Any], bool] | None = None) -> list[Any]:
        with self._lock:
            out = []
            for key, obj in self._stores[kind].items():
                if namespace is not None and getattr(obj.metadata, "namespace", "") != namespace:
                    continue
                if label_selector is not None and any(
                    obj.metadata.labels.get(k) != v for k, v in label_selector.items()
                ):
                    continue
                if filter_fn is not None and not filter_fn(obj):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def watch(self, kind: str, fn: WatchFn,
              selector: Callable[[Any], bool] | None = None
              ) -> Callable[[], None]:
        """Register a watcher; returns an unsubscribe function.  New watchers
        receive synthetic ADDED events for existing objects (informer sync).

        `selector` is the field-selector analog (a real kubelet watches
        pods with spec.nodeName=<self>): evaluated BEFORE the per-watcher
        deep copy, against an object the selector must not mutate.  At
        fleet scale this is the difference between every pod write
        fanning out N-nodes deep copies and fanning out a handful.

        Unlike an apiserver fieldSelector, an object that STOPS
        matching is simply not delivered — no synthetic DELETED is
        synthesized for leaving the selection.  Select only on fields
        that are stable for the object's relevant lifetime (a pod's
        spec.nodeName is set once at bind and immutable until
        deletion); a selector over a mutable field would leave the
        watcher holding the last matching state forever."""
        entry = (fn, selector)
        with self._lock:
            self._watchers[kind].append(entry)
            for obj in list(self._stores[kind].values()):
                if selector is not None and not selector(obj):
                    continue
                fn("ADDED", copy.deepcopy(obj))

        def unsubscribe() -> None:
            with self._lock:
                if entry in self._watchers[kind]:
                    self._watchers[kind].remove(entry)

        return unsubscribe

    # -- field-index style helpers (reference gpupartitioner.go:270-292) ---
    def pods_by_phase(self, phase: str) -> list[Pod]:
        return self.list("Pod", filter_fn=lambda p: p.status.phase == phase)

    def pods_on_node(self, node_name: str) -> list[Pod]:
        return self.list("Pod", filter_fn=lambda p: p.spec.node_name == node_name)


@guarded_by("_lock", "_store")
class Informer:
    """Watch-maintained local store of one kind — the client-go shared
    informer analog over the watch bus.

    Keeps the latest object per key ("namespace/name" or "name"), synced
    by the synthetic-ADDED replay on subscribe and updated on every
    event; read-mostly consumers (the scheduler's cluster-view cache)
    get current objects WITHOUT a full `list()` re-copy per read.  The
    optional `on_event` hook runs synchronously after the store update,
    in store-commit order, with the event's own deep-copied object —
    the place to maintain derived indexes and generation counters.

    Works against any watch-capable substrate (APIServer, ChaosAPIServer,
    the REST client's informer-style watch).  `store=False` skips the
    local store entirely — for consumers that maintain their own indexes
    in the hook (the scheduler cache), a duplicate store would just be a
    second lock acquisition and a second copy of every object."""

    def __init__(self, api, kind: str, on_event: WatchFn | None = None,
                 store: bool = True) -> None:
        self._lock = threading.Lock()
        self._store: dict[str, Any] | None = {} if store else None
        self._on_event = on_event
        self._unsubscribe = api.watch(kind, self._handle)

    def _handle(self, event: str, obj: Any) -> None:
        if self._store is not None:
            ns = getattr(obj.metadata, "namespace", "")
            key = f"{ns}/{obj.metadata.name}" if ns else obj.metadata.name
            with self._lock:
                if event == "DELETED":
                    self._store.pop(key, None)
                else:
                    self._store[key] = obj
        if self._on_event is not None:
            self._on_event(event, obj)

    def items(self) -> dict[str, Any]:
        """Point-in-time view: the dict is a copy, the objects are the
        store's own (callers must not mutate them)."""
        with self._lock:
            return dict(self._store or {})

    def get(self, key: str) -> Any | None:
        with self._lock:
            return (self._store or {}).get(key)

    def __len__(self) -> int:
        with self._lock:
            return len(self._store or {})

    def close(self) -> None:
        self._unsubscribe()


# Canonical kind names used across the framework.
KIND_POD = "Pod"
KIND_NODE = "Node"
KIND_CONFIGMAP = "ConfigMap"
KIND_ELASTIC_QUOTA = "ElasticQuota"
KIND_COMPOSITE_ELASTIC_QUOTA = "CompositeElasticQuota"
KIND_POD_GROUP = "PodGroup"

__all__ = [
    "APIServer", "Informer", "NotFound", "Conflict", "TransientAPIError",
    "KIND_POD", "KIND_NODE", "KIND_CONFIGMAP",
    "KIND_ELASTIC_QUOTA", "KIND_COMPOSITE_ELASTIC_QUOTA", "KIND_POD_GROUP",
    "Node", "Pod", "ConfigMap",
]
