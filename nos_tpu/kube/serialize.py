"""JSON (de)serialization of the cluster object model.

Two consumers:

- the `/snapshot` endpoint every cmd/ main serves (`cmd/_runtime.py`),
  which lets the one-shot metricsexporter observe a *live* process's
  cluster instead of an empty one (the reference metricsexporter reads
  the actual cluster, cmd/metricsexporter/metricsexporter.go:33-91);
- state dump/restore: all durable control-plane state lives in the API
  server (SURVEY.md §5 checkpoint/resume), so `dump_state`/`load_state`
  of its stores IS the control plane's checkpoint format.

Objects are plain nested dataclasses (kube/objects.py, api/*), so the
codec is generic: `dataclasses.asdict` out, recursive field-typed
construction back in.  Unknown keys in input are ignored and unknown
kinds are skipped with a warning (forward compatibility: a snapshot from
a newer build must not prevent loading the kinds this build knows).
"""

from __future__ import annotations

import dataclasses
import logging
import typing
from typing import Any

from nos_tpu.api.elasticquota import CompositeElasticQuota, ElasticQuota
from nos_tpu.api.pdb import KIND_POD_DISRUPTION_BUDGET, PodDisruptionBudget
from nos_tpu.api.podgroup import PodGroup
from nos_tpu.kube.client import (
    APIServer, KIND_COMPOSITE_ELASTIC_QUOTA, KIND_CONFIGMAP,
    KIND_ELASTIC_QUOTA, KIND_NODE, KIND_POD, KIND_POD_GROUP,
)
from nos_tpu.kube.objects import ConfigMap, Node, Pod

logger = logging.getLogger(__name__)

KIND_TYPES: dict[str, type] = {
    KIND_POD: Pod,
    KIND_NODE: Node,
    KIND_CONFIGMAP: ConfigMap,
    KIND_ELASTIC_QUOTA: ElasticQuota,
    KIND_COMPOSITE_ELASTIC_QUOTA: CompositeElasticQuota,
    KIND_POD_GROUP: PodGroup,
    KIND_POD_DISRUPTION_BUDGET: PodDisruptionBudget,
}


def _build(cls: type, data: Any) -> Any:
    """Recursively construct `cls` from plain JSON data using dataclass
    field types; tolerates missing (defaulted) and unknown keys."""
    if data is None:
        return None
    origin = typing.get_origin(cls)
    if origin in (list, tuple):
        (item_t,) = typing.get_args(cls)[:1] or (Any,)
        seq = [_build(item_t, v) for v in data]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        return dict(data)
    if origin is typing.Union:  # Optional[...]
        args = [a for a in typing.get_args(cls) if a is not type(None)]
        return _build(args[0], data) if args else data
    if dataclasses.is_dataclass(cls):
        if not isinstance(data, dict):
            # a str here would "work" (substring `in`) and silently yield
            # a default object — corrupt input must fail loudly instead
            raise ValueError(
                f"expected object for {cls.__name__}, got {type(data).__name__}")
        hints = typing.get_type_hints(cls)
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name in data:
                kwargs[f.name] = _build(hints.get(f.name, Any), data[f.name])
        return cls(**kwargs)
    return data


def to_dict(obj: Any) -> Any:
    return dataclasses.asdict(obj) if dataclasses.is_dataclass(obj) else obj


def from_dict(kind: str, data: dict) -> Any:
    cls = KIND_TYPES.get(kind)
    return _build(cls, data) if cls is not None else data


def dump_state(api: APIServer) -> dict:
    """{kind: [object dicts]} for every populated store.

    Enumerates the serializable kinds directly (one list() per kind)
    rather than asking `api.kinds()` first — against the REST substrate
    kinds() itself lists everything, which would double the apiserver
    round trips per snapshot."""
    out: dict[str, list] = {}
    kinds = set(KIND_TYPES)
    if isinstance(api, APIServer):  # in-memory enumeration is free
        kinds |= set(api.kinds())
    for kind in sorted(kinds):
        objs = api.list(kind)
        if objs:
            out[kind] = [to_dict(o) for o in objs]
    return out


def load_state(data: dict, api: APIServer | None = None) -> APIServer:
    """Rebuild an APIServer from dump_state output (admission/webhooks are
    not re-run: the snapshot is already-admitted state)."""
    api = api or APIServer()
    for kind, objs in data.items():
        if kind not in KIND_TYPES:
            logger.warning("load_state: skipping unknown kind %r "
                           "(%d object(s))", kind, len(objs))
            continue
        for obj in objs:
            api.create(kind, from_dict(kind, obj))
    return api
