"""Resource-list arithmetic.

Analog of reference pkg/resource/resource.go:57-146 (framework.Resource
Sum/Subtract/SubtractNonNegative/Abs and pod request math).  A ResourceList is
a plain ``dict[str, float]``; helpers are pure functions returning new dicts.
"""

from __future__ import annotations

from typing import Mapping

ResourceList = dict[str, float]


def parse_quantity(q: str | int | float) -> float:
    """Parse a Kubernetes quantity ("500m", "2", "16Gi") into a float.

    Memory suffixes normalise to bytes; "m" is milli (cpu).
    """
    if isinstance(q, (int, float)):
        return float(q)
    s = q.strip()
    suffixes = {
        "Ki": 1024.0, "Mi": 1024.0**2, "Gi": 1024.0**3, "Ti": 1024.0**4,
        "Pi": 1024.0**5, "Ei": 1024.0**6,
        "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    }
    for suf, mul in suffixes.items():
        if s.endswith(suf):
            return float(s[: -len(suf)]) * mul
    if s.endswith("m"):
        return float(s[:-1]) / 1000.0
    return float(s)


def sum_resources(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) + v
    return out


def subtract(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    """a - b, keeping negative values (used for lacking-resource detection,
    reference snapshot.go:132-165)."""
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0.0) - v
    return out


def subtract_non_negative(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    return {k: max(0.0, v) for k, v in subtract(a, b).items()}


def abs_resources(a: Mapping[str, float]) -> ResourceList:
    return {k: abs(v) for k, v in a.items()}


def max_resources(a: Mapping[str, float], b: Mapping[str, float]) -> ResourceList:
    out = dict(a)
    for k, v in b.items():
        out[k] = max(out.get(k, 0.0), v)
    return out


def negatives_only(a: Mapping[str, float]) -> ResourceList:
    """Keep only strictly negative entries, as positive magnitudes."""
    return {k: -v for k, v in a.items() if v < 0}


def fits(request: Mapping[str, float], available: Mapping[str, float]) -> bool:
    return all(available.get(k, 0.0) >= v for k, v in request.items() if v > 0)


def less_or_equal(a: Mapping[str, float], b: Mapping[str, float]) -> bool:
    """True iff a[k] <= b[k] for every resource in a (missing in b == 0)."""
    return all(v <= b.get(k, 0.0) for k, v in a.items())


def any_greater(a: Mapping[str, float], b: Mapping[str, float]) -> bool:
    """True iff a exceeds b in at least one resource."""
    return any(v > b.get(k, 0.0) for k, v in a.items())


def nonzero(a: Mapping[str, float]) -> ResourceList:
    return {k: v for k, v in a.items() if v != 0}


def pod_request(pod) -> ResourceList:
    """Effective pod resource request: max(max(initContainers), sum(containers))
    + overhead.  Reference pkg/resource/resource.go:127-146.
    """
    total: ResourceList = {}
    for c in pod.spec.containers:
        total = sum_resources(total, c.resources)
    for ic in pod.spec.init_containers:
        total = max_resources(total, ic.resources)
    if pod.spec.overhead:
        total = sum_resources(total, pod.spec.overhead)
    return total
