"""Minimal Kubernetes object model.

The control plane in this framework is cluster-API-shaped (level-triggered
reconcilers exchanging state through node annotations — SURVEY.md §1 "the two
planes"), so we carry a small, typed object model rather than raw dicts.
Analog of the corev1 types used throughout the reference.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace

from .resources import ResourceList

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = ""
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None
    owner_kind: str = ""          # e.g. "DaemonSet" — used by pod predicates
    resource_version: int = 0


@dataclass
class Container:
    name: str = "main"
    resources: ResourceList = field(default_factory=dict)


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: ResourceList = field(default_factory=dict)
    node_name: str = ""
    priority: int = 0
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    scheduler_name: str = "nos-tpu-scheduler"


@dataclass
class PodCondition:
    type: str
    status: str
    reason: str = ""
    message: str = ""


# Pod phases
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"


@dataclass
class PodStatus:
    phase: str = PENDING
    conditions: list[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""


@dataclass
class Pod:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def is_unschedulable(self) -> bool:
        """Pod marked unschedulable by the scheduler (condition
        PodScheduled=False/Unschedulable).  Reference pkg/util/pod/pod.go:31-39."""
        return any(
            c.type == "PodScheduled" and c.status == "False" and c.reason == "Unschedulable"
            for c in self.status.conditions
        )

    def mark_unschedulable(self, message: str = "") -> None:
        self.status.conditions = [
            c for c in self.status.conditions if c.type != "PodScheduled"
        ]
        self.status.conditions.append(
            PodCondition("PodScheduled", "False", "Unschedulable", message)
        )


@dataclass
class NodeStatus:
    allocatable: ResourceList = field(default_factory=dict)
    capacity: ResourceList = field(default_factory=dict)


@dataclass
class Node:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class ConfigMap:
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)


def clone_meta(meta: ObjectMeta) -> ObjectMeta:
    return replace(
        meta, labels=dict(meta.labels), annotations=dict(meta.annotations)
    )
