"""Minimal Kubernetes object model.

The control plane in this framework is cluster-API-shaped (level-triggered
reconcilers exchanging state through node annotations — SURVEY.md §1 "the two
planes"), so we carry a small, typed object model rather than raw dicts.
Analog of the corev1 types used throughout the reference.
"""

from __future__ import annotations

import copy
import itertools
from dataclasses import dataclass, field, replace

from .resources import ResourceList

_uid_counter = itertools.count(1)

# The APIServer deep-copies on every read/write to keep real-API-server
# value isolation, which makes object copying THE control-plane hot path
# (20M generic-deepcopy frames per simulated v5e-256 trace).  These object
# trees are acyclic and alias-free, so a direct structural copy preserves
# deepcopy semantics at a fraction of the dispatch cost.
_ATOMIC = (str, int, float, bool, type(None))


def _fast_copy(v, memo):
    t = v.__class__
    if t in _ATOMIC:
        return v
    if t is dict:
        return {k: _fast_copy(x, memo) for k, x in v.items()}
    if t is list:
        return [_fast_copy(x, memo) for x in v]
    if t is tuple:
        return tuple(_fast_copy(x, memo) for x in v)
    dc = getattr(v, "__deepcopy__", None)
    if dc is not None:
        return dc(memo)
    return copy.deepcopy(v, memo)


class FastCopy:
    """Mixin: structural __deepcopy__ for the kube/CRD object model.

    Copies every instance attribute (including ones tests bolt on), so it
    is behavior-compatible with generic deepcopy for these trees."""

    def __deepcopy__(self, memo):
        new = object.__new__(self.__class__)
        nd = new.__dict__
        for k, v in self.__dict__.items():
            nd[k] = _fast_copy(v, memo)
        return new


def fast_deepcopy(obj):
    """Deep copy one FastCopy object without copy.deepcopy's dispatch
    prologue (memo setup, reductor probing) — the per-object hot-path
    copy for callers that know the class carries the structural
    __deepcopy__ (e.g. the scheduler's assume cache booking a pod)."""
    return obj.__deepcopy__({})


def new_uid() -> str:
    return f"uid-{next(_uid_counter)}"


@dataclass
class ObjectMeta(FastCopy):
    name: str = ""
    namespace: str = ""
    uid: str = field(default_factory=new_uid)
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    creation_timestamp: float = 0.0
    deletion_timestamp: float | None = None
    owner_kind: str = ""          # e.g. "DaemonSet" — used by pod predicates
    resource_version: int = 0


@dataclass
class Container(FastCopy):
    name: str = "main"
    resources: ResourceList = field(default_factory=dict)


@dataclass
class PodSpec(FastCopy):
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: ResourceList = field(default_factory=dict)
    node_name: str = ""
    priority: int = 0
    preemption_policy: str = "PreemptLowerPriority"  # or "Never"
    scheduler_name: str = "nos-tpu-scheduler"


@dataclass
class PodCondition(FastCopy):
    type: str
    status: str
    reason: str = ""
    message: str = ""


# Pod phases
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"


@dataclass
class PodStatus(FastCopy):
    phase: str = PENDING
    conditions: list[PodCondition] = field(default_factory=list)
    nominated_node_name: str = ""


@dataclass
class Pod(FastCopy):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    @property
    def key(self) -> str:
        return f"{self.metadata.namespace}/{self.metadata.name}"

    def is_unschedulable(self) -> bool:
        """Pod marked unschedulable by the scheduler (condition
        PodScheduled=False, reason Unschedulable).  Reference
        pkg/util/pod/pod.go:31-39.  The split() tolerates conditions
        persisted by older builds that refined the reason in place
        ("Unschedulable/<class>") before the class moved to the
        `nos.tpu/unschedulable-class` label."""
        return any(
            c.type == "PodScheduled" and c.status == "False"
            and c.reason.split("/", 1)[0] == "Unschedulable"
            for c in self.status.conditions
        )

    def unschedulable_class(self) -> str:
        """Machine-readable refinement of the Unschedulable verdict
        (e.g. "quota-hol"), from the scheduler-stamped label; "" when
        unclassified.  Falls back to the legacy in-reason refinement for
        conditions written by older builds."""
        from nos_tpu.api.constants import LABEL_UNSCHEDULABLE_CLASS

        cls = self.metadata.labels.get(LABEL_UNSCHEDULABLE_CLASS, "")
        if cls:
            return cls
        for c in self.status.conditions:
            if c.type == "PodScheduled" and c.status == "False" \
                    and c.reason.split("/", 1)[0] == "Unschedulable" \
                    and "/" in c.reason:
                return c.reason.split("/", 1)[1]
        return ""

    def mark_unschedulable(self, message: str = "",
                           reason: str = "") -> None:
        """The condition reason is the ecosystem-exact "Unschedulable"
        string — external tooling (cluster-autoscaler, kueue, operator
        scripts) matches `reason == "Unschedulable"` verbatim, so the
        machine-readable class `reason` (e.g. "quota-hol") is carried on
        the `nos.tpu/unschedulable-class` label (read it back via
        `unschedulable_class()`), never by refining the reason string."""
        from nos_tpu.api.constants import LABEL_UNSCHEDULABLE_CLASS

        self.status.conditions = [
            c for c in self.status.conditions if c.type != "PodScheduled"
        ]
        self.status.conditions.append(
            PodCondition("PodScheduled", "False", "Unschedulable", message)
        )
        if reason:
            self.metadata.labels[LABEL_UNSCHEDULABLE_CLASS] = reason
        else:
            self.metadata.labels.pop(LABEL_UNSCHEDULABLE_CLASS, None)


@dataclass
class NodeStatus(FastCopy):
    allocatable: ResourceList = field(default_factory=dict)
    capacity: ResourceList = field(default_factory=dict)


@dataclass
class Node(FastCopy):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    status: NodeStatus = field(default_factory=NodeStatus)

    @property
    def name(self) -> str:
        return self.metadata.name


@dataclass
class ConfigMap(FastCopy):
    metadata: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)


def clone_meta(meta: ObjectMeta) -> ObjectMeta:
    return replace(
        meta, labels=dict(meta.labels), annotations=dict(meta.annotations)
    )
