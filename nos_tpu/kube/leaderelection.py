"""Leader election over the API substrate (ConfigMap-lease pattern).

Every reference main runs controller-runtime leader election backed by a
coordination Lease (helm values.yaml:57,121,285); here the lease is a
ConfigMap annotation record — the classic pre-Lease-API pattern — so the
SAME implementation works against the in-memory APIServer and the REST
substrate (the ConfigMap kind exists on both; a dedicated Lease kind
would only exist on the latter).

Protocol: the lease ConfigMap's annotations carry holder identity and a
renew deadline.  A candidate acquires when the lease is absent, expired,
or already its own; the holder renews every `renew_s`; anyone else
re-checks after `retry_s`.  Clock skew tolerance comes from
`lease_duration_s` being several renew intervals.  Acquire/renew writes
go through create/update — PUT carries the read resourceVersion, so a
lost race is a Conflict (409) on both substrates; merge-patch would have
no optimistic concurrency on REST and allow split-brain.

Semantics follow controller-runtime: callbacks fire on gaining
leadership (bind controllers then), and LOSING an acquired lease is
fatal — the owner is expected to shut down and restart as a candidate
(a half-demoted process with live watch callbacks would keep writing).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid

from nos_tpu.kube.client import Conflict, KIND_CONFIGMAP, NotFound
from nos_tpu.kube.objects import ConfigMap, ObjectMeta

logger = logging.getLogger(__name__)

ANN_HOLDER = "nos.tpu/leader"
ANN_DEADLINE = "nos.tpu/lease-renew-deadline"


class LeaderElector:
    """Acquire/renew a named lease; `is_leader` is set while held.

    `run(stop_event)` drives the acquire/renew loop (Main starts it on a
    thread); Main gates every run loop on `is_leader`, so a non-leader
    replica idles until the holder dies or releases."""

    def __init__(self, api, name: str, namespace: str = "nos-tpu-system",
                 identity: str | None = None,
                 lease_duration_s: float = 15.0,
                 renew_s: float = 5.0,
                 retry_s: float = 2.0,
                 clock=time.time,  # wall clock: deadlines cross processes
                 on_started_leading=None,
                 on_stopped_leading=None) -> None:
        self._api = api
        self._name = name
        self._ns = namespace
        self.identity = identity or f"{name}-{uuid.uuid4().hex[:8]}"
        self._duration = lease_duration_s
        self._renew = renew_s
        self._retry = retry_s
        self._clock = clock
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.is_leader = threading.Event()
        self._held_until = 0.0   # deadline of the last lease WE wrote

    # election step outcomes
    LEADING = "leading"   # we hold the lease (held_until refreshed)
    BLOCKED = "blocked"   # another identity verifiably holds a live lease
    ERROR = "error"       # could not tell (API blip, lost write race)

    def try_acquire_or_renew(self) -> str:
        """One election step.  BLOCKED is definitive (we read someone
        else's live lease); ERROR is not — a leader whose own lease has
        not yet expired keeps leading through ERRORs (controller-runtime
        retries until the renew deadline actually passes)."""
        now = self._clock()
        deadline = now + self._duration
        try:
            cm = self._api.try_get(KIND_CONFIGMAP, self._name, self._ns)
            if cm is None:
                cm = ConfigMap(metadata=ObjectMeta(
                    name=self._name, namespace=self._ns,
                    annotations={ANN_HOLDER: self.identity,
                                 ANN_DEADLINE: str(deadline)}))
                try:
                    self._api.create(KIND_CONFIGMAP, cm)
                except NotFound:
                    # the lease NAMESPACE is missing: unrecoverable
                    # misconfiguration, not "someone else leads"
                    logger.error(
                        "leader election %s: cannot create lease in "
                        "namespace %r (does it exist?)",
                        self._name, self._ns)
                    return self.ERROR
                logger.info("leader election %s: %s acquired",
                            self._name, self.identity)
                self._held_until = deadline
                return self.LEADING
            anns = cm.metadata.annotations
            holder = anns.get(ANN_HOLDER, "")
            try:
                held_until = float(anns.get(ANN_DEADLINE, "0"))
            except ValueError:
                held_until = 0.0
            if holder != self.identity and held_until > now:
                return self.BLOCKED  # someone else holds a live lease
            # CAS: the PUT carries the resourceVersion we just read, so
            # a concurrent acquirer makes this a Conflict — merge-patch
            # would have no such guard on the REST substrate.
            anns[ANN_HOLDER] = self.identity
            anns[ANN_DEADLINE] = str(deadline)
            # CAS is the election: a Conflict means another candidate won.
            # noslint: N001 — retrying the lost CAS would steal the winner's lease
            self._api.update(KIND_CONFIGMAP, cm)
            if holder != self.identity:
                logger.info("leader election %s: %s took over from %s",
                            self._name, self.identity, holder or "<none>")
            self._held_until = deadline
            return self.LEADING
        except (Conflict, NotFound):
            return self.ERROR   # lost a write race: re-read next step
        except Exception as e:  # noqa: BLE001 — a blip must not end election
            logger.warning("leader election %s: step failed (%s); retrying",
                           self._name, e)
            return self.ERROR

    def run(self, stop: threading.Event) -> None:
        """Acquire/renew loop until `stop`; releases the lease on exit.
        Losing an acquired lease invokes on_stopped_leading (fatal in
        Main: a half-demoted process would keep writing via its watch
        callbacks) and ends the loop."""
        led = False
        try:
            while not stop.is_set():
                outcome = self.try_acquire_or_renew()
                if outcome == self.LEADING:
                    if not led:
                        led = True
                        if self.on_started_leading is not None:
                            self.on_started_leading()
                    self.is_leader.set()
                    stop.wait(self._renew)
                    continue
                if led and outcome == self.ERROR \
                        and self._clock() < self._held_until:
                    # our lease is still valid — a blip must not demote;
                    # retry renewing until the deadline actually passes
                    stop.wait(self._retry)
                    continue
                if led:
                    logger.error(
                        "leader election %s: %s LOST the lease — "
                        "stopping (restart to rejoin as candidate)",
                        self._name, self.identity)
                    self.is_leader.clear()
                    if self.on_stopped_leading is not None:
                        self.on_stopped_leading()
                    return
                self.is_leader.clear()
                stop.wait(self._retry)
        finally:
            self.is_leader.clear()
            self._release()

    def _release(self) -> None:
        """Drop the lease so a successor takes over immediately."""
        try:
            def mutate(cm: ConfigMap) -> None:
                anns = cm.metadata.annotations
                if anns.get(ANN_HOLDER) == self.identity:
                    anns[ANN_DEADLINE] = "0"

            # noslint: N001 — best-effort lease drop on exit; must not retry against a successor
            self._api.patch(KIND_CONFIGMAP, self._name, self._ns,
                            mutate=mutate)
        except (Conflict, NotFound, OSError):
            pass
