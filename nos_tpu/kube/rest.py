"""KubeClient: the production substrate adapter — the APIServer surface
over a real kube-apiserver's REST API.

The whole control plane is written against the in-memory APIServer's
method surface (create/get/update/patch/delete/list/watch plus the
field-index helpers); this class implements the same surface over HTTP
so the cmd/ mains run against a real cluster with `--kubeconfig`
(reference analog: the controller-runtime client every main builds).
Contract tests (tests/test_substrate.py) run the in-memory server and
this client against the same assertions, the client talking to a
k8s-REST-shaped stub that enforces the real server's awkward semantics
(nodeName immutability, the /binding and /status subresources).

Semantics mapping:
- create/update/delete  -> POST/PUT/DELETE on the typed paths
  (nos_tpu/kube/k8s_codec.py owns JSON <-> dataclass translation).
- patch(mutate=...)     -> JSON **merge patch** of exactly the fields
  the mutate callback changed (diff of the codec's before/after
  encodings), so unmodeled server-side fields are never stripped or
  overwritten.  A status change routes to the /status subresource; a
  Pod gaining spec.nodeName routes through POST .../binding (nodeName
  is immutable via PUT/PATCH on a real apiserver).
- watch(fn)             -> informer: synchronous list replay as ADDED,
  then a streaming thread that re-lists on every (re)connect and diffs
  against what it already delivered, so events raced between list and
  stream — or dropped across a reconnect/410 — are recovered.
- register_admission    -> collects the validator for the operator's
  HTTPS AdmissionReview endpoint (kube/webhook.py WebhookServer): on a
  real cluster enforcement happens server-side via the chart's
  ValidatingWebhookConfiguration pointing at that endpoint, with the
  SAME validator functions the in-memory substrate runs in-process.

Auth: minimal kubeconfig — server, CA (file or data), bearer token or
client certificate (file or data).  Exotic auth plugins are out of
scope.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
import urllib.error
import urllib.request
from typing import Any, Callable

from nos_tpu.kube.client import (
    Conflict, NotFound, TransientAPIError, WatchFn,
)
from nos_tpu.kube.k8s_codec import KIND_REST, from_k8s, rest_path, to_k8s
from nos_tpu.kube.objects import Pod

logger = logging.getLogger(__name__)

# Kinds whose status lives behind the /status subresource (the shipped
# CRDs all declare it; Pod and PDB have it natively).
_STATUS_SUBRESOURCE = {"Pod", "ElasticQuota", "CompositeElasticQuota",
                       "PodGroup", "PodDisruptionBudget", "Node"}


def merge_diff(old: Any, new: Any) -> Any:
    """JSON merge patch (RFC 7386) turning `old` into `new`; None when
    they are equal."""
    if not isinstance(old, dict) or not isinstance(new, dict):
        return new if new != old else None
    out = {}
    for key in new:
        if key not in old:
            out[key] = new[key]
        else:
            delta = merge_diff(old[key], new[key])
            if delta is not None:
                out[key] = delta
    for key in old:
        if key not in new:
            out[key] = None  # merge-patch deletion
    return out or None


def _b64_file(data: str, suffix: str) -> str:
    import base64
    import tempfile

    tmp = tempfile.NamedTemporaryFile(suffix=suffix, delete=False,
                                      mode="wb")
    tmp.write(base64.b64decode(data))
    tmp.close()
    return tmp.name


class KubeConfig:
    def __init__(self, server: str, token: str = "",
                 ca_file: str = "", insecure: bool = False,
                 client_cert_file: str = "",
                 client_key_file: str = "") -> None:
        self.server = server.rstrip("/")
        self.token = token
        self.ca_file = ca_file
        self.insecure = insecure
        self.client_cert_file = client_cert_file
        self.client_key_file = client_key_file

    @classmethod
    def load(cls, path: str) -> "KubeConfig":
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f)
        ctx_name = data.get("current-context", "")
        contexts = {c["name"]: c["context"]
                    for c in data.get("contexts") or []}
        ctx = contexts.get(ctx_name) or next(iter(contexts.values()), {})
        clusters = {c["name"]: c["cluster"]
                    for c in data.get("clusters") or []}
        users = {u["name"]: u["user"] for u in data.get("users") or []}
        cluster = clusters.get(ctx.get("cluster", "")) \
            or next(iter(clusters.values()), {})
        user = users.get(ctx.get("user", "")) \
            or next(iter(users.values()), {})
        ca_file = cluster.get("certificate-authority", "")
        if cluster.get("certificate-authority-data") and not ca_file:
            ca_file = _b64_file(
                cluster["certificate-authority-data"], ".crt")
        cert_file = user.get("client-certificate", "")
        if user.get("client-certificate-data") and not cert_file:
            cert_file = _b64_file(user["client-certificate-data"], ".crt")
        key_file = user.get("client-key", "")
        if user.get("client-key-data") and not key_file:
            key_file = _b64_file(user["client-key-data"], ".key")
        return cls(
            server=cluster.get("server", ""),
            token=user.get("token", ""),
            ca_file=ca_file,
            insecure=bool(cluster.get("insecure-skip-tls-verify", False)),
            client_cert_file=cert_file,
            client_key_file=key_file,
        )


class KubeClient:
    """APIServer-surface client over kube-apiserver REST."""

    def __init__(self, config: KubeConfig, timeout_s: float = 10.0) -> None:
        from nos_tpu.kube.webhook import AdmissionHandler

        self._cfg = config
        self._timeout = timeout_s
        self._watch_stop = threading.Event()
        self._watch_threads: list[threading.Thread] = []
        # validators registered via register_admission, served by the
        # operator's HTTPS AdmissionReview endpoint (kube/webhook.py)
        self.admission = AdmissionHandler(self)
        if config.server.startswith("https"):
            if config.insecure:
                self._ssl = ssl._create_unverified_context()
            else:
                self._ssl = ssl.create_default_context(
                    cafile=config.ca_file or None)
            if config.client_cert_file:
                self._ssl.load_cert_chain(
                    config.client_cert_file,
                    config.client_key_file or None)
        else:
            self._ssl = None

    @classmethod
    def from_kubeconfig(cls, path: str) -> "KubeClient":
        return cls(KubeConfig.load(path))

    # -- HTTP ---------------------------------------------------------------
    def _request(self, method: str, path: str, body: dict | None = None,
                 query: str = "", timeout: float | None = None,
                 content_type: str = "application/json"):
        url = self._cfg.server + path + (f"?{query}" if query else "")
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Accept", "application/json")
        if body is not None:
            req.add_header("Content-Type", content_type)
        if self._cfg.token:
            req.add_header("Authorization", f"Bearer {self._cfg.token}")
        try:
            return urllib.request.urlopen(
                req, timeout=timeout or self._timeout, context=self._ssl)
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise NotFound(path) from None
            if e.code == 409:
                raise Conflict(path) from None
            detail = e.read().decode(errors="replace")[:500]
            if e.code >= 500 or e.code == 429:
                # server-side / overload failures are retryable
                # (utils/retry.py); 4xx request errors are not
                raise TransientAPIError(
                    f"{method} {path} -> HTTP {e.code}: {detail}") from None
            raise RuntimeError(
                f"{method} {path} -> HTTP {e.code}: {detail}") from None

    def _json(self, method: str, path: str, body: dict | None = None,
              query: str = "", content_type: str = "application/json"):
        with self._request(method, path, body, query,
                           content_type=content_type) as resp:
            return json.load(resp)

    # -- CRUD (APIServer surface) ------------------------------------------
    def create(self, kind: str, obj: Any) -> Any:
        ns = getattr(obj.metadata, "namespace", "")
        data = self._json("POST", rest_path(kind, ns), to_k8s(kind, obj))
        return from_k8s(kind, data)

    def get(self, kind: str, name: str, namespace: str = "") -> Any:
        data = self._json("GET", rest_path(kind, namespace, name))
        return from_k8s(kind, data)

    def try_get(self, kind: str, name: str, namespace: str = "") -> Any | None:
        try:
            return self.get(kind, name, namespace)
        except NotFound:
            return None

    def update(self, kind: str, obj: Any) -> Any:
        ns = getattr(obj.metadata, "namespace", "")
        data = self._json("PUT", rest_path(kind, ns, obj.metadata.name),
                          to_k8s(kind, obj))
        return from_k8s(kind, data)

    _MERGE = "application/merge-patch+json"

    def patch(self, kind: str, name: str, namespace: str = "",
              mutate: Callable[[Any], None] | None = None) -> Any:
        """Merge-patch exactly the fields `mutate` changed.

        The diff is computed between the codec's encodings of the object
        before and after the callback, so fields this framework does not
        model are never touched on the server.  Special routes:
        - Pod spec.nodeName appearing -> POST .../binding (nodeName is
          immutable through PUT/PATCH);
        - status changes -> PATCH on the /status subresource.
        """
        obj = self.get(kind, name, namespace)
        before = to_k8s(kind, obj)
        if mutate is not None:
            mutate(obj)
        after = to_k8s(kind, obj)
        delta = merge_diff(before, after) or {}
        meta_delta = delta.get("metadata")
        if meta_delta:  # keep label/annotation changes, drop rv noise
            for noise in ("resourceVersion", "uid", "creationTimestamp"):
                meta_delta.pop(noise, None)
            if not meta_delta:
                delta.pop("metadata")

        path = rest_path(kind, namespace, name)
        if kind == "Pod":
            spec_delta = delta.get("spec") or {}
            node_name = spec_delta.pop("nodeName", None)
            if not spec_delta:
                delta.pop("spec", None)
            if node_name:
                self._json("POST", f"{path}/binding", {
                    "apiVersion": "v1", "kind": "Binding",
                    "metadata": {"name": name, "namespace": namespace},
                    "target": {"apiVersion": "v1", "kind": "Node",
                               "name": node_name},
                })
        status_delta = None
        if kind in _STATUS_SUBRESOURCE:
            status_delta = delta.pop("status", None)
        result = None
        if delta:
            result = self._json("PATCH", path, delta,
                                content_type=self._MERGE)
        if status_delta is not None:
            result = self._json("PATCH", f"{path}/status",
                                {"status": status_delta},
                                content_type=self._MERGE)
        if result is not None:
            return from_k8s(kind, result)
        # binding-only (or no-op) path: one GET for the server's view
        return self.get(kind, name, namespace)

    def delete(self, kind: str, name: str, namespace: str = "") -> None:
        with self._request("DELETE", rest_path(kind, namespace, name)):
            pass

    def list(self, kind: str, namespace: str | None = None,
             label_selector: dict[str, str] | None = None,
             filter_fn: Callable[[Any], bool] | None = None) -> list[Any]:
        query = ""
        if label_selector:
            sel = ",".join(f"{k}={v}" for k, v in label_selector.items())
            query = f"labelSelector={urllib.request.quote(sel)}"
        data = self._json("GET", rest_path(kind, namespace or ""),
                          query=query)
        out = [from_k8s(kind, item) for item in data.get("items") or []]
        if namespace is not None:
            out = [o for o in out
                   if getattr(o.metadata, "namespace", "") == namespace]
        if filter_fn is not None:
            out = [o for o in out if filter_fn(o)]
        return out

    # -- watch --------------------------------------------------------------
    def watch(self, kind: str, fn: WatchFn,
              selector=None) -> Callable[[], None]:
        """Informer-style: replay existing objects as ADDED synchronously,
        then stream; every (re)connect re-lists and diffs against what was
        already delivered, so events raced between list and stream — or
        dropped across a 410/reconnect — are recovered as synthetic
        ADDED/MODIFIED/DELETED.

        `selector` filters delivered objects client-side (the in-memory
        substrate's field-selector analog; a production deployment would
        push it down as an apiserver fieldSelector).  Objects that stop
        matching are NOT reported (no synthetic DELETED on leaving the
        selection) — select only on fields stable for the object's
        relevant lifetime, e.g. a pod's spec.nodeName."""
        stop = threading.Event()
        # (namespace, name) -> resource_version already delivered
        known: dict[tuple[str, str], int] = {}

        def obj_key(obj) -> tuple[str, str]:
            return (getattr(obj.metadata, "namespace", ""),
                    obj.metadata.name)

        def deliver(event: str, obj) -> None:
            if selector is not None and not selector(obj):
                return
            key = obj_key(obj)
            if event == "DELETED":
                known.pop(key, None)
                fn(event, obj)
                return
            rv = obj.metadata.resource_version
            prev = known.get(key)
            if prev is None:
                known[key] = rv
                fn("ADDED", obj)
            elif rv > prev:
                # strictly newer only: a reconnecting stream can replay
                # events older than what sync() already delivered, and
                # forwarding them would regress watchers to stale state
                known[key] = rv
                fn("MODIFIED", obj)

        def sync() -> str:
            """List, diff against `known`, return the list rv."""
            listing = self._json("GET", rest_path(kind, ""))
            seen: set[tuple[str, str]] = set()
            for item in listing.get("items") or []:
                obj = from_k8s(kind, item)
                seen.add(obj_key(obj))
                deliver("MODIFIED", obj)
            for ns, name in [k for k in known if k not in seen]:
                deliver("DELETED", from_k8s(
                    kind, {"metadata": {"name": name, "namespace": ns}}))
            return str((listing.get("metadata") or {})
                       .get("resourceVersion", ""))

        rv = sync()  # synchronous initial replay (informer sync)

        def pump() -> None:
            from nos_tpu.utils.retry import Backoff

            # Capped jittered backoff between reconnect attempts: a down
            # or overloaded apiserver must not be hammered on a tight
            # 1 s loop by every watcher of every kind.  Reset only after
            # a successful (re)connect + sync.
            backoff = Backoff(base_s=0.5, cap_s=30.0)
            last_rv = rv
            while not stop.is_set() and not self._watch_stop.is_set():
                try:
                    q = "watch=true" + (
                        f"&resourceVersion={last_rv}" if last_rv else "")
                    with self._request("GET", rest_path(kind, ""),
                                       query=q, timeout=330.0) as resp:
                        # The stream is registered server-side once the
                        # response headers arrive; a sync here recovers
                        # anything that happened between the previous
                        # list and this registration (deliver() dedups
                        # by resourceVersion).
                        last_rv = sync()
                        backoff.reset()
                        for line in resp:
                            if stop.is_set():
                                return
                            if not line.strip():
                                continue
                            evt = json.loads(line)
                            if evt.get("type") == "ERROR":
                                break  # e.g. 410 Gone: reconnect + sync
                            obj = from_k8s(kind, evt.get("object") or {})
                            deliver(evt.get("type", "MODIFIED"), obj)
                except (OSError, ValueError, NotFound, Conflict,
                        RuntimeError) as e:
                    if stop.is_set() or self._watch_stop.is_set():
                        return
                    delay = backoff.next_delay()
                    logger.debug("watch %s reconnect in %.1fs: %s",
                                 kind, delay, e)
                    stop.wait(delay)

        t = threading.Thread(target=pump, name=f"watch-{kind}", daemon=True)
        t.start()
        self._watch_threads.append(t)
        return stop.set

    def close(self) -> None:
        self._watch_stop.set()

    # -- field-index helpers (APIServer parity) ----------------------------
    def kinds(self) -> list[str]:
        return [k for k in KIND_REST if self.list(k)]

    def pods_by_phase(self, phase: str) -> list[Pod]:
        return self.list("Pod", filter_fn=lambda p: p.status.phase == phase)

    def pods_on_node(self, node_name: str) -> list[Pod]:
        return self.list(
            "Pod", filter_fn=lambda p: p.spec.node_name == node_name)

    def register_admission(self, kind: str, fn) -> None:
        """Collect the validator for the AdmissionReview endpoint the
        operator serves (kube/webhook.py); a KubeClient cannot intercept
        writes client-side — the kube-apiserver consults the webhook."""
        self.admission.register(kind, fn)
