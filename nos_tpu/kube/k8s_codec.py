"""Kubernetes JSON <-> nos_tpu object model codec.

The in-memory APIServer stores typed dataclasses; a real kube-apiserver
speaks camelCase JSON with string quantities.  This module owns the
translation for exactly the kinds and fields the control plane uses —
unknown incoming fields are ignored (the controllers never touch them),
and the outgoing JSON carries only what the framework sets.

Reference analog: the client-go typed codecs behind every reconciler;
here it backs nos_tpu/kube/rest.py (the production substrate adapter).
"""

from __future__ import annotations

import re
import time
from typing import Any

from nos_tpu.api.elasticquota import (
    CompositeElasticQuota, CompositeElasticQuotaSpec, ElasticQuota,
    ElasticQuotaSpec, ElasticQuotaStatus,
)
from nos_tpu.api.pdb import (
    PodDisruptionBudget, PodDisruptionBudgetSpec, PodDisruptionBudgetStatus,
)
from nos_tpu.api.podgroup import PodGroup, PodGroupSpec, PodGroupStatus
from nos_tpu.kube.objects import (
    ConfigMap, Container, Node, NodeStatus, ObjectMeta, Pod, PodCondition,
    PodSpec, PodStatus,
)

GROUP_VERSION = "nos.tpu/v1alpha1"

# kind -> (apiVersion, REST plural, namespaced)
KIND_REST: dict[str, tuple[str, str, bool]] = {
    "Pod": ("v1", "pods", True),
    "Node": ("v1", "nodes", False),
    "ConfigMap": ("v1", "configmaps", True),
    "ElasticQuota": (GROUP_VERSION, "elasticquotas", True),
    "CompositeElasticQuota": (GROUP_VERSION, "compositeelasticquotas", True),
    "PodGroup": (GROUP_VERSION, "podgroups", True),
    "PodDisruptionBudget": ("policy/v1", "poddisruptionbudgets", True),
}

_QTY_SUFFIX = {
    "Ki": 2 ** 10, "Mi": 2 ** 20, "Gi": 2 ** 30, "Ti": 2 ** 40,
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "m": 1e-3,
}


def parse_quantity(q: Any) -> float:
    """k8s resource.Quantity string -> float (plain numbers, binary/SI
    suffixes, milli)."""
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    m = re.fullmatch(r"([0-9.eE+-]+)([A-Za-z]*)", s)
    if not m:
        raise ValueError(f"unparseable quantity {q!r}")
    value, suffix = m.groups()
    mult = _QTY_SUFFIX.get(suffix, None) if suffix else 1
    if mult is None:
        raise ValueError(f"unknown quantity suffix {suffix!r} in {q!r}")
    return float(value) * mult


def format_quantity(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return str(v)


def _resources_out(rl: dict) -> dict:
    return {k: format_quantity(v) for k, v in (rl or {}).items()}


def _resources_in(data: dict) -> dict:
    return {k: parse_quantity(v) for k, v in (data or {}).items()}


_RFC3339 = "%Y-%m-%dT%H:%M:%SZ"


def _ts_in(s: Any) -> float:
    if not s:
        return 0.0
    try:
        return float(s)
    except (TypeError, ValueError):
        pass
    try:
        import calendar

        return float(calendar.timegm(time.strptime(str(s), _RFC3339)))
    except ValueError:
        return 0.0


def meta_out(meta: ObjectMeta, namespaced: bool) -> dict:
    out: dict = {"name": meta.name}
    if namespaced and meta.namespace:
        out["namespace"] = meta.namespace
    if meta.labels:
        out["labels"] = dict(meta.labels)
    if meta.annotations:
        out["annotations"] = dict(meta.annotations)
    if meta.resource_version:
        out["resourceVersion"] = str(meta.resource_version)
    if meta.uid and not meta.uid.startswith("uid-"):
        out["uid"] = meta.uid
    return out


def meta_in(data: dict) -> ObjectMeta:
    owner_kind = ""
    owners = data.get("ownerReferences") or []
    if owners:
        owner_kind = owners[0].get("kind", "")
    rv = data.get("resourceVersion", 0)
    try:
        rv = int(rv)
    except (TypeError, ValueError):
        rv = 0
    return ObjectMeta(
        name=data.get("name", ""),
        namespace=data.get("namespace", ""),
        uid=data.get("uid") or ObjectMeta().uid,
        labels=dict(data.get("labels") or {}),
        annotations=dict(data.get("annotations") or {}),
        creation_timestamp=_ts_in(data.get("creationTimestamp")),
        deletion_timestamp=(
            _ts_in(data["deletionTimestamp"])
            if data.get("deletionTimestamp") else None),
        owner_kind=owner_kind,
        resource_version=rv,
    )


# -- per-kind codecs ---------------------------------------------------------

def _pod_out(p: Pod) -> dict:
    def container_out(c: Container) -> dict:
        return {"name": c.name,
                "resources": {"limits": _resources_out(c.resources)}}

    spec: dict = {
        "containers": [container_out(c) for c in p.spec.containers],
        "schedulerName": p.spec.scheduler_name,
    }
    if p.spec.init_containers:
        spec["initContainers"] = [
            container_out(c) for c in p.spec.init_containers]
    if p.spec.overhead:
        spec["overhead"] = _resources_out(p.spec.overhead)
    if p.spec.node_name:
        spec["nodeName"] = p.spec.node_name
    if p.spec.priority:
        spec["priority"] = p.spec.priority
    if p.spec.preemption_policy != "PreemptLowerPriority":
        spec["preemptionPolicy"] = p.spec.preemption_policy
    status: dict = {"phase": p.status.phase}
    if p.status.conditions:
        status["conditions"] = [
            {"type": c.type, "status": c.status, "reason": c.reason,
             "message": c.message} for c in p.status.conditions]
    if p.status.nominated_node_name:
        status["nominatedNodeName"] = p.status.nominated_node_name
    return {"spec": spec, "status": status}


def _pod_in(data: dict) -> Pod:
    def container_in(c: dict) -> Container:
        limits = (c.get("resources") or {}).get("limits") or {}
        requests = (c.get("resources") or {}).get("requests") or {}
        return Container(name=c.get("name", "main"),
                         resources=_resources_in(limits or requests))

    spec = data.get("spec") or {}
    status = data.get("status") or {}
    return Pod(
        metadata=meta_in(data.get("metadata") or {}),
        spec=PodSpec(
            containers=[container_in(c)
                        for c in spec.get("containers") or []],
            init_containers=[container_in(c)
                             for c in spec.get("initContainers") or []],
            overhead=_resources_in(spec.get("overhead") or {}),
            node_name=spec.get("nodeName", ""),
            priority=spec.get("priority") or 0,
            preemption_policy=spec.get("preemptionPolicy")
            or "PreemptLowerPriority",
            scheduler_name=spec.get("schedulerName", ""),
        ),
        status=PodStatus(
            phase=status.get("phase", "Pending"),
            conditions=[
                PodCondition(type=c.get("type", ""),
                             status=c.get("status", ""),
                             reason=c.get("reason", ""),
                             message=c.get("message", ""))
                for c in status.get("conditions") or []],
            nominated_node_name=status.get("nominatedNodeName", ""),
        ),
    )


def _node_out(n: Node) -> dict:
    return {"status": {
        "allocatable": _resources_out(n.status.allocatable),
        "capacity": _resources_out(n.status.capacity),
    }}


def _node_in(data: dict) -> Node:
    status = data.get("status") or {}
    return Node(
        metadata=meta_in(data.get("metadata") or {}),
        status=NodeStatus(
            allocatable=_resources_in(status.get("allocatable") or {}),
            capacity=_resources_in(status.get("capacity") or {}),
        ),
    )


def _configmap_out(cm: ConfigMap) -> dict:
    return {"data": dict(cm.data)}


def _configmap_in(data: dict) -> ConfigMap:
    return ConfigMap(metadata=meta_in(data.get("metadata") or {}),
                     data=dict(data.get("data") or {}))


def _eq_out(eq: ElasticQuota) -> dict:
    return {"spec": {"min": _resources_out(eq.spec.min),
                     "max": _resources_out(eq.spec.max)},
            "status": {"used": _resources_out(eq.status.used)}}


def _eq_in(data: dict) -> ElasticQuota:
    spec = data.get("spec") or {}
    status = data.get("status") or {}
    return ElasticQuota(
        metadata=meta_in(data.get("metadata") or {}),
        spec=ElasticQuotaSpec(min=_resources_in(spec.get("min") or {}),
                              max=_resources_in(spec.get("max") or {})),
        status=ElasticQuotaStatus(used=_resources_in(
            status.get("used") or {})),
    )


def _ceq_out(ceq: CompositeElasticQuota) -> dict:
    return {"spec": {"min": _resources_out(ceq.spec.min),
                     "max": _resources_out(ceq.spec.max),
                     "namespaces": list(ceq.spec.namespaces)},
            "status": {"used": _resources_out(ceq.status.used)}}


def _ceq_in(data: dict) -> CompositeElasticQuota:
    spec = data.get("spec") or {}
    status = data.get("status") or {}
    return CompositeElasticQuota(
        metadata=meta_in(data.get("metadata") or {}),
        spec=CompositeElasticQuotaSpec(
            min=_resources_in(spec.get("min") or {}),
            max=_resources_in(spec.get("max") or {}),
            namespaces=list(spec.get("namespaces") or [])),
        status=ElasticQuotaStatus(used=_resources_in(
            status.get("used") or {})),
    )


def _pg_out(pg: PodGroup) -> dict:
    return {"spec": {"minMember": pg.spec.min_member, "mesh": pg.spec.mesh},
            "status": {"phase": pg.status.phase,
                       "scheduled": pg.status.scheduled}}


def _pg_in(data: dict) -> PodGroup:
    spec = data.get("spec") or {}
    status = data.get("status") or {}
    return PodGroup(
        metadata=meta_in(data.get("metadata") or {}),
        spec=PodGroupSpec(min_member=spec.get("minMember") or 1,
                          mesh=spec.get("mesh", "")),
        status=PodGroupStatus(phase=status.get("phase", "Pending"),
                              scheduled=status.get("scheduled") or 0),
    )


def _pdb_out(pdb: PodDisruptionBudget) -> dict:
    return {"spec": {"minAvailable": pdb.spec.min_available,
                     "selector": {"matchLabels": dict(pdb.spec.selector)}},
            "status": {
                "disruptionsAllowed": pdb.status.disruptions_allowed,
                "currentHealthy": pdb.status.current_healthy,
                "desiredHealthy": pdb.status.desired_healthy}}


def _pdb_in(data: dict) -> PodDisruptionBudget:
    spec = data.get("spec") or {}
    status = data.get("status") or {}
    selector = (spec.get("selector") or {}).get("matchLabels") or {}
    return PodDisruptionBudget(
        metadata=meta_in(data.get("metadata") or {}),
        spec=PodDisruptionBudgetSpec(
            min_available=spec.get("minAvailable") or 0,
            selector=dict(selector)),
        status=PodDisruptionBudgetStatus(
            disruptions_allowed=status.get("disruptionsAllowed") or 0,
            current_healthy=status.get("currentHealthy") or 0,
            desired_healthy=status.get("desiredHealthy") or 0),
    )


_OUT = {"Pod": _pod_out, "Node": _node_out, "ConfigMap": _configmap_out,
        "ElasticQuota": _eq_out, "CompositeElasticQuota": _ceq_out,
        "PodGroup": _pg_out, "PodDisruptionBudget": _pdb_out}
_IN = {"Pod": _pod_in, "Node": _node_in, "ConfigMap": _configmap_in,
       "ElasticQuota": _eq_in, "CompositeElasticQuota": _ceq_in,
       "PodGroup": _pg_in, "PodDisruptionBudget": _pdb_in}


def to_k8s(kind: str, obj: Any) -> dict:
    api_version, _, namespaced = KIND_REST[kind]
    body = _OUT[kind](obj)
    body["apiVersion"] = api_version
    body["kind"] = kind
    body["metadata"] = meta_out(obj.metadata, namespaced)
    return body


def from_k8s(kind: str, data: dict) -> Any:
    return _IN[kind](data)


def rest_path(kind: str, namespace: str = "", name: str = "") -> str:
    """API path for a kind (collection without name, object with)."""
    api_version, plural, namespaced = KIND_REST[kind]
    prefix = f"/api/{api_version}" if "/" not in api_version \
        else f"/apis/{api_version}"
    if namespaced and namespace:
        path = f"{prefix}/namespaces/{namespace}/{plural}"
    else:
        path = f"{prefix}/{plural}"
    return f"{path}/{name}" if name else path
