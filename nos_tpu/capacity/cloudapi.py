"""Simulated Cloud TPU node-pool API.

The provider model the capacity plane reconciles against.  Shaped after
the real Cloud TPU node API in the three ways that matter for control
logic, and deliberately nothing else:

- **Creates are asynchronous.**  ``create_node`` returns an operation
  id immediately; the node materialises only after a provisioning
  delay, observed on the next read.  Controllers must therefore be
  level-triggered — they can never assume a create they issued last
  poll has landed, or even that it ever will.
- **Capacity errors are typed.**  ``StockoutError`` (the class/zone has
  no machines) and ``QuotaExceededError`` are *not* retryable inline —
  retrying a stockout hot-loops against an empty warehouse; they feed
  the provisioner's circuit breaker instead.  ``RateLimitedError``
  (HTTP 429) subclasses the kube client's ``TransientAPIError`` so the
  standard jittered-backoff retry path covers it.
- **Joining is a separate step.**  A landed cloud node only becomes a
  scheduler-visible host when the ``joiner`` callback fires (the test
  harness wires it to create the API-server Node and start an agent; a
  real deployment's kubelet plays this role).  A "zombie" is a create
  the cloud reports DONE whose joiner never fires — the node exists,
  burns quota, and never takes work; only deadline reaping clears it.

Fault injection lives in the ``_pre_call`` / ``_draw_create_fault`` /
``_draw_delete_fault`` seams, which this base class leaves inert;
``nos_tpu.testing.chaos.ChaosCloudTPUAPI`` overrides them with seeded
draws.  Keeping the base class fault-free preserves the repo's pattern:
production-shaped code here, chaos in testing/.

Locking: one leaf lock over the operation/node tables.  The joiner is
invoked *outside* the lock (it creates API-server objects, which takes
the API-server lock — calling it under ours would add a lock-order
edge; noslint N004).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from nos_tpu.kube.client import TransientAPIError
from nos_tpu.utils.guards import guarded_by

OP_PENDING = "PENDING"
OP_DONE = "DONE"
OP_FAILED = "FAILED"


class CloudError(Exception):
    """Base class for cloud node-pool API errors."""


class CloudNotFoundError(CloudError):
    """The named node/operation does not exist."""


class AlreadyExistsError(CloudError):
    """A node or in-flight create with this name already exists.  The
    idempotency backstop: a provisioner that crashed after issuing a
    create and re-issues it on restart gets this, not a duplicate."""


class StockoutError(CloudError):
    """No machines of this class available in this zone right now.
    NOT retryable inline — feed the stockout circuit breaker."""

    def __init__(self, machine_class: str, zone: str) -> None:
        super().__init__(f"stockout: {machine_class} in {zone}")
        self.machine_class = machine_class
        self.zone = zone


class QuotaExceededError(CloudError):
    """The project's node quota is exhausted.  NOT retryable inline —
    only a scale-down or a quota bump clears it."""


class RateLimitedError(CloudError, TransientAPIError):
    """HTTP 429.  Subclasses TransientAPIError so the standard
    utils/retry jittered-backoff path retries it."""


class DeleteFailedError(CloudError, TransientAPIError):
    """A delete the cloud accepted but failed to execute.  Transient:
    the level-triggered reconcile simply retries next poll."""


class CloudOperation:
    """One asynchronous create.  ``lands_at`` is when the create
    settles against the clock; ``zombie`` (sim-internal) marks a create
    whose node will land in the cloud but never invoke the joiner."""

    __slots__ = ("op_id", "name", "machine_class", "zone", "labels",
                 "status", "error", "created_at", "lands_at", "zombie")

    def __init__(self, op_id: str, name: str, machine_class: str,
                 zone: str, labels: dict[str, str], created_at: float,
                 lands_at: float, zombie: bool) -> None:
        self.op_id = op_id
        self.name = name
        self.machine_class = machine_class
        self.zone = zone
        self.labels = labels
        self.status = OP_PENDING
        self.error = ""
        self.created_at = created_at
        self.lands_at = lands_at
        self.zombie = zombie

    def to_dict(self) -> dict[str, object]:
        return {
            "op_id": self.op_id,
            "name": self.name,
            "machine_class": self.machine_class,
            "zone": self.zone,
            "labels": dict(self.labels),
            "status": self.status,
            "error": self.error,
            "created_at": self.created_at,
            "lands_at": self.lands_at,
        }


class CloudNode:
    """A node the cloud believes exists (landed create, not deleted)."""

    __slots__ = ("name", "machine_class", "zone", "labels", "created_at")

    def __init__(self, name: str, machine_class: str, zone: str,
                 labels: dict[str, str], created_at: float) -> None:
        self.name = name
        self.machine_class = machine_class
        self.zone = zone
        self.labels = labels
        self.created_at = created_at

    def to_dict(self) -> dict[str, object]:
        return {
            "name": self.name,
            "machine_class": self.machine_class,
            "zone": self.zone,
            "labels": dict(self.labels),
            "created_at": self.created_at,
        }


@guarded_by("_lock", "_ops", "_nodes", "_seq")
class CloudTPUAPI:
    """The fault-free provider.  Operations settle lazily: every read
    first lands any due creates against the clock, invoking ``joiner``
    for each non-zombie landing (outside the lock)."""

    def __init__(self, *,
                 clock: Callable[[], float] = time.monotonic,
                 provision_delay_s: float = 1.0,
                 quota_nodes: int = 0,
                 joiner: Callable[[CloudNode], None] | None = None) -> None:
        self._clock = clock
        self._provision_delay_s = provision_delay_s
        # 0 = unlimited.  Quota counts landed nodes plus in-flight
        # creates: a pending create reserves its machine.
        self._quota_nodes = quota_nodes
        self._joiner = joiner
        self._lock = threading.Lock()
        self._ops: dict[str, CloudOperation] = {}
        self._nodes: dict[str, CloudNode] = {}
        self._seq = 0

    def set_joiner(self, joiner: Callable[[CloudNode], None]) -> None:
        self._joiner = joiner

    # -- fault seams (inert here; ChaosCloudTPUAPI overrides) ---------------
    def _pre_call(self, verb: str) -> None:
        """Raise RateLimitedError to 429 a call before it executes."""

    def _draw_create_fault(self, machine_class: str,
                           zone: str) -> tuple[float, bool]:
        """Return (extra provisioning delay, zombie?) for one create, or
        raise StockoutError / QuotaExceededError."""
        return 0.0, False

    def _draw_delete_fault(self, name: str) -> None:
        """Raise DeleteFailedError to fail one delete."""

    # -- write side ---------------------------------------------------------
    def create_node(self, name: str, machine_class: str, zone: str = "-",
                    labels: dict[str, str] | None = None) -> str:
        """Start an asynchronous node create; returns the operation id.

        Raises AlreadyExistsError for a duplicate name (landed or in
        flight), QuotaExceededError / StockoutError / RateLimitedError
        per the provider's state and the chaos seams."""
        self._pre_call("create")
        now = self._clock()
        with self._lock:
            if name in self._nodes or any(
                    op.name == name and op.status == OP_PENDING
                    for op in self._ops.values()):
                raise AlreadyExistsError(name)
            if self._quota_nodes > 0:
                in_use = len(self._nodes) + sum(
                    1 for op in self._ops.values()
                    if op.status == OP_PENDING)
                if in_use >= self._quota_nodes:
                    raise QuotaExceededError(
                        f"quota: {in_use}/{self._quota_nodes} nodes")
        # the fault draw takes its own (chaos) lock; never ours
        extra, zombie = self._draw_create_fault(machine_class, zone)
        with self._lock:
            self._seq += 1
            op = CloudOperation(
                f"op-{self._seq}", name, machine_class, zone,
                dict(labels or {}), now,
                now + self._provision_delay_s + extra, zombie)
            self._ops[op.op_id] = op
            return op.op_id

    def delete_node(self, name: str) -> None:
        """Delete a landed node, or cancel its in-flight create.  Raises
        CloudNotFoundError if the cloud has no record of the name, and
        DeleteFailedError (transient) under chaos."""
        self._pre_call("delete")
        self._settle()
        self._draw_delete_fault(name)
        with self._lock:
            if name in self._nodes:
                del self._nodes[name]
                return
            for op in self._ops.values():
                if op.name == name and op.status == OP_PENDING:
                    op.status = OP_FAILED
                    op.error = "cancelled"
                    return
        raise CloudNotFoundError(name)

    def ack_operation(self, op_id: str) -> None:
        """Drop a settled operation record: the controller's GC after it
        has journalled the outcome.  Unknown ids are a no-op (crash
        between ack and journal is at-least-once, never lost)."""
        with self._lock:
            op = self._ops.get(op_id)
            if op is not None and op.status != OP_PENDING:
                del self._ops[op_id]

    # -- read side ----------------------------------------------------------
    def get_operation(self, op_id: str) -> dict[str, object]:
        self._settle()
        with self._lock:
            op = self._ops.get(op_id)
            if op is None:
                raise CloudNotFoundError(op_id)
            return op.to_dict()

    def list_operations(self) -> list[dict[str, object]]:
        """All unacked operations, oldest first."""
        self._settle()
        with self._lock:
            return [op.to_dict() for op in
                    sorted(self._ops.values(), key=lambda o: o.op_id)]

    def list_nodes(self) -> list[dict[str, object]]:
        self._settle()
        with self._lock:
            return [self._nodes[k].to_dict()
                    for k in sorted(self._nodes)]

    # -- settlement ---------------------------------------------------------
    def _settle(self) -> None:
        """Land every due create.  Joiner callbacks fire after the lock
        is released (they take the API-server lock; N004)."""
        now = self._clock()
        joined: list[CloudNode] = []
        with self._lock:
            for op in self._ops.values():
                if op.status != OP_PENDING or now < op.lands_at:
                    continue
                op.status = OP_DONE
                node = CloudNode(op.name, op.machine_class, op.zone,
                                 dict(op.labels), now)
                self._nodes[op.name] = node
                if not op.zombie:
                    joined.append(node)
        if self._joiner is not None:
            for node in joined:
                self._joiner(node)
