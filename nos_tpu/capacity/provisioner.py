"""The capacity provisioner: level-triggered reconcile of the node
fleet against demand, with stockout degradation.

Everything is re-derived every poll from three observable sources — the
API server's node/pod inventory, the cloud's operation list, and a
durable per-pool size record (a ConfigMap) — so a crash, restart or
leader failover changes nothing: the next reconcile reaches the same
conclusions from the same evidence.  No decision depends on in-memory
state surviving (timers reset to "not yet sustained", which only delays
a scale-up by one sustain window).  Deterministic node names
(``{pool}-h{idx}``) make re-issued creates collide with their earlier
selves (AlreadyExistsError) instead of duplicating hosts.

The reconcile passes, in order:

1. **Operations** — land/ack finished creates (journal
   PROVISION_LANDED, clear the `provisioning` ledger hold once the node
   is usable), reap creates past the provisioning deadline whether
   still pending (cancel) or landed-but-never-joined (**zombies**:
   the cloud says DONE, the node never appears — delete, journal
   PROVISION_FAILED).
2. **Vacancies** — ``host_index_vacancies(live, recorded_size)``
   against the durable size record, which also exposes a dead HIGHEST
   index (the blind spot docs/scheduler.md documents for the purely
   observational spare policy).  Fill preference: same-pool warm spare
   (instant) → cloud create → cross-pool borrow of a compatible spare
   when the breaker says the class/zone is stocked out.
3. **Scale-up** — sustained chip deficit (pending demand minus free
   minus already-arriving capacity) past a threshold grows the most
   heavily used pool, up to ``max_pending_creates`` in flight; on
   stockout the breaker opens and borrowing covers what it can.
4. **Spare replacement** — dead or quarantined warm spares leave the
   healthy count below target; provision replacements.
5. **Scale-down** — only the pool's HIGHEST index (preserving the
   contiguous host-index window convention), only when the fleet could
   serve all pending demand with a whole host to spare (a
   churn-transient pod must not reset the idle timer — that ratchets
   the fleet up), and the surplus has been sustained.  If the shrink
   candidate is still busy it is **cordoned** with a capacity-owned
   migration drain (drain-then-release: the scheduler's
   fragmentation-aware scoring would otherwise refill it forever);
   once empty and hold-free it is released — cloud delete first, then
   the API object, then the size record, so a crash at any point
   re-converges.  Cordons are level-triggered: any capacity cordon on
   a host that is no longer the shrink candidate is retracted the same
   poll.

The **stockout breaker** is per (machine class, zone): repeated
StockoutErrors open it (creates stop burning the rate limit against an
empty warehouse); after ``open_s`` one half-open probe create is let
through — success closes it, another stockout re-opens it for a full
window.  While open, the provisioner degrades to borrowing warm spares
across pools rather than going dark.

Every cloud call goes through jittered exponential backoff for 429s and
transient faults (the ``nos_tpu.utils.retry.sleep`` seam, so tests and
benches control time); stockouts and quota errors are never retried
inline — they are capacity states, not glitches.
"""

from __future__ import annotations

import json
import logging
import math
import random
import threading
import time
from typing import Callable, Mapping

from nos_tpu.api import constants as C
from nos_tpu.exporter.metrics import REGISTRY
from nos_tpu.kube.client import (
    APIServer, Conflict, KIND_CONFIGMAP, KIND_NODE, KIND_POD, NotFound,
    TransientAPIError,
)
from nos_tpu.kube.objects import ConfigMap, Node, ObjectMeta, PENDING, Pod
from nos_tpu.kube.resources import pod_request
from nos_tpu.obs import journal as J
from nos_tpu.obs.journal import record as journal_record
from nos_tpu.obs.ledger import (
    PROVISIONING as LEDGER_PROVISIONING, get_ledger, pod_chip_equiv,
)
from nos_tpu.partitioning.core.failure import (
    healthy_spares_by_pool, host_index_vacancies, promote_spare,
)
from nos_tpu.utils import retry as retry_mod
from nos_tpu.utils.guards import guarded_by
from nos_tpu.utils.retry import Backoff, RETRYABLE, retry_on_conflict

from .cloudapi import (
    AlreadyExistsError, CloudError, CloudNotFoundError, CloudTPUAPI,
    OP_DONE, OP_PENDING, QuotaExceededError, StockoutError,
)

logger = logging.getLogger(__name__)

REGISTRY.describe("nos_tpu_provision_requests_total",
                  "Cloud node creates requested, per pool")
REGISTRY.describe("nos_tpu_provision_landed_total",
                  "Provisioned nodes that joined and became usable")
REGISTRY.describe("nos_tpu_provision_failed_total",
                  "Provisioning attempts abandoned, per reason")
REGISTRY.describe("nos_tpu_provision_stockouts_total",
                  "Stockout errors from the cloud, per machine-class/zone")
REGISTRY.describe("nos_tpu_provision_latency_seconds",
                  "Create request to node-usable latency")
REGISTRY.describe("nos_tpu_provision_pending",
                  "Creates currently in flight (requested, not landed)")
REGISTRY.describe("nos_tpu_capacity_breakers_open",
                  "Stockout circuit breakers currently open or half-open")
REGISTRY.describe("nos_tpu_capacity_spare_borrows_total",
                  "Cross-pool spare promotions under stockout, per pool")
REGISTRY.describe("nos_tpu_capacity_scale_downs_total",
                  "Empty top-index hosts released back to the cloud")

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"

# Warm spares provisioned by the capacity plane park OUTSIDE the active
# host-index window, same convention the recovery benches use.
SPARE_PARK_BASE = 100

# Capacity-owned migration drain stamped on a busy shrink candidate so
# the scheduler stops refilling it (drain-then-release).  The owner
# segment ("capacity") keeps the other planes' stray-drain healers off
# it; _heal_cordons is the only retraction path.
CORDON_VALUE = C.migration_drain_value("capacity", "scale-down")


@guarded_by("_lock", "_streak", "_open_until", "_probing")
class StockoutBreaker:
    """Per-(machine class, zone) stockout circuit breaker.

    Closed → repeated stockouts reach `threshold` → open for `open_s` →
    half-open lets exactly ONE probe create through → success closes,
    another stockout re-opens for a full window.  Mirrors the actuation
    quarantine's streak/half-open shape (partitioning/core/quarantine)
    so operators debug one state machine, not two."""

    def __init__(self, threshold: int = 3, open_s: float = 60.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._threshold = max(1, threshold)
        self._open_s = open_s
        self._clock = clock
        self._lock = threading.Lock()
        self._streak: dict[tuple[str, str], int] = {}
        self._open_until: dict[tuple[str, str], float] = {}
        self._probing: dict[tuple[str, str], bool] = {}

    def allow(self, key: tuple[str, str]) -> bool:
        """May a create for this class/zone be attempted now?  Crossing
        an expired open window claims the single half-open probe slot."""
        now = self._clock()
        with self._lock:
            until = self._open_until.get(key)
            if until is None:
                return True
            if now < until:
                return False
            if self._probing.get(key, False):
                return False        # a probe is already in flight
            self._probing[key] = True
            return True

    def record_stockout(self, key: tuple[str, str]) -> str | None:
        """Count one stockout; returns the NEW state iff it changed
        (the caller journals transitions, not every error)."""
        now = self._clock()
        with self._lock:
            if self._probing.pop(key, False):
                # failed half-open probe: full window again
                self._open_until[key] = now + self._open_s
                return BREAKER_OPEN
            if key in self._open_until:
                return None         # already open; nothing new
            streak = self._streak.get(key, 0) + 1
            self._streak[key] = streak
            if streak >= self._threshold:
                self._open_until[key] = now + self._open_s
                return BREAKER_OPEN
            return None

    def record_success(self, key: tuple[str, str]) -> str | None:
        """A create was accepted: clear everything.  Returns "closed"
        iff the breaker was open/half-open before."""
        with self._lock:
            was_open = key in self._open_until
            self._streak.pop(key, None)
            self._open_until.pop(key, None)
            self._probing.pop(key, None)
            return BREAKER_CLOSED if was_open else None

    def state(self, key: tuple[str, str]) -> str:
        now = self._clock()
        with self._lock:
            until = self._open_until.get(key)
            if until is None:
                return BREAKER_CLOSED
            if self._probing.get(key, False) or now >= until:
                return BREAKER_HALF_OPEN
            return BREAKER_OPEN

    def snapshot(self) -> dict[str, dict[str, object]]:
        """`"class/zone" -> {state, streak, retry_in_s}` for `obs
        capacity` and the capacity report."""
        now = self._clock()
        with self._lock:
            keys = set(self._streak) | set(self._open_until)
            out: dict[str, dict[str, object]] = {}
            for key in sorted(keys):
                until = self._open_until.get(key)
                if until is None:
                    state = BREAKER_CLOSED
                elif self._probing.get(key, False) or now >= until:
                    state = BREAKER_HALF_OPEN
                else:
                    state = BREAKER_OPEN
                out["/".join(key)] = {
                    "state": state,
                    "streak": self._streak.get(key, self._threshold
                                               if until is not None else 0),
                    "retry_in_s": max(0.0, (until or now) - now),
                }
            return out

    def open_count(self) -> int:
        with self._lock:
            return len(self._open_until)


class PoolState:
    """One pool's observed inventory for a single reconcile pass."""

    __slots__ = ("name", "machine_class", "zone", "chips_per_host",
                 "active", "spares", "free_chips", "held")

    def __init__(self, name: str) -> None:
        self.name = name
        self.machine_class = ""
        self.zone = "-"
        self.chips_per_host = 0.0
        self.active: dict[int, str] = {}
        self.spares: list[str] = []
        self.free_chips = 0.0
        self.held: set[str] = set()


class _Inflight:
    """Creates requested but not yet usable, per reconcile pass."""

    __slots__ = ("names", "count", "chips", "spares_by_pool", "pending")

    def __init__(self) -> None:
        self.names: set[str] = set()
        self.count = 0
        self.chips = 0.0
        self.spares_by_pool: dict[str, int] = {}
        self.pending: list[dict[str, object]] = []


@guarded_by("_lock", "_deficit_since", "_surplus_since", "_last_scale_up",
            "_last_scale_down", "_vacancy_since", "_quota_until",
            "_counters", "_report")
class CapacityProvisioner:
    """See the module docstring for the reconcile model."""

    def __init__(self, api: APIServer, cloud: CloudTPUAPI, *,
                 scale_up_deficit_chips: float = 8.0,
                 scale_up_after_s: float = 6.0,
                 scale_up_cooldown_s: float = 15.0,
                 max_pending_creates: int = 4,
                 scale_down_idle_s: float = 120.0,
                 scale_down_cooldown_s: float = 60.0,
                 min_hosts_per_pool: int = 1,
                 provision_deadline_s: float = 120.0,
                 join_grace_s: float = 10.0,
                 vacancy_grace_s: float = 4.0,
                 breaker_threshold: int = 3,
                 breaker_open_s: float = 60.0,
                 spare_target_per_pool: int = 0,
                 inventory_configmap: str = "nos-tpu-capacity-inventory",
                 inventory_namespace: str = "nos-tpu-system",
                 chips_per_host_cap: float = 8.0,
                 hbm_gb_per_chip: float = 16.0,
                 cloud_attempts: int = 4,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self._api = api
        self._cloud = cloud
        self._clock = clock
        self._scale_up_deficit_chips = scale_up_deficit_chips
        self._scale_up_after_s = scale_up_after_s
        self._scale_up_cooldown_s = scale_up_cooldown_s
        self._max_pending_creates = max_pending_creates
        self._scale_down_idle_s = scale_down_idle_s
        self._scale_down_cooldown_s = scale_down_cooldown_s
        self._min_hosts_per_pool = min_hosts_per_pool
        self._provision_deadline_s = provision_deadline_s
        self._join_grace_s = join_grace_s
        self._vacancy_grace_s = vacancy_grace_s
        self._spare_target_per_pool = spare_target_per_pool
        self._inventory_cm = inventory_configmap
        self._inventory_ns = inventory_namespace
        self._chip_cap = chips_per_host_cap
        self._hbm_gb_per_chip = hbm_gb_per_chip
        self._cloud_attempts = max(1, cloud_attempts)
        self.breaker = StockoutBreaker(breaker_threshold, breaker_open_s,
                                       clock)
        # jitter source for cloud-call backoff: seeded so a chaos seed
        # reproduces the same retry schedule (noslint N002 spirit — no
        # wall-clock or global-rng dependence in the decision path)
        self._retry_rng = random.Random(0xCA9AC17)
        self._lock = threading.Lock()
        self._deficit_since: float | None = None
        self._surplus_since: dict[str, float] = {}
        self._last_scale_up = float("-inf")
        self._last_scale_down = float("-inf")
        self._vacancy_since: dict[tuple[str, int], float] = {}
        self._quota_until = float("-inf")
        self._counters: dict[str, int] = {
            "requested": 0, "landed": 0, "failed": 0, "stockouts": 0,
            "borrows": 0, "scale_downs": 0, "zombie_reaps": 0,
            "orphan_reaps": 0, "cordons": 0,
        }
        self._report: dict[str, object] = {"pools": {}, "breakers": {},
                                           "pending_creates": []}

    # -- cloud call wrapper -------------------------------------------------
    def _call_cloud(self, what: str, fn: Callable[[], object]) -> object:
        """429s/transients get jittered exponential backoff through the
        `nos_tpu.utils.retry.sleep` seam; capacity errors (stockout,
        quota) propagate untouched on the first throw."""
        backoff = Backoff(base_s=0.2, cap_s=5.0, rng=self._retry_rng)
        attempt = 0
        while True:
            try:
                return fn()
            except TransientAPIError:
                attempt += 1
                if attempt >= self._cloud_attempts:
                    raise
                retry_mod.sleep(backoff.next_delay())

    # -- the reconcile ------------------------------------------------------
    def reconcile(self) -> None:
        """One level-triggered pass.  Never raises: a cloud or apiserver
        failure logs, skips the dependent pass, and the next poll
        retries from scratch."""
        now = self._clock()
        try:
            ops_obj = self._call_cloud("list-operations",
                                       self._cloud.list_operations)
        except (CloudError, TransientAPIError):
            logger.warning("capacity: cloud operation list unavailable; "
                           "skipping reconcile")
            return
        ops = list(ops_obj) if isinstance(ops_obj, list) else []
        nodes = {n.metadata.name: n for n in self._api.list(KIND_NODE)}
        holds = get_ledger().holds()
        pools, pending_chips, pods_by_node = self._observe(nodes, holds)
        inventory, loaded = self._load_inventory(pools)
        inflight = self._process_operations(ops, nodes, pods_by_node, now)
        self._reap_orphans(nodes, inflight, now)
        self._fill_vacancies(pools, inventory, nodes, inflight, now)
        self._scale_up(pools, inventory, inflight, pending_chips, now)
        self._replace_spares(pools, inventory, nodes, inflight, now)
        self._scale_down(pools, inventory, holds, pods_by_node, nodes,
                         pending_chips, now)
        self._store_inventory(inventory, loaded)
        self._publish(pools, inventory, inflight, pending_chips, now)

    # -- observation --------------------------------------------------------
    def _observe(self, nodes: Mapping[str, Node],
                 holds: Mapping[str, Mapping[str, Mapping[str, object]]],
                 ) -> tuple[dict[str, PoolState], float,
                            dict[str, list[Pod]]]:
        pods_by_node: dict[str, list[Pod]] = {}
        pending_chips = 0.0
        for pod in self._api.list(KIND_POD):
            if pod.spec.node_name:
                pods_by_node.setdefault(pod.spec.node_name, []).append(pod)
            elif pod.status.phase == PENDING:
                pending_chips += pod_chip_equiv(
                    pod_request(pod), self._chip_cap, self._hbm_gb_per_chip)

        pools: dict[str, PoolState] = {}
        spares = healthy_spares_by_pool(nodes)
        for name, node in nodes.items():
            labels = node.metadata.labels
            pool = labels.get(C.LABEL_POD_ID, "")
            if not pool or C.LABEL_ACCELERATOR not in labels:
                continue
            st = pools.setdefault(pool, PoolState(pool))
            st.machine_class = labels.get(C.LABEL_ACCELERATOR, "")
            st.zone = labels.get(C.LABEL_ZONE, "-")
            chips = float(labels.get(C.LABEL_CHIP_COUNT, "0") or "0")
            st.chips_per_host = max(st.chips_per_host, chips)
            if C.LABEL_SPARE in labels:
                continue            # healthy spares collected below
            try:
                idx = int(labels.get(C.LABEL_HOST_INDEX, ""))
            except ValueError:
                continue
            st.active[idx] = name
            if self._disqualifying_hold(holds, name):
                st.held.add(name)
                continue            # held chips are never free supply
            used = sum(pod_chip_equiv(pod_request(p), chips,
                                      self._hbm_gb_per_chip)
                       for p in pods_by_node.get(name, ()))
            st.free_chips += max(0.0, chips - used)
        for pool, names in spares.items():
            if pool in pools:
                # a spare under a quarantine/drain-class hold is not
                # promotable — and not counted toward the healthy
                # target, so replacement provisioning kicks in
                pools[pool].spares = [
                    n for n in names
                    if not self._disqualifying_hold(holds, n)]
        return pools, pending_chips, pods_by_node

    @staticmethod
    def _disqualifying_hold(
            holds: Mapping[str, Mapping[str, Mapping[str, object]]],
            name: str) -> bool:
        """A PROVISIONING hold alone does not disqualify a node that has
        already joined: the holds snapshot is taken before this pass's
        operation processing clears landed holds, so a host landing this
        very poll still carries one.  Treating it as quarantine-class
        would double-provision for one poll (the landed node is invisible
        as supply while its in-flight op is already acked)."""
        return bool(set(holds.get(name, ())) - {LEDGER_PROVISIONING})

    # -- durable inventory --------------------------------------------------
    def _load_inventory(self, pools: Mapping[str, PoolState],
                        ) -> tuple[dict[str, int], dict[str, int]]:
        """Recorded pool sizes; unknown pools are seeded from the live
        window (``max(live)+1`` — all one snapshot can prove).  Returns
        (working copy, loaded snapshot) so the store step only writes on
        change."""
        recorded: dict[str, int] = {}
        cm = self._api.try_get(KIND_CONFIGMAP, self._inventory_cm,
                               self._inventory_ns)
        if cm is not None:
            try:
                raw = json.loads(cm.data.get("pools", "{}"))
                recorded = {str(k): int(v) for k, v in raw.items()}
            except (ValueError, TypeError, AttributeError):
                logger.warning("capacity: inventory configmap %s/%s is "
                               "unparseable; reseeding from observation",
                               self._inventory_ns, self._inventory_cm)
        loaded = dict(recorded)
        for pool, st in pools.items():
            if pool not in recorded and st.active:
                recorded[pool] = max(st.active) + 1
        return recorded, loaded

    def _store_inventory(self, inventory: dict[str, int],
                         loaded: dict[str, int]) -> None:
        if inventory == loaded:
            return
        payload = json.dumps(inventory, sort_keys=True)

        def mutate(cm: ConfigMap) -> None:
            cm.data["pools"] = payload

        try:
            retry_on_conflict(self._api, KIND_CONFIGMAP, self._inventory_cm,
                              mutate, self._inventory_ns,
                              component="capacity-inventory")
        except NotFound:
            cm = ConfigMap(metadata=ObjectMeta(name=self._inventory_cm,
                                               namespace=self._inventory_ns),
                           data={"pools": payload})
            try:
                self._api.create(KIND_CONFIGMAP, cm)
            except Conflict:
                pass        # racing leader wrote it; next poll merges
        except RETRYABLE:
            logger.warning("capacity: inventory write failed after "
                           "retries; next reconcile re-derives and "
                           "re-writes")

    # -- operation lifecycle ------------------------------------------------
    def _process_operations(self, ops: list[dict[str, object]],
                            nodes: Mapping[str, Node],
                            pods_by_node: Mapping[str, list[Pod]],
                            now: float) -> _Inflight:
        inflight = _Inflight()
        for op in ops:
            op_id = str(op["op_id"])
            name = str(op["name"])
            status = str(op["status"])
            labels_obj = op.get("labels")
            labels: dict[str, str] = (dict(labels_obj)
                                      if isinstance(labels_obj, dict) else {})
            pool = labels.get(C.LABEL_POD_ID, "")
            created_at = float(op.get("created_at", now) or now)
            age = now - created_at
            if status == OP_DONE and name in nodes:
                if self._node_usable(nodes[name], pods_by_node, created_at,
                                     now):
                    self._landed(op_id, name, pool, op, age)
                    continue
                self._track_inflight(inflight, name, pool, labels, op, now)
            elif status == OP_DONE:
                # landed in the cloud, never joined: a zombie once past
                # the provisioning deadline
                if age > self._provision_deadline_s:
                    self._reap(op_id, name, pool, "zombie", now)
                else:
                    self._track_inflight(inflight, name, pool, labels, op,
                                         now)
            elif status == OP_PENDING:
                if age > self._provision_deadline_s:
                    self._reap(op_id, name, pool, "deadline", now)
                else:
                    self._track_inflight(inflight, name, pool, labels, op,
                                         now)
            else:
                # FAILED (a cancel we crashed before acking): close out
                self._failed(op_id, name, pool,
                             str(op.get("error", "")) or "failed")
        return inflight

    def _node_usable(self, node: Node, pods_by_node: Mapping[str, list[Pod]],
                     created_at: float, now: float) -> bool:
        """Usable = the agent reported geometry, or it already hosts a
        resident, or the join grace elapsed (an agentless test node)."""
        name = node.metadata.name
        if any(k.startswith(C.ANNOT_STATUS_PREFIX)
               for k in node.metadata.annotations):
            return True
        if pods_by_node.get(name):
            return True
        return (now - created_at) > (self._provision_deadline_s
                                     + self._join_grace_s)

    def _track_inflight(self, inflight: _Inflight, name: str, pool: str,
                        labels: Mapping[str, str], op: dict[str, object],
                        now: float) -> None:
        inflight.names.add(name)
        inflight.count += 1
        chips = float(labels.get(C.LABEL_CHIP_COUNT, "0") or "0")
        if C.LABEL_SPARE in labels:
            inflight.spares_by_pool[pool] = (
                inflight.spares_by_pool.get(pool, 0) + 1)
        else:
            inflight.chips += chips
        inflight.pending.append({
            "name": name, "pool": pool,
            "machine_class": str(op.get("machine_class", "")),
            "zone": str(op.get("zone", "-")),
            "age_s": round(now - float(op.get("created_at", now) or now), 3),
            "status": str(op.get("status", "")),
        })

    def _landed(self, op_id: str, name: str, pool: str,
                op: dict[str, object], age: float) -> None:
        get_ledger().clear_hold(name, LEDGER_PROVISIONING,
                                owner="provisioner")
        journal_record(J.PROVISION_LANDED, name, pool=pool,
                       machine_class=str(op.get("machine_class", "")),
                       zone=str(op.get("zone", "-")),
                       latency_s=round(age, 3))
        REGISTRY.inc("nos_tpu_provision_landed_total",
                     labels={"pool": pool})
        REGISTRY.observe("nos_tpu_provision_latency_seconds", age)
        self._count("landed")
        self._cloud.ack_operation(op_id)

    def _reap(self, op_id: str, name: str, pool: str, reason: str,
              now: float) -> None:
        try:
            self._call_cloud("delete",
                             lambda: self._cloud.delete_node(name))
        except CloudNotFoundError:
            pass
        except (CloudError, TransientAPIError):
            logger.warning("capacity: reap of %s (%s) failed; next poll "
                           "retries", name, reason)
            return              # keep the op; retry next reconcile
        get_ledger().clear_hold(name, LEDGER_PROVISIONING,
                                owner="provisioner")
        journal_record(J.PROVISION_FAILED, name, pool=pool, reason=reason)
        REGISTRY.inc("nos_tpu_provision_failed_total",
                     labels={"reason": reason})
        self._count("failed")
        if reason == "zombie":
            self._count("zombie_reaps")
        self._cloud.ack_operation(op_id)

    def _reap_orphans(self, nodes: Mapping[str, Node],
                      inflight: _Inflight, now: float) -> None:
        """Delete cloud nodes whose kube node vanished AFTER the create
        op was acked (out-of-band node deletion, a host that died
        post-join).  Without this the name is wedged: every re-create of
        the vacant slot hits AlreadyExists against the stale cloud
        record.  A node still covered by an unacked op is in-flight, not
        an orphan; fresh landings get the same deadline+grace the join
        path gets before we declare them gone."""
        try:
            cloud_nodes = self._call_cloud("list-nodes",
                                           self._cloud.list_nodes)
        except (CloudError, TransientAPIError):
            logger.warning("capacity: cloud node list unavailable; "
                           "skipping orphan reaping")
            return
        for cn in cloud_nodes:
            name = str(cn["name"])
            if name in nodes or name in inflight.names:
                continue
            age = now - float(cn.get("created_at", now))
            if age <= self._provision_deadline_s + self._join_grace_s:
                continue
            try:
                self._call_cloud("delete",
                                 lambda n=name: self._cloud.delete_node(n))
            except CloudNotFoundError:
                pass
            except (CloudError, TransientAPIError):
                logger.warning("capacity: orphan reap of %s failed; "
                               "next poll retries", name)
                continue
            journal_record(J.PROVISION_FAILED, name, reason="orphan")
            REGISTRY.inc("nos_tpu_provision_failed_total",
                         labels={"reason": "orphan"})
            self._count("orphan_reaps")

    def _failed(self, op_id: str, name: str, pool: str,
                reason: str) -> None:
        get_ledger().clear_hold(name, LEDGER_PROVISIONING,
                                owner="provisioner")
        journal_record(J.PROVISION_FAILED, name, pool=pool, reason=reason)
        REGISTRY.inc("nos_tpu_provision_failed_total",
                     labels={"reason": reason})
        self._count("failed")
        self._cloud.ack_operation(op_id)

    # -- vacancy closure ----------------------------------------------------
    def _fill_vacancies(self, pools: dict[str, PoolState],
                        inventory: dict[str, int],
                        nodes: Mapping[str, Node], inflight: _Inflight,
                        now: float) -> None:
        open_vacancies: set[tuple[str, int]] = set()
        for pool in sorted(pools):
            st = pools[pool]
            recorded = inventory.get(pool, 0)
            for idx in host_index_vacancies(st.active, recorded):
                name = f"{pool}-h{idx}"
                if name in nodes or name in inflight.names:
                    continue
                key = (pool, idx)
                open_vacancies.add(key)
                with self._lock:
                    since = self._vacancy_since.setdefault(key, now)
                if now - since < self._vacancy_grace_s:
                    continue    # the watching spare policy gets first claim
                if st.spares:
                    spare = st.spares.pop(0)
                    if promote_spare(self._api, spare, pool, idx,
                                     kind="capacity"):
                        open_vacancies.discard(key)
                    continue
                if self._create(st, name, idx, inflight, now, spare=False):
                    open_vacancies.discard(key)
                    continue
                if self._borrow(pools, st, idx, now):
                    open_vacancies.discard(key)
        with self._lock:
            self._vacancy_since = {k: v for k, v in
                                   self._vacancy_since.items()
                                   if k in open_vacancies}

    # -- scale-up -----------------------------------------------------------
    def _scale_up(self, pools: dict[str, PoolState],
                  inventory: dict[str, int], inflight: _Inflight,
                  pending_chips: float, now: float) -> None:
        free = sum(st.free_chips for st in pools.values())
        deficit = pending_chips - free - inflight.chips
        with self._lock:
            if deficit < self._scale_up_deficit_chips:
                self._deficit_since = None
                return
            if self._deficit_since is None:
                self._deficit_since = now
            sustained = now - self._deficit_since
            ready = (sustained >= self._scale_up_after_s
                     and now - self._last_scale_up
                     >= self._scale_up_cooldown_s
                     and now >= self._quota_until)
        if not ready or not pools:
            return
        # grow the fullest pool: demand concentrates where it fits
        target = min(pools.values(), key=lambda s: (s.free_chips, s.name))
        if target.chips_per_host <= 0:
            return
        want = math.ceil(deficit / target.chips_per_host)
        slots = self._max_pending_creates - inflight.count
        acted = False
        for _ in range(max(0, min(want, slots))):
            idx = inventory.get(target.name, 0)
            name = f"{target.name}-h{idx}"
            if self._create(target, name, idx, inflight, now, spare=False):
                inventory[target.name] = idx + 1
                acted = True
            elif self._borrow(pools, target, idx, now):
                # stocked out: a borrowed spare becomes the new index
                inventory[target.name] = idx + 1
                acted = True
            else:
                break
        if acted:
            with self._lock:
                self._last_scale_up = now
                self._deficit_since = None

    # -- warm-spare replacement ---------------------------------------------
    def _replace_spares(self, pools: dict[str, PoolState],
                        inventory: dict[str, int],
                        nodes: Mapping[str, Node], inflight: _Inflight,
                        now: float) -> None:
        if self._spare_target_per_pool <= 0:
            return
        for pool in sorted(pools):
            st = pools[pool]
            have = (len(st.spares)
                    + inflight.spares_by_pool.get(pool, 0))
            seq = 0
            while have < self._spare_target_per_pool:
                name = f"{pool}-s{seq}"
                seq += 1
                if name in nodes or name in inflight.names:
                    continue
                if not self._create(st, name, SPARE_PARK_BASE + seq,
                                    inflight, now, spare=True):
                    break       # stocked out / quota / slots exhausted
                have += 1

    # -- scale-down ---------------------------------------------------------
    def _scale_down(self, pools: dict[str, PoolState],
                    inventory: dict[str, int],
                    holds: Mapping[str, Mapping[str, Mapping[str, object]]],
                    pods_by_node: Mapping[str, list[Pod]],
                    nodes: Mapping[str, Node],
                    pending_chips: float, now: float) -> None:
        total_free = sum(st.free_chips for st in pools.values())
        live_surplus: set[str] = set()
        desired_cordons: set[str] = set()
        released = False
        for pool in sorted(pools):
            st = pools[pool]
            recorded = inventory.get(pool, 0)
            if recorded <= self._min_hosts_per_pool:
                continue
            top = recorded - 1
            name = st.active.get(top)
            if name is None:
                continue        # top index is a vacancy, not a surplus
            # surplus = the fleet can serve all pending demand AND still
            # has this whole host's worth of slack to give back.  A
            # churn-transient pod that fits the slack must NOT reset the
            # timer (every bind gap would restart the clock and the
            # surplus never drains — a ratchet); demand that genuinely
            # needs the host fails this test and blocks the release.
            if total_free < pending_chips + st.chips_per_host:
                continue        # not surplus; timer pruned below
            live_surplus.add(pool)
            with self._lock:
                since = self._surplus_since.setdefault(pool, now)
                sustained = now - since >= self._scale_down_idle_s
                ready = (sustained and now - self._last_scale_down
                         >= self._scale_down_cooldown_s)
            if not sustained:
                continue
            if pods_by_node.get(name):
                # drain-then-release: the scheduler's fragmentation-
                # aware score key can refill the top host forever (it
                # prefers hosts whose windows are already broken — and
                # the release candidate is exactly the window it churns
                # on).  Cordon it with a capacity-owned migration drain
                # (hard placement rejection, planner snapshot exclusion,
                # never healed by the other planes) and let residents
                # finish; the release happens once it is empty.
                desired_cordons.add(name)
                self._cordon(nodes, name)
                continue
            if name in holds or released or not ready:
                continue
            try:
                self._call_cloud("delete",
                                 lambda n=name: self._cloud.delete_node(n))
            except CloudNotFoundError:
                pass            # a pre-capacity host the cloud never knew
            except (CloudError, TransientAPIError):
                logger.warning("capacity: cloud release of %s failed; "
                               "next poll retries", name)
                continue
            try:
                self._api.delete(KIND_NODE, name)
            except NotFound:
                pass
            inventory[pool] = top
            journal_record(J.SCALE_DOWN, name, pool=pool, host_index=top,
                           idle_s=round(now - since, 3))
            REGISTRY.inc("nos_tpu_capacity_scale_downs_total",
                         labels={"pool": pool})
            self._count("scale_downs")
            with self._lock:
                self._last_scale_down = now
                self._surplus_since.pop(pool, None)
            released = True     # one release per poll: gentle by design
        with self._lock:
            self._surplus_since = {p: t for p, t in
                                   self._surplus_since.items()
                                   if p in live_surplus}
        self._heal_cordons(nodes, desired_cordons)

    def _cordon(self, nodes: Mapping[str, Node], name: str) -> None:
        node = nodes.get(name)
        if node is None \
                or node.metadata.annotations.get(C.ANNOT_DEFRAG_DRAIN):
            return              # gone, or another plane already drains it

        def mutate(n: Node) -> None:
            n.metadata.annotations.setdefault(C.ANNOT_DEFRAG_DRAIN,
                                              CORDON_VALUE)

        try:
            retry_on_conflict(self._api, KIND_NODE, name, mutate,
                              component="capacity-cordon")
        except NotFound:
            return
        except RETRYABLE:
            logger.warning("capacity: cordon of %s failed; next poll "
                           "retries", name)
            return
        self._count("cordons")

    def _heal_cordons(self, nodes: Mapping[str, Node],
                      desired: set[str]) -> None:
        """Level-triggered retraction: any capacity-owned cordon on a
        host that is no longer the shrink candidate (demand returned,
        the pool shrank past it, a predecessor died mid-shrink) is
        retracted this poll — a stray cordon must never deprioritize a
        healthy host forever."""
        for name, node in nodes.items():
            if name in desired:
                continue
            if node.metadata.annotations.get(C.ANNOT_DEFRAG_DRAIN) \
                    != CORDON_VALUE:
                continue

            def mutate(n: Node) -> None:
                if n.metadata.annotations.get(C.ANNOT_DEFRAG_DRAIN) \
                        == CORDON_VALUE:
                    n.metadata.annotations.pop(C.ANNOT_DEFRAG_DRAIN)

            try:
                retry_on_conflict(self._api, KIND_NODE, name, mutate,
                                  component="capacity-cordon")
            except NotFound:
                pass
            except RETRYABLE:
                logger.warning("capacity: cordon retraction on %s "
                               "failed; next poll retries", name)

    # -- create / borrow primitives -----------------------------------------
    def _create(self, st: PoolState, name: str, idx: int,
                inflight: _Inflight, now: float, *, spare: bool) -> bool:
        """One cloud create, breaker-gated.  True iff the request was
        accepted (or already in flight from a previous incarnation)."""
        if inflight.count >= self._max_pending_creates:
            return False
        key = (st.machine_class, st.zone)
        if not self.breaker.allow(key):
            return False
        labels = {
            C.LABEL_ACCELERATOR: st.machine_class,
            C.LABEL_POD_ID: st.name,
            C.LABEL_HOST_INDEX: str(idx),
            C.LABEL_CHIP_COUNT: str(int(st.chips_per_host or
                                        self._chip_cap)),
            C.LABEL_ZONE: st.zone,
        }
        if spare:
            labels[C.LABEL_SPARE] = C.SPARE_WARM
        try:
            op_obj = self._call_cloud(
                "create", lambda: self._cloud.create_node(
                    name, st.machine_class, st.zone, labels))
        except AlreadyExistsError:
            return True         # our earlier incarnation asked already
        except StockoutError:
            self._count("stockouts")
            REGISTRY.inc("nos_tpu_provision_stockouts_total",
                         labels={"key": "/".join(key)})
            transition = self.breaker.record_stockout(key)
            if transition is not None:
                journal_record(J.PROVISION_STOCKOUT, "/".join(key),
                               state=transition, pool=st.name)
            journal_record(J.PROVISION_FAILED, name, pool=st.name,
                           reason="stockout")
            REGISTRY.inc("nos_tpu_provision_failed_total",
                         labels={"reason": "stockout"})
            self._count("failed")
            return False
        except QuotaExceededError:
            journal_record(J.PROVISION_FAILED, name, pool=st.name,
                           reason="quota")
            REGISTRY.inc("nos_tpu_provision_failed_total",
                         labels={"reason": "quota"})
            self._count("failed")
            with self._lock:
                self._quota_until = now + self._scale_up_cooldown_s
            return False
        except (CloudError, TransientAPIError):
            logger.warning("capacity: create of %s failed after retries",
                           name)
            return False
        transition = self.breaker.record_success(key)
        if transition is not None:
            journal_record(J.PROVISION_STOCKOUT, "/".join(key),
                           state=transition, pool=st.name)
        op_id = str(op_obj)
        journal_record(J.PROVISION_REQUESTED, name, pool=st.name,
                       machine_class=st.machine_class, zone=st.zone,
                       host_index=idx, op=op_id, spare=spare)
        REGISTRY.inc("nos_tpu_provision_requests_total",
                     labels={"pool": st.name})
        self._count("requested")
        get_ledger().set_hold(name, LEDGER_PROVISIONING,
                              owner="provisioner", pool=st.name,
                              machine_class=st.machine_class, zone=st.zone,
                              op=op_id)
        self._track_inflight(inflight, name, st.name, labels,
                             {"op_id": op_id, "name": name,
                              "machine_class": st.machine_class,
                              "zone": st.zone, "status": OP_PENDING,
                              "created_at": now}, now)
        return True

    def _borrow(self, pools: dict[str, PoolState], target: PoolState,
                idx: int, now: float) -> bool:
        """Cross-pool degradation: promote a compatible (same machine
        class) warm spare from a sibling pool into the target's index.
        Last resort — it spends another pool's recovery headroom."""
        for other in sorted(pools):
            st = pools[other]
            if other == target.name:
                continue
            if st.machine_class != target.machine_class:
                continue
            while st.spares:
                spare = st.spares.pop(0)
                if promote_spare(self._api, spare, target.name, idx,
                                 kind="capacity", cross_pool=True):
                    REGISTRY.inc("nos_tpu_capacity_spare_borrows_total",
                                 labels={"pool": target.name})
                    self._count("borrows")
                    return True
        return False

    # -- reporting ----------------------------------------------------------
    def _count(self, key: str) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + 1

    def _publish(self, pools: dict[str, PoolState],
                 inventory: dict[str, int], inflight: _Inflight,
                 pending_chips: float, now: float) -> None:
        breakers = self.breaker.snapshot()
        pool_rows = {
            pool: {
                "recorded_size": inventory.get(pool, 0),
                "active": len(st.active),
                "spares": len(st.spares),
                "machine_class": st.machine_class,
                "zone": st.zone,
                "chips_per_host": st.chips_per_host,
                "free_chips": round(st.free_chips, 3),
                "held": sorted(st.held),
            }
            for pool, st in sorted(pools.items())
        }
        free = sum(st.free_chips for st in pools.values())
        with self._lock:
            counters = dict(self._counters)
            self._report = {
                "pools": pool_rows,
                "breakers": breakers,
                "pending_creates": list(inflight.pending),
                "pending_demand_chips": round(pending_chips, 3),
                "free_chips": round(free, 3),
                "arriving_chips": round(inflight.chips, 3),
                "deficit_chips": round(
                    pending_chips - free - inflight.chips, 3),
                "counters": counters,
            }
        REGISTRY.set("nos_tpu_provision_pending", float(inflight.count))
        REGISTRY.set("nos_tpu_capacity_breakers_open",
                     float(self.breaker.open_count()))

    def report(self) -> dict[str, object]:
        """The `obs capacity` surface: last reconcile's view — pools,
        breakers, in-flight creates, demand/supply balance, counters."""
        with self._lock:
            return dict(self._report)
