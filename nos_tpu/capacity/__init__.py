"""Cloud capacity plane: the provisioner that grows, shrinks and heals
the TPU node fleet against a (simulated) cloud node-pool API.

Everything above this package assumes the set of hosts is whatever the
API server says it is; this package is the only place that *changes*
that set.  Two halves:

- ``cloudapi``  — the provider model: an async create/delete node-pool
  API with operations that land after a provisioning delay, plus the
  fault seams (stockout, quota, 429, slow, zombie, failed delete) that
  ``nos_tpu.testing.chaos.ChaosCloudTPUAPI`` overrides.
- ``provisioner`` — the level-triggered reconcile controller: scale-up
  on sustained pending demand, scale-down of drained empty hosts, warm
  spare replacement, per-(machine class, zone) stockout circuit breaker
  with cross-pool spare borrowing, and provisioning-deadline reaping of
  zombies.  Crash-safe: desired state is re-derived every poll from the
  observed inventory plus a durable pool-size record.

Off means off: with ``ProvisionerConfig.enabled`` false (the default)
none of this is constructed and the decision journal is byte-identical
to a build without the plane (bench_capacity.py proves it).
"""

from .cloudapi import (
    AlreadyExistsError,
    CloudError,
    CloudNotFoundError,
    CloudTPUAPI,
    DeleteFailedError,
    QuotaExceededError,
    RateLimitedError,
    StockoutError,
)
from .provisioner import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CapacityProvisioner,
    StockoutBreaker,
)

__all__ = [
    "AlreadyExistsError",
    "BREAKER_CLOSED",
    "BREAKER_HALF_OPEN",
    "BREAKER_OPEN",
    "CapacityProvisioner",
    "CloudError",
    "CloudNotFoundError",
    "CloudTPUAPI",
    "DeleteFailedError",
    "QuotaExceededError",
    "RateLimitedError",
    "StockoutError",
    "StockoutBreaker",
]
