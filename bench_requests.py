"""Request-path bench: the inference data plane end to end on the
serving control plane (docs/serving.md, "The request path").

Builds on bench_serving's cluster (12 slice + 2 timeshare v5e hosts,
real scheduler/partitioners/agents/quota, batch + best-effort filler
soaking idle chips) and replaces the aggregate requests-in-flight
annotation stamp with the REAL request path (nos_tpu/requests):

    chat    prefill/decode DISAGGREGATED — chat-prefill on 1x2 slices
            (compute for prompt processing), chat-decode on 1x1 slices
            (KV-heavy MHA model, ~8k KV tokens per replica); sessions
            sticky to their decode replica
    embed   aggregated on 8gb timeshare replicas; prompt-only requests
            complete at prefill

Requests are individual seeded arrivals (sim ArrivalSource thinning a
DiurnalTrace rate — bursty, peak-hour millions of users compressed
onto the bench clock); the router places them by KV occupancy with
session affinity, sheds-with-retry on saturation, and publishes each
replica's occupancy through ANNOT_SERVING_LOAD so the replica
autoscaler scales on KV PRESSURE (target ~0.55 reserved) instead of a
requests-in-flight estimate.

Falsifiable invariants:

  - per-request p99 (phase=total) < 10 s at peak diurnal load, judged
    by the SLO engine next to schedule latency;
  - ZERO serving preemption victims while requests flow;
  - KV-pressure autoscaling holds mean decode occupancy under the 0.9
    ceiling for >= 90% of post-warmup samples through bursts;
  - the router-saturation curve (offered load vs goodput/p99/shed on
    fixed replicas) shows goodput plateau at capacity, not collapse;
  - OFF MEANS OFF: a router-disabled run journals the byte-identical
    decision sequence of plain bench_serving (check_byte_identity —
    bench_serving's smoke asserts it).

Time is virtual; one seed's shortened trace is the CI gate (--smoke).
"""

from __future__ import annotations

import argparse
import contextlib
import math
import random
import time

import bench_serving as bs
from nos_tpu.api import constants as C
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD
from nos_tpu.kube.objects import PENDING, RUNNING
from nos_tpu.obs import scoped as obs_scoped
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.slo import LATENCY, SLOEngine, SLOObjective
from nos_tpu.obs.timeseries import TimeSeriesSampler
from nos_tpu.requests import (
    ModelProfile, Request, RequestCostModel, RouterService, ServingRouter,
)
from nos_tpu.serving import ReplicaAutoscaler, ServingService, replica_load
from nos_tpu.serving.trace import DiurnalTrace
from nos_tpu.sim import ArrivalSource, SimEngine, emit, write_report
from nos_tpu.testing.factory import make_pod, make_tpu_node

REQUEST_P99_TARGET_S = 10.0
REQUEST_MIN_EVENTS = 5
OCC_CEILING = 0.9           # fleet-mean decode occupancy the
OCC_WITHIN = 0.9            # autoscaler must hold >= this fraction
LOAD_SCALE = 1.0

# Chat: deliberately KV-heavy (full MHA, no GQA — every head caches)
# so decode replicas hold ~8k KV tokens (~20 mid-size streams) and KV
# pressure, not request count, is the binding constraint.  Weights at
# 12 GB leave 4 GB of KV on a 16 GB 1x1 replica.
CHAT_MODEL = ModelProfile(
    name="chat-7b-mha", num_layers=32, num_heads=32, num_kv_heads=32,
    head_dim=128, intermediate_size=14336, weights_gb=12.0)
# Embed: small encoder, prompt-only (output_tokens=1 completes at
# prefill), served aggregated from 8gb timeshare replicas.
EMBED_MODEL = ModelProfile(
    name="embed-1b", num_layers=12, num_heads=16, num_kv_heads=16,
    head_dim=64, intermediate_size=4096, weights_gb=2.0)

ROUTER_SERVICES = (
    RouterService(
        name="chat", namespace="serve",
        prefill_service="chat-prefill", decode_service="chat-decode",
        model=CHAT_MODEL,
        prefill_costs=RequestCostModel(
            profile=CHAT_MODEL, device_kind="v5e", chips=2,
            hbm_gb=16.0, mfu=0.4),
        decode_costs=RequestCostModel(
            profile=CHAT_MODEL, device_kind="v5e", chips=1,
            hbm_gb=16.0),
        # the retry ladder (0.3 + 0.6 + ... = 4.5 s) must outlast a
        # KV-pressure scale-up round trip (publish -> reconcile ->
        # schedule -> admit), or bursts shed work the fleet could
        # absorb two seconds later
        max_queue_per_replica=16, max_retries=5, retry_backoff_s=0.3,
        session_idle_s=30.0),
    RouterService(
        name="embed", namespace="serve", model=EMBED_MODEL,
        prefill_costs=RequestCostModel(
            profile=EMBED_MODEL, device_kind="v5e", chips=1,
            hbm_gb=8.0, mfu=0.2, hbm_efficiency=0.4),
        max_queue_per_replica=32, max_retries=3, retry_backoff_s=0.25,
        session_idle_s=20.0),
)

# Per-role ServingService entries: the disaggregation maps to DIFFERENT
# slice shapes — prefill gets 1x2 (compute), decode 1x1 (KV).  The
# autoscaler target is the published load signal's setpoint: ~0.55
# reserved-KV fraction for decode/aggregated pools, ~0.5 queue
# saturation for the prefill pool.
ROUTER_AUTOSCALED = (
    ServingService(name="chat-prefill", namespace="serve",
                   slice_shape="1x2", min_replicas=1, max_replicas=3,
                   target_load_per_replica=0.5,
                   scale_up_cooldown_s=0.2, scale_down_cooldown_s=10.0,
                   down_hysteresis=0.2),
    ServingService(name="chat-decode", namespace="serve",
                   slice_shape="1x1", min_replicas=2, max_replicas=12,
                   target_load_per_replica=0.55,
                   scale_up_cooldown_s=0.2, scale_down_cooldown_s=10.0,
                   down_hysteresis=0.2),
    ServingService(name="embed", namespace="serve", timeshare_gb=8,
                   min_replicas=1, max_replicas=8,
                   target_load_per_replica=0.55,
                   scale_up_cooldown_s=0.2, scale_down_cooldown_s=12.0,
                   down_hysteresis=0.2),
)

# request shape draws (per-service RNGs, consumed only inside
# engine-ordered arrival callbacks — deterministic per seed)
CHAT_PROMPT = (64, 512)
CHAT_OUTPUT = (16, 96)
CHAT_SESSIONS = 600
EMBED_PROMPT = (32, 256)
# Little's-law divisor turning the trace's requests-in-flight into an
# arrival rate; must match the service_time_s each trace was built with.
SERVICE_TIME_S = {"chat": 0.5, "embed": 1.0}


def request_traces(seed: int) -> dict[str, DiurnalTrace]:
    """Arrival-rate curves (requests/s = load_at/service_time), sized
    so steady peak wants ~6 decode replicas and 3x bursts push the
    band toward its max — the autoscaler has real work."""
    return {
        "serve/chat": DiurnalTrace(
            seed=seed * 11 + 3, period_s=120.0,
            base_users=150_000.0, peak_users=900_000.0,
            requests_per_user_per_s=2e-5, service_time_s=0.5,
            burst_rate_per_s=1.0 / 40.0, burst_multiplier=3.0,
            burst_duration_s=8.0),
        "serve/embed": DiurnalTrace(
            seed=seed * 11 + 4, period_s=150.0, phase_s=60.0,
            base_users=800_000.0, peak_users=4_800_000.0,
            requests_per_user_per_s=1e-5, service_time_s=1.0,
            burst_rate_per_s=1.0 / 55.0, burst_multiplier=2.5,
            burst_duration_s=10.0),
    }


def slo_objectives() -> list[SLOObjective]:
    return bs.slo_objectives() + [
        SLOObjective(name="request-latency", kind=LATENCY,
                     metric="nos_tpu_request_latency_seconds",
                     target=REQUEST_P99_TARGET_S,
                     labels={"phase": "total"}, each_label="service",
                     compliance=0.99, quantile=0.99,
                     min_events=REQUEST_MIN_EVENTS),
    ]


class Sim(bs.Sim):
    """bench_serving's cluster with the request data plane on top.
    ``router_enabled=False`` constructs the parent UNCHANGED — every
    override delegates immediately, so the journal is byte-identical
    to plain bench_serving (check_byte_identity pins it)."""

    def __init__(self, seed: int = 0, *, router_enabled: bool = True,
                 load_scale: float = LOAD_SCALE) -> None:
        super().__init__(seed)
        self.router: ServingRouter | None = None
        if not router_enabled:
            return
        clock = self.eng.now
        self.load_scale = load_scale
        # per-role services replace the aggregate ones end to end
        self.autoscaler = ReplicaAutoscaler(
            self.api, ROUTER_AUTOSCALED, clock=clock)
        self.replica_series = {svc.key: []
                               for svc in ROUTER_AUTOSCALED}
        self.occ_series: dict[str, list[tuple[float, float]]] = {
            svc.key: [] for svc in ROUTER_SERVICES}
        self.router = ServingRouter(
            self.api, ROUTER_SERVICES, clock=clock,
            publish_every_ticks=bs.STAMP_EVERY_TICKS,
            keep_completed=True)
        self.req_traces = request_traces(seed)
        self._req_rng = {svc.name: random.Random(seed * 1000 + i * 7)
                         for i, svc in enumerate(ROUTER_SERVICES)}
        self._rid = 0
        self.slo_engine = SLOEngine(
            TimeSeriesSampler(clock=clock, maxlen=4096),
            slo_objectives(),
            fast_window_s=bs.SLO_FAST_WINDOW_S,
            slow_window_s=bs.SLO_SLOW_WINDOW_S, clock=clock)

    # -- arrivals -----------------------------------------------------------
    def _arrive(self, svc: RouterService, t: float) -> None:
        rng = self._req_rng[svc.name]
        self._rid += 1
        if svc.name == "chat":
            req = Request("chat", f"chat-r{self._rid}",
                          f"chat-s{rng.randrange(CHAT_SESSIONS)}",
                          rng.randrange(*CHAT_PROMPT),
                          rng.randrange(*CHAT_OUTPUT), t)
        else:
            # embeds are sessionless one-shots: prompt only
            req = Request("embed", f"embed-r{self._rid}",
                          f"embed-r{self._rid}",
                          rng.randrange(*EMBED_PROMPT), 1, t)
        assert self.router is not None
        self.router.submit(svc.key, req)

    def _arrival_sources(self) -> list[ArrivalSource]:
        out = []
        for svc in ROUTER_SERVICES:
            trace = self.req_traces[svc.key]
            scale = self.load_scale / SERVICE_TIME_S[svc.name]

            def rate(t: float, trace=trace, scale=scale) -> float:
                return trace.load_at(t) * scale

            # thinning bound: the trace is a pure function of t, so a
            # coarse scan over the horizon (bursts last >= 4 s) finds
            # the true peak; the 1.05 pad keeps rate_fn strictly under
            peak = max(rate(i * 0.5)
                       for i in range(int(bs.TRACE_S * 2) + 2)) * 1.05
            out.append(ArrivalSource(
                seed=self.seed * 31 + len(out), rate_fn=rate,
                fn=(lambda t, svc=svc: self._arrive(svc, t)),
                peak_rate=peak, until=bs.TRACE_S,
                label=f"req-{svc.name}"))
        return out

    # -- overrides (router mode only; otherwise delegate) -------------------
    def _stamp_loads(self) -> None:
        if self.router is None:
            return super()._stamp_loads()
        # the router's publish loop owns the load signal

    def _record_serving_binds(self) -> None:
        if self.router is None:
            return super()._record_serving_binds()
        for svc in ROUTER_AUTOSCALED:
            for p in self.api.list(
                    KIND_POD, namespace=svc.namespace,
                    label_selector={C.LABEL_SERVICE: svc.name}):
                if not p.spec.node_name \
                        or p.metadata.name in self._serving_seen:
                    continue
                self._serving_seen.add(p.metadata.name)
                if self.eng.now() < bs.WARMUP_S:
                    continue
                self.serving_latencies.append(
                    self.eng.now() - p.metadata.creation_timestamp)

    def _track_replicas(self) -> None:
        if self.router is None:
            return super()._track_replicas()
        for svc in ROUTER_AUTOSCALED:
            pods = self.api.list(
                KIND_POD, namespace=svc.namespace,
                label_selector={C.LABEL_SERVICE: svc.name},
                filter_fn=lambda p: p.status.phase in (PENDING, RUNNING))
            load = sum(replica_load(p) for p in pods)
            desired = min(svc.max_replicas, max(
                svc.min_replicas,
                math.ceil(load / svc.target_load_per_replica)))
            self.replica_series[svc.key].append(
                (round(self.eng.now(), 2), round(load, 2), len(pods),
                 desired))
        for svc in ROUTER_SERVICES:
            occs = self.router.pool_occupancies(svc.key)
            # the KV ceiling is judged on the pool that HOLDS streams
            pool = occs.get("decode") or occs.get("prefill") or []
            if pool:
                self.occ_series[svc.key].append(
                    (round(self.eng.now(), 2),
                     round(sum(pool) / len(pool), 4)))

    def _tracking_stats(self) -> dict:
        if self.router is None:
            return super()._tracking_stats()
        out: dict[str, dict] = {}
        for svc in ROUTER_AUTOSCALED:
            rows = [r for r in self.replica_series[svc.key]
                    if r[0] >= bs.WARMUP_S]
            if not rows:
                out[svc.key] = {"samples": 0}
                continue
            within = sum(1 for _, _, live, desired in rows
                         if live >= desired - 1)
            out[svc.key] = {
                "samples": len(rows),
                "within_one": round(within / len(rows), 4),
                "replicas_min": min(r[2] for r in rows),
                "replicas_max": max(r[2] for r in rows),
            }
        return out

    def _tick(self) -> None:
        if self.router is None:
            return super()._tick()
        self._tick_no += 1
        tick = self._tick_no
        self._complete_finished()
        self._spawn()
        # the router ticks BEFORE the autoscaler so a fresh occupancy
        # stamp (its publish cadence == the old stamp cadence) is what
        # reconcile reads
        self.router.tick(bs.TICK_S)
        self.autoscaler.reconcile()
        t0 = time.perf_counter()
        self.scheduler.run_cycle()
        self.cycle_wall_ms.append((time.perf_counter() - t0) * 1e3)
        self._requeue_evicted()
        self.slice_ctl.process_if_ready()
        self.ts_ctl.process_if_ready()
        for a in list(self.agents.values()):
            a.tick()
        self.eq_reconciler.reconcile_all()
        self._record_serving_binds()
        self._record_batch_binds()
        if tick % bs.STAMP_EVERY_TICKS == 0:
            self._track_replicas()
        self._sample_utilization()
        if self.eng.now() >= bs.WARMUP_S:
            self.slo_engine.tick()

    def run(self) -> dict:
        if self.router is None:
            return super().run()
        for src in self._arrival_sources():
            src.install(self.eng)
        return super().run()

    # -- report -------------------------------------------------------------
    def _request_stats(self) -> dict:
        assert self.router is not None
        pct = bs.percentile
        out: dict[str, dict] = {}
        stats = self.router.stats()
        for svc in ROUTER_SERVICES:
            reqs = [r for r in self.router.completed_requests(svc.key)
                    if r.finished is not None
                    and r.created >= bs.WARMUP_S]
            total = [r.finished - r.created for r in reqs]
            ttft = [r.prefill_done - r.created for r in reqs
                    if r.prefill_done is not None]
            occ = [o for t, o in self.occ_series[svc.key]
                   if t >= bs.WARMUP_S]
            under = (sum(1 for o in occ if o <= OCC_CEILING) / len(occ)
                     if occ else None)
            out[svc.key] = {
                **stats[svc.key],
                "completed_post_warmup": len(reqs),
                "p50_s": pct(total, 0.50, 3),
                "p99_s": pct(total, 0.99, 3),
                "ttft_p99_s": pct(ttft, 0.99, 3),
                "occupancy_mean_max": (round(max(occ), 4) if occ
                                       else None),
                "occupancy_under_ceiling": (round(under, 4)
                                            if under is not None
                                            else None),
            }
        return out

    def _report(self) -> dict:
        out = super()._report()
        if self.router is not None:
            out["requests"] = self._request_stats()
            out["request_p99_target_s"] = REQUEST_P99_TARGET_S
        return out


# -- router-saturation curve -------------------------------------------------
# Fixed replica fleet, flat offered rate per point: the curve isolates
# ROUTER + replica capacity (goodput plateau, p99 blow-up, shed onset)
# from the autoscaler, which the main trace exercises.
CURVE_DECODE_REPLICAS = 6
CURVE_PREFILL_REPLICAS = 2
CURVE_BASE_RPS = 30.0
CURVE_TICK_S = 0.05


def saturation_point(seed: int, scale: float,
                     trace_s: float = 60.0) -> dict:
    eng = SimEngine()
    api = APIServer()
    api.create(KIND_NODE, make_tpu_node("curve-host", pod_id="pod-0"))
    chat = ROUTER_SERVICES[0]
    for i in range(CURVE_PREFILL_REPLICAS):
        api.create(KIND_POD, make_pod(
            name=f"chat-prefill-{i}", namespace="serve", phase=RUNNING,
            node_name="curve-host",
            labels={C.LABEL_SERVICE: chat.prefill_label,
                    C.LABEL_TIER: C.TIER_SERVING}))
    for i in range(CURVE_DECODE_REPLICAS):
        api.create(KIND_POD, make_pod(
            name=f"chat-decode-{i}", namespace="serve", phase=RUNNING,
            node_name="curve-host",
            labels={C.LABEL_SERVICE: chat.decode_service,
                    C.LABEL_TIER: C.TIER_SERVING}))
    router = ServingRouter(api, (chat,), clock=eng.now,
                           keep_completed=True)
    rng = random.Random(seed * 997 + 13)
    rid = [0]

    def arrive(t: float) -> None:
        rid[0] += 1
        router.submit("serve/chat", Request(
            "chat", f"r{rid[0]}", f"s{rng.randrange(CHAT_SESSIONS)}",
            rng.randrange(*CHAT_PROMPT), rng.randrange(*CHAT_OUTPUT),
            t))

    rate = CURVE_BASE_RPS * scale
    ArrivalSource(seed=seed * 53 + 1, rate_fn=lambda t: rate,
                  fn=arrive, peak_rate=rate * 1.01, until=trace_s,
                  label="curve-req").install(eng)
    eng.tick_loop(CURVE_TICK_S, lambda: router.tick(CURVE_TICK_S),
                  until=trace_s, label="router-tick")
    eng.run()
    stats = router.stats()["serve/chat"]
    lats = [r.finished - r.created
            for r in router.completed_requests("serve/chat")
            if r.finished is not None]
    return {
        "load_scale": scale,
        "offered_rps": round(stats["submitted"] / trace_s, 2),
        "goodput_rps": round(stats["completed"] / trace_s, 2),
        "shed": stats["shed"],
        "retried": stats["retried"],
        "p50_s": bs.percentile(lats, 0.50, 3),
        "p99_s": bs.percentile(lats, 0.99, 3),
    }


def saturation_curve(seed: int = 0,
                     scales=(0.5, 1.0, 1.5, 2.0, 3.0)) -> list[dict]:
    return [saturation_point(seed, s) for s in scales]


# -- off means off -----------------------------------------------------------
@contextlib.contextmanager
def _short_trace(trace_s: float, warmup_s: float):
    """Temporarily shorten bench_serving's module-global trace (the
    run_smoke pattern, reused for byte-identity and smoke runs)."""
    prev = (bs.TRACE_S, bs.WARMUP_S, bs.SLO_FAST_WINDOW_S,
            bs.SLO_SLOW_WINDOW_S, bs.SERVING_MIN_EVENTS)
    bs.TRACE_S, bs.WARMUP_S = trace_s, warmup_s
    bs.SLO_FAST_WINDOW_S = min(bs.SLO_FAST_WINDOW_S, trace_s / 6)
    bs.SLO_SLOW_WINDOW_S = min(bs.SLO_SLOW_WINDOW_S, trace_s / 2)
    bs.SERVING_MIN_EVENTS = 1
    try:
        yield
    finally:
        (bs.TRACE_S, bs.WARMUP_S, bs.SLO_FAST_WINDOW_S,
         bs.SLO_SLOW_WINDOW_S, bs.SERVING_MIN_EVENTS) = prev


def _journaled_trace(make_sim) -> list:
    """Run a sim under its OWN decision journal; normalize records to
    the (category, subject, sorted attrs) byte-identity basis (the
    bench_capacity off-means-off pattern)."""
    sim = make_sim()
    journal = DecisionJournal(maxlen=200_000, clock=sim.eng.now)
    with obs_scoped(journal=journal):
        sim.run()
    return [(r.category, r.subject,
             tuple(sorted((k, str(v)) for k, v in r.attrs.items()
                          if k != "plan_id")))
            for r in journal.events()]


def check_byte_identity(trace_s: float = 30.0,
                        warmup_s: float = 10.0) -> tuple[bool, str]:
    """Off means off: a router-disabled Sim must journal the EXACT
    decision sequence of plain bench_serving — importing the request
    plane and threading its hooks through the subclass cannot perturb
    the annotation-driven path."""
    with _short_trace(trace_s, warmup_s):
        base = _journaled_trace(lambda: bs.Sim(seed=0))
        off = _journaled_trace(
            lambda: Sim(seed=0, router_enabled=False))
    if base == off:
        return True, f"{len(base)} records identical"
    for i, (ra, rb) in enumerate(zip(base, off)):
        if ra != rb:
            return False, f"first divergence at record {i}: {ra} vs {rb}"
    return False, f"length mismatch: {len(base)} vs {len(off)}"


# -- entry points ------------------------------------------------------------
def run_full(seed: int = 0) -> dict:
    sim = Sim(seed=seed)
    out = sim.run()
    out["saturation_curve"] = saturation_curve(seed)
    identical, detail = check_byte_identity()
    out["byte_identity"] = {"ok": identical, "detail": detail}
    assert identical, f"router-disabled not byte-identical: {detail}"
    return out


def run_smoke() -> dict:
    """The request-path regression gate (scripts/check.sh): one seed,
    shortened trace.  Asserts the tentpole invariants end to end;
    byte-identity runs from bench_serving's smoke (its path is the one
    being protected).  Raises AssertionError on regression."""
    t0 = time.perf_counter()
    with _short_trace(90.0, 30.0):
        sim = Sim(seed=0)
        result = sim.run()
    result["saturation_curve"] = saturation_curve(0, scales=(1.0, 2.5))
    wall = time.perf_counter() - t0

    assert result["serving"]["preempted"] == 0, \
        f"{result['serving']['preempted']} serving preemption victim(s)"
    reqs = result["requests"]
    for key, r in reqs.items():
        assert r["completed_post_warmup"] > 0, f"no requests: {key}"
        assert r["p99_s"] is not None \
            and r["p99_s"] < REQUEST_P99_TARGET_S, \
            f"{key} request p99 {r['p99_s']}s >= {REQUEST_P99_TARGET_S}s"
    chat = reqs["serve/chat"]
    assert chat["occupancy_under_ceiling"] is not None \
        and chat["occupancy_under_ceiling"] >= OCC_WITHIN, \
        f"KV occupancy over {OCC_CEILING} ceiling too often: " \
        f"{chat['occupancy_under_ceiling']}"
    verdicts = [v for v in result["slo"]["verdicts"]
                if v["objective"] == "request-latency"]
    assert verdicts, "no request-latency SLO verdict"
    assert any(v["value"] is not None for v in verdicts), \
        "request-latency verdict never judged real events"
    for v in verdicts:
        assert not v["breached"], f"request SLO breached: {v}"
    curve = result["saturation_curve"]
    assert curve[-1]["offered_rps"] > curve[0]["offered_rps"], \
        "saturation curve not ordered by offered load"
    assert all(p["goodput_rps"] > 0 for p in curve), \
        f"router produced no goodput: {curve}"
    assert wall < 480.0, f"smoke took {wall:.1f}s (> 480s bound)"
    return {
        "smoke": "ok",
        "wall_s": round(wall, 1),
        "serving_preempted": result["serving"]["preempted"],
        "requests": reqs,
        "saturation_curve": curve,
        "tracking": result["serving"]["tracking"],
        "slo": result["slo"],
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="inference request data-plane bench")
    ap.add_argument("--smoke", action="store_true",
                    help="1-seed shortened-trace request-path gate")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--requests-report", default="",
                    help="also write the request block to this file "
                         "(CI uploads it as an artifact)")
    args = ap.parse_args(argv)
    out = run_smoke() if args.smoke else run_full(args.seed)
    write_report(args.requests_report,
                 {k: v for k, v in out.items() if k != "per_seed"},
                 note="requests report")
    emit(out)


if __name__ == "__main__":
    main()
