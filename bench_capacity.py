"""Capacity-plane benchmark: demand swings and stockout storms against
the cloud node-pool provisioner (ISSUE 16; docs/capacity.md).

Before this plane the fleet was fixed: a 2x demand swing either queued
jobs against a wall (too few hosts) or stranded chips idle (too many),
and a zonal stockout during a ramp was an operator page.  This bench
drives both regimes against the provisioner and gates that the plane
holds the line with ZERO operator action:

- **Swing**: steady demand doubles mid-trace, then halves back.  The
  provisioner must scale the pool up (sustained-deficit trigger,
  bounded in-flight creates, slow cloud + slow join modeled) and back
  down (drained top-index hosts only), keeping serving utilization >=
  95% outside brief adaptation windows.  The join lag shows up in the
  waste ledger as `provisioning` chip-seconds — "cloud is slow", NOT
  `idle_no_demand` — and chip-second conservation holds throughout.
- **Storm**: a zonal stockout opens exactly when demand steps up.  The
  per-(class, zone) breaker must OPEN (journaled transition), spare
  borrowing from the sibling pool must cover the whole gap, no job may
  starve, and every pending create must be landed or reaped by trace
  end — nothing leaks.
- **Off means off**: a provisioner-disabled run never constructs the
  plane; an ARMED-but-quiescent run (capacity exactly matching steady
  demand) must journal the byte-identical decision sequence — the
  plane leaks nothing into scheduling while it has nothing to do.

Gates (asserted per seed, exit 1 on regression):
- swing utilization >= 0.95 (outside warmup + adaptation windows);
- swing scale-up landed >= 4 hosts and scale-down released >= 3, final
  pool within one host of the baseline (round trip, no ratchet);
- provisioning chip-seconds > 0 attributed in the swing run's ledger;
- storm: breaker open transition journaled, borrows == 2, every job
  bound by settle end (never_bound == 0), zero outstanding cloud ops;
- byte-identity of the quiescent armed run vs the plane-off run;
- chip-second conservation inside every run.
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time

from nos_tpu.api import constants as C
from nos_tpu.capacity import CapacityProvisioner, CloudTPUAPI
from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.kube.client import APIServer, KIND_NODE, KIND_POD, NotFound
from nos_tpu.obs import journal as J, scoped as obs_scoped
from nos_tpu.obs import ledger as L
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.ledger import ChipSecondLedger, conservation_ok
from nos_tpu.sim import SimEngine, emit, write_report
from nos_tpu.testing.chaos import ChaosCloudTPUAPI
from nos_tpu.testing.factory import admit_all, make_slice_pod, make_tpu_node
from nos_tpu.topology import V5E
from nos_tpu.topology.profile import slice_resource_name
from nos_tpu.utils import retry as retry_mod
from nos_tpu.utils.retry import retry_on_conflict

MC = V5E.name                        # "tpu-v5e"
CHIPS_PER_HOST = V5E.chips_per_host  # 8
SHAPE = "2x4"                        # whole-host jobs: 8 chips each
SLICE_RES = slice_resource_name(SHAPE)

TICK_S = 0.5
WARMUP_S = 30.0
SETTLE_S = 90.0
JOIN_LAG_S = 6.0                     # VM up -> agent serving geometry
PROVISION_DELAY_S = 8.0

# swing: 4 hosts' demand -> 8 hosts' -> back, one pool, one zone
SWING_TRACE_S = 600.0
SWING_SHIFTS = (200.0, 400.0)
SWING_ADAPT_S = 90.0
BASE_HOSTS = 4
UTIL_TARGET = 0.95

# storm: two pools, demand steps up exactly as the target zone stocks out
STORM_TRACE_S = 420.0
STORM_START = 120.0
STORM_DURATION_S = 160.0
STORM_ADAPT_S = 40.0
STORM_POOL_HOSTS = 3
STORM_SPARES = 2

QUIET_TRACE_S = 120.0

DURATION_LO, DURATION_HI = 15.0, 25.0

PROV_KNOBS = dict(
    scale_up_deficit_chips=8.0, scale_up_after_s=4.0,
    scale_up_cooldown_s=6.0, max_pending_creates=4,
    scale_down_idle_s=15.0, scale_down_cooldown_s=8.0,
    min_hosts_per_pool=1, provision_deadline_s=60.0,
    join_grace_s=JOIN_LAG_S + 4.0, vacancy_grace_s=2.0,
    breaker_threshold=2, breaker_open_s=40.0, spare_target_per_pool=0,
)


def percentile(xs, q, digits=2):
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(q * len(xs)))], digits)


class Job:
    def __init__(self, name, duration, created):
        self.name = name
        self.duration = duration
        self.created = created
        self.bound_at = None


class Sim:
    """One trace run.  `plane` constructs + polls the provisioner; the
    plane-off run never constructs it (off means off — there is no
    disabled-but-present mode).  `scenario` picks the demand/fault
    schedule: swing | storm | quiet."""

    def __init__(self, seed=0, plane=True, scenario="swing"):
        self.seed = seed
        self.plane = plane
        self.scenario = scenario
        self.rng = random.Random(seed)
        self.eng = SimEngine()
        clock = self.eng.now
        self.api = APIServer()
        self.scheduler = build_scheduler(self.api, 16, clock=clock)
        self.ledger = ChipSecondLedger(clock=clock)
        self.journal = DecisionJournal(maxlen=200_000, clock=clock)
        self.trace_s = {"swing": SWING_TRACE_S, "storm": STORM_TRACE_S,
                        "quiet": QUIET_TRACE_S}[scenario]
        self._join_queue: list[tuple[float, str]] = []
        self.cloud = None
        self.prov = None
        if plane:
            if scenario == "storm":
                # deterministic storm: the injected window is the fault;
                # the random fault rates stay 0 so gates are exact
                self.cloud = ChaosCloudTPUAPI(
                    seed, clock=clock,
                    provision_delay_s=PROVISION_DELAY_S)
            else:
                self.cloud = CloudTPUAPI(
                    clock=clock, provision_delay_s=PROVISION_DELAY_S)
            self.cloud.set_joiner(self._cloud_join)
            self.prov = CapacityProvisioner(self.api, self.cloud,
                                            clock=clock, **PROV_KNOBS)
        if scenario == "storm":
            for h in range(STORM_POOL_HOSTS):
                self._add_host("pod-0", h, zone="us-a")
                self._add_host("pod-1", h, zone="us-b")
            for s in range(STORM_SPARES):
                self._add_host("pod-1", 100 + s, zone="us-b", spare=True)
        else:
            for h in range(BASE_HOSTS):
                self._add_host("pod-0", h, zone="us-a")
        self.jobs: dict[str, Job] = {}
        self._job_seq = 0
        self._pod_job: dict[str, Job] = {}
        self.completed = 0
        self.waits: list[float] = []
        self._util_area = 0.0
        self._util_time = 0.0
        self._util_min = 1.0

    # -- cluster -------------------------------------------------------------
    def _add_host(self, pool, host_index, zone, spare=False):
        extra = {C.LABEL_ZONE: zone}
        name = f"{pool}-h{host_index}"
        if spare:
            extra[C.LABEL_SPARE] = C.SPARE_WARM
            name = f"{pool}-spare{host_index}"
        self.api.create(KIND_NODE, make_tpu_node(
            name, pod_id=pool, host_index=host_index,
            status_geometry={"free": {SHAPE: 1}}, extra_labels=extra))

    def _cloud_join(self, cloud_node):
        """The kubelet-join model: the node object appears bare (labels
        only, no geometry — the agent is still starting) and begins
        serving JOIN_LAG_S later.  Until then its chips read as
        `provisioning` in the waste waterfall (the hold the provisioner
        stamped at create), not `idle_no_demand`."""
        labels = dict(cloud_node.labels)
        pool = labels.pop(C.LABEL_POD_ID, "pod-0")
        idx = int(labels.pop(C.LABEL_HOST_INDEX, "0"))
        for managed in (C.LABEL_ACCELERATOR, C.LABEL_PARTITIONING,
                        C.LABEL_CHIP_COUNT):
            labels.pop(managed, None)
        self.api.create(KIND_NODE, make_tpu_node(
            cloud_node.name, pod_id=pool, host_index=idx,
            extra_labels=labels))
        self._join_queue.append((self.eng.now() + JOIN_LAG_S,
                                 cloud_node.name))

    def _land_joins(self):
        for due, name in [e for e in self._join_queue
                          if e[0] <= self.eng.now()]:
            self._join_queue.remove((due, name))

            def mutate(node):
                node.metadata.annotations[
                    f"{C.ANNOT_STATUS_PREFIX}0-{SHAPE}-free"] = "1"
                node.status.allocatable[SLICE_RES] = 1.0
                node.status.capacity[SLICE_RES] = 1.0

            try:
                retry_on_conflict(self.api, KIND_NODE, name, mutate,
                                  component="bench-join")
            except NotFound:
                pass        # scaled down / reaped before it ever served

    # -- demand schedule -----------------------------------------------------
    def _target_chips(self) -> float:
        t = self.eng.now()
        if self.scenario == "swing":
            lo, hi = SWING_SHIFTS
            base = BASE_HOSTS * CHIPS_PER_HOST
            return float(2 * base if lo <= t < hi else base)
        if self.scenario == "storm":
            base = 2 * STORM_POOL_HOSTS * CHIPS_PER_HOST
            return float(base + STORM_SPARES * CHIPS_PER_HOST
                         if t >= STORM_START else base)
        return float(BASE_HOSTS * CHIPS_PER_HOST)       # quiet

    def _install_faults(self):
        """The storm as a first-class one-shot: a PRIO_FAULT event at
        STORM_START fires before the same-timestamp control tick, which
        is exactly when the old in-tick ``now >= STORM_START`` check
        triggered."""
        if self.scenario == "storm" and self.cloud is not None:
            self.eng.at(
                STORM_START,
                lambda: self.cloud.inject_stockout(
                    MC, "us-a", duration_s=STORM_DURATION_S),
                label="stockout-storm")

    def _in_adaptation(self) -> bool:
        t = self.eng.now()
        if t < WARMUP_S:
            return True
        if self.scenario == "swing":
            return any(s <= t < s + SWING_ADAPT_S for s in SWING_SHIFTS)
        if self.scenario == "storm":
            return STORM_START <= t < STORM_START + STORM_ADAPT_S
        return False

    # -- workload ------------------------------------------------------------
    def _spawn(self, target=None):
        target = self._target_chips() if target is None else target
        inflight = len(self.jobs) * float(CHIPS_PER_HOST)
        while inflight < target:
            self._job_seq += 1
            name = f"job-{self._job_seq}"
            job = Job(name, self.rng.uniform(DURATION_LO, DURATION_HI),
                      self.eng.now())
            self.api.create(KIND_POD, make_slice_pod(
                SHAPE, 1, name=name, namespace="work",
                creation_timestamp=self.eng.now()))
            self.jobs[name] = job
            self._pod_job[name] = job
            inflight += CHIPS_PER_HOST

    def _complete_finished(self):
        for job in list(self.jobs.values()):
            if job.bound_at is None \
                    or self.eng.now() < job.bound_at + job.duration:
                continue
            try:
                self.api.delete(KIND_POD, job.name, "work")
            except NotFound:
                pass
            self._pod_job.pop(job.name, None)
            del self.jobs[job.name]
            self.completed += 1

    def _record_binds(self):
        for p in self.api.list(KIND_POD):
            if not p.spec.node_name:
                continue
            job = self._pod_job.get(p.metadata.name)
            if job is not None and job.bound_at is None:
                job.bound_at = self.eng.now()
                self.waits.append(self.eng.now() - job.created)

    # -- measurement ---------------------------------------------------------
    def _serving_chips(self) -> float:
        chips = 0.0
        for node in self.api.list(KIND_NODE):
            labels = node.metadata.labels
            if labels.get(C.LABEL_SPARE, "") == C.SPARE_WARM:
                continue
            if not any(k.startswith(C.ANNOT_STATUS_PREFIX)
                       for k in node.metadata.annotations):
                continue        # joined but not serving yet
            chips += float(labels.get(C.LABEL_CHIP_COUNT, "0") or 0.0)
        return chips

    def _sample_utilization(self):
        if self._in_adaptation():
            return
        live = self._serving_chips()
        if live <= 0:
            return
        used = sum(CHIPS_PER_HOST for p in self.api.list(KIND_POD)
                   if p.spec.node_name)
        util = min(1.0, used / live)
        self._util_area += util * TICK_S
        self._util_time += TICK_S
        self._util_min = min(self._util_min, util)

    def _active_hosts(self) -> int:
        return sum(1 for n in self.api.list(KIND_NODE)
                   if n.metadata.labels.get(C.LABEL_SPARE, "")
                   != C.SPARE_WARM)

    # -- main loop -----------------------------------------------------------
    def _tick(self, spawn_target=None):
        self._complete_finished()
        self._land_joins()
        self._spawn(target=spawn_target)
        self.scheduler.run_cycle()
        if self.prov is not None:
            self.prov.reconcile()
        admit_all(self.api)
        self._record_binds()
        self._sample_utilization()

    def run(self):
        # cloud 429 retries back off through utils/retry's sleep seam;
        # virtual time must not really sleep
        real_sleep, retry_mod.sleep = retry_mod.sleep, lambda s: None
        try:
            with obs_scoped(journal=self.journal, ledger=self.ledger):
                self._install_faults()
                self.eng.tick_loop(TICK_S, self._tick,
                                   until=self.trace_s, label="ctl-tick")
                self.eng.run(until=self.trace_s)
                # settle: demand stops, the backlog must drain — a job
                # spawned seconds before trace end deserves its bind
                # before the never_bound verdict is passed
                self.eng.tick_loop(
                    TICK_S, lambda: self._tick(spawn_target=0.0),
                    until=self.eng.now() + SETTLE_S,
                    while_fn=lambda: any(j.bound_at is None
                                         for j in self.jobs.values()),
                    label="settle-tick")
                self.eng.run()
        finally:
            retry_mod.sleep = real_sleep
        waste = self.ledger.report()
        assert conservation_ok(waste), (
            "chip-second conservation violated: "
            + str({p: v["conservation_delta"]
                   for p, v in waste["pools"].items()}))
        never_bound = sorted(j.name for j in self.jobs.values()
                             if j.bound_at is None)
        counters = dict(self.prov.report().get("counters", {})) \
            if self.prov is not None else {}
        outstanding = (list(self.cloud.list_operations())
                       if self.cloud is not None else [])
        breaker_opens = len([
            r for r in self.journal.events(category=J.PROVISION_STOCKOUT)
            if r.attrs.get("state") == "open"])
        return {
            "utilization_pct": round(
                self._util_area / self._util_time, 4)
                if self._util_time else 0.0,
            "utilization_min": round(self._util_min, 4),
            "jobs_completed": self.completed,
            "never_bound": len(never_bound),
            "never_bound_jobs": never_bound,
            "bind_wait_p50_s": percentile(self.waits, 0.5),
            "bind_wait_p90_s": percentile(self.waits, 0.9),
            "hosts_final": self._active_hosts(),
            "provision_landed": counters.get("landed", 0),
            "scale_downs": counters.get("scale_downs", 0),
            "borrows": counters.get("borrows", 0),
            "breaker_opens": breaker_opens,
            "outstanding_ops": len(outstanding),
            "provisioning_chip_seconds": round(
                waste["fleet"]["chip_seconds"].get(L.PROVISIONING, 0.0),
                1),
        }

    def decision_trace(self):
        """(category, subject, attrs) with run-unique identifiers (uuid
        plan ids) normalized — the byte-identity basis."""
        return [(r.category, r.subject, tuple(sorted(
            (k, str(v)) for k, v in r.attrs.items()
            if k != "plan_id")))
            for r in self.journal.events()]


def check_byte_identity():
    """Off means off: the armed-but-quiescent plane (capacity exactly
    matching steady demand, no faults) must journal the EXACT record
    sequence of a run that never constructed the plane.  Any leak —
    a speculative create, a scale-down twitch on a churn gap — shows
    up as the first divergent record."""
    off = Sim(seed=0, plane=False, scenario="quiet")
    off.run()
    on = Sim(seed=0, plane=True, scenario="quiet")
    on_result = on.run()
    a, b = off.decision_trace(), on.decision_trace()
    quiescent = (on_result["provision_landed"] == 0
                 and on_result["scale_downs"] == 0)
    if not quiescent:
        return False, ("armed plane acted on a quiet trace: "
                       + json.dumps(on_result))
    if a == b:
        return True, f"{len(a)} records identical"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if ra != rb:
            return False, f"first divergence at record {i}: {ra} vs {rb}"
    return False, f"length mismatch: {len(a)} vs {len(b)}"


def assert_gates(seed, swing, storm):
    failures = []
    if swing["utilization_pct"] < UTIL_TARGET:
        failures.append(
            f"seed {seed}: swing utilization "
            f"{swing['utilization_pct']} < {UTIL_TARGET}")
    if swing["provision_landed"] < BASE_HOSTS:
        failures.append(
            f"seed {seed}: swing landed only "
            f"{swing['provision_landed']} hosts (< {BASE_HOSTS})")
    if swing["scale_downs"] < BASE_HOSTS - 1:
        failures.append(
            f"seed {seed}: swing released only "
            f"{swing['scale_downs']} hosts (< {BASE_HOSTS - 1})")
    if swing["hosts_final"] > BASE_HOSTS + 1:
        failures.append(
            f"seed {seed}: swing did not round-trip — "
            f"{swing['hosts_final']} hosts at end (ratchet)")
    if swing["never_bound"] != 0:
        failures.append(
            f"seed {seed}: swing never_bound = {swing['never_bound']} "
            f"({swing['never_bound_jobs']})")
    if swing["provisioning_chip_seconds"] <= 0.0:
        failures.append(
            f"seed {seed}: no provisioning chip-seconds attributed — "
            f"the join lag read as idle_no_demand")
    if swing["outstanding_ops"] != 0:
        failures.append(
            f"seed {seed}: swing left {swing['outstanding_ops']} cloud "
            f"ops outstanding")
    if storm["breaker_opens"] < 1:
        failures.append(f"seed {seed}: storm never opened the breaker")
    if storm["borrows"] != STORM_SPARES:
        failures.append(
            f"seed {seed}: storm borrowed {storm['borrows']} spares "
            f"(expected {STORM_SPARES} — borrowing must cover the gap)")
    if storm["never_bound"] != 0:
        failures.append(
            f"seed {seed}: storm never_bound = {storm['never_bound']} "
            f"({storm['never_bound_jobs']})")
    if storm["outstanding_ops"] != 0:
        failures.append(
            f"seed {seed}: storm left {storm['outstanding_ops']} cloud "
            f"ops outstanding (pending creates must land or be reaped)")
    if storm["utilization_pct"] < UTIL_TARGET:
        failures.append(
            f"seed {seed}: storm utilization "
            f"{storm['utilization_pct']} < {UTIL_TARGET}")
    return failures


def run_bench(seeds, identity=True):
    per_seed = {}
    failures = []
    for seed in seeds:
        swing = Sim(seed=seed, plane=True, scenario="swing").run()
        storm = Sim(seed=seed, plane=True, scenario="storm").run()
        failures.extend(assert_gates(seed, swing, storm))
        per_seed[str(seed)] = {"swing": swing, "storm": storm}
    out = {
        "base_hosts": BASE_HOSTS,
        "trace_seconds": {"swing": SWING_TRACE_S, "storm": STORM_TRACE_S},
        "utilization_target": UTIL_TARGET,
        "utilization_worst": min(
            (min(s["swing"]["utilization_pct"],
                 s["storm"]["utilization_pct"])
             for s in per_seed.values()), default=None),
        "per_seed": per_seed,
        "gates": {"failures": failures},
    }
    if identity:
        identical, detail = check_byte_identity()
        if not identical:
            failures.append(
                f"provisioner-disabled not byte-identical: {detail}")
        out["byte_identity"] = {"ok": identical, "detail": detail}
    out["ok"] = not failures
    return out


def run_smoke():
    """CI gate (scripts/check.sh): one seed, both scenarios, every gate
    asserted — swing utilization and round trip, storm breaker +
    borrowing + op hygiene, byte-identity, conservation (inside each
    run).  Raises AssertionError on regression."""
    t0 = time.perf_counter()
    out = run_bench([0])
    out["smoke"] = "ok" if out["ok"] else "FAILED"
    out["wall_s"] = round(time.perf_counter() - t0, 1)
    assert out["ok"], "capacity gates failed: " + "; ".join(
        out["gates"]["failures"])
    assert out["wall_s"] < 300.0, \
        f"capacity smoke took {out['wall_s']}s (> 300s bound)"
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="cloud capacity provisioner bench")
    ap.add_argument("--smoke", action="store_true",
                    help="1-seed capacity gate (CI)")
    ap.add_argument("--seeds", type=int, default=3,
                    help="seeds for the full run")
    ap.add_argument("--capacity-report", default="",
                    help="also write the result JSON to this file "
                         "(CI uploads it as an artifact)")
    args = ap.parse_args(argv)
    if args.smoke:
        out = run_smoke()
    else:
        out = run_bench(list(range(args.seeds)))
    write_report(args.capacity_report, out, note="capacity report")
    emit(out)
    if not out.get("ok", True):
        sys.exit(1)


if __name__ == "__main__":
    main()
