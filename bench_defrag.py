"""Defragmentation benchmark: a fragmentation-adversarial churn trace.

The waste ledger (PR 12) put numbers on the two sinks this bench
attacks: frag_stranded (free chips no pending class can use because
admission-time placement pinned the carving) and gang_wait.  The trace
is built to MANUFACTURE that regime, then measures whether the defrag
plane (malleable gangs + the background repartitioner) reclaims it:

- **Phase 1 (fill)**: a high backlog of small 1x1/1x2 fillers packs the
  v5e pod; completions then pock every host with pinned survivors.
- **Phase 2 (frag)**: filler pressure drops and whole-host 2x4 demand
  arrives — aggregate free chips abound, but every host holds a filler,
  so no carve can serve the class.  Without defrag this demand pends
  forever and the utilization floor collapses.
- **Phase 3 (burst + gang)**: a 2-host 4x4 gang and a burst of
  higher-priority 1x2 singles join: the gang needs a window only
  migration can empty, and the burst exercises shrink-before-evict
  against the elastic sponge gang.

An **elastic dp gang** (`nos.tpu/elastic: "dp"`, min 2 / max replicas
sized to the pod) runs the whole trace as a utilization sponge: the
scheduler's grow pass feeds it spare chips, and preemption's shrink
rung reclaims them for the burst without killing the job.

Everything runs through the REAL control plane (cmd/assembly wiring:
scheduler + slice partitioner controller + node agents on a virtual
clock); the defragmenter runs inside the partitioner controller exactly
as in production.

Gates (the ISSUE 14 acceptance criteria, asserted per seed):
- utilization_min >= 0.95 with defrag on (the no-defrag floor on this
  trace sits far below);
- frag_stranded chip-seconds <= 50% of the no-defrag baseline on the
  SAME trace and seed;
- migration churn bounded: <= MAX_MIGRATIONS_PER_JOB defrag evictions
  per job over the trace (and a global cap), enforced by the proposer's
  demand cooldown;
- defrag disabled is byte-identical to a propose-only run (payback
  threshold = inf): the what-if forks leak nothing into decisions —
  the journals match record for record once DEFRAG_* lines are removed;
- chip-second conservation holds in every configuration (the ledger's
  invariant survives drain holds appearing and resolving mid-trace).
"""

from __future__ import annotations

import argparse
import random
import sys
import time

from nos_tpu.api import constants as C
from nos_tpu.api.podgroup import PodGroup, PodGroupSpec
from nos_tpu.cmd.assembly import build_scheduler
from nos_tpu.controllers.node_controller import NodeController
from nos_tpu.controllers.pod_controller import PodController
from nos_tpu.controllers.sliceagent.agent import SliceAgent
from nos_tpu.device import default_tpu_runtime
from nos_tpu.device.fake import FakePodResources
from nos_tpu.kube.client import (
    APIServer, KIND_NODE, KIND_POD, KIND_POD_GROUP, NotFound,
)
from nos_tpu.kube.objects import ObjectMeta, PENDING, RUNNING
from nos_tpu.obs import journal as J, scoped as obs_scoped
from nos_tpu.obs.journal import DecisionJournal
from nos_tpu.obs.ledger import ChipSecondLedger, conservation_ok
from nos_tpu.partitioning.slicepart import SliceNodeInitializer
from nos_tpu.partitioning.slicepart.factory import (
    new_slice_partitioner_controller,
)
from nos_tpu.partitioning.state import ClusterState
from nos_tpu.sim import SimEngine, emit, write_report
from nos_tpu.testing.factory import make_slice_pod, make_tpu_node
from nos_tpu.topology import V5E

HOSTS = 24
CHIPS_PER_HOST = V5E.chips_per_host          # 8
TOTAL_CHIPS = HOSTS * CHIPS_PER_HOST         # 192

TICK_S = 0.25
WARMUP_S = 60.0
TRACE_S = 300.0
BATCH_IDLE_S = 0.5
BATCH_TIMEOUT_S = 2.0

# Defrag knobs under test (PartitionerConfig analogs)
DEFRAG_INTERVAL_S = 6.0
DEFRAG_PAYBACK_MIN = 1.2
DEFRAG_DRAIN_TIMEOUT_S = 30.0

UTILIZATION_MIN_TARGET = 0.95
FRAG_HALVING_TARGET = 0.50
MAX_MIGRATIONS_PER_JOB = 2
MAX_TOTAL_MIGRATIONS = 40

# Elastic sponge gang: dp members each consuming a 1x2 slice.  The
# sponge soaks spare chips (grow) and is the defragmenter's cheapest
# victim (shrink) when a blocked class needs its window back.
ELASTIC_MIN, ELASTIC_MAX = 2, 60
SPONGES = ("sponge-a", "sponge-b")

FILLER_DURATION = (120.0, 240.0)    # long-lived pins: the frag source
BIG_DURATION = (50.0, 90.0)
BURST_DURATION = (10.0, 20.0)
GANG_DURATION = (60.0, 100.0)

# phase start -> {class: backlog target in chip-equivalents}
PHASES = [
    (0.0, {"filler": 150.0, "big": 0.0, "burst": 0.0, "gang": 0.0}),
    (40.0, {"filler": 0.0, "big": 56.0, "burst": 0.0, "gang": 0.0}),
    (190.0, {"filler": 0.0, "big": 40.0, "burst": 12.0, "gang": 16.0}),
]

CLASS_SPECS = {
    "filler": (("1x2",), 1, 0, FILLER_DURATION),
    "big": (("2x4",), 1, 5, BIG_DURATION),
    "burst": (("1x2",), 1, 10, BURST_DURATION),
    "gang": (("4x4",), 2, 10, GANG_DURATION),
}

# Utilization floor is judged on a short rolling mean: per-0.25s-tick
# instantaneous samples punish the 1-2 tick rebind gap of every
# completion/migration handoff, which no fleet operator would call
# waste; 3 s windows keep genuine stranding visible.
UTIL_WINDOW_TICKS = 20


def percentile(xs, q, digits=3):
    if not xs:
        return None
    xs = sorted(xs)
    return round(xs[min(len(xs) - 1, int(q * len(xs)))], digits)


def chip_equiv(pod) -> float:
    from nos_tpu.kube.resources import pod_request
    from nos_tpu.topology.profile import extract_slice_requests

    return sum(min(s.chips, CHIPS_PER_HOST) * q
               for s, q in extract_slice_requests(
                   pod_request(pod)).items())


class Job:
    def __init__(self, name, kind, pods, duration, created,
                 shape="1x1", priority=0):
        self.name = name
        self.kind = kind
        self.pods = pods
        self.duration = duration
        self.created = created
        self.shape = shape
        self.priority = priority
        self.bound_at = None


class Sim:
    """One trace run.  `defrag` enables the proposer; `elastic_grow`
    (default: follows `defrag`) enables the scheduler's grow pass — the
    no-defrag baseline runs BOTH off, i.e. the pre-PR control plane, so
    the comparison prices the whole malleable-gang + defrag plane."""

    def __init__(self, seed=0, defrag=True, payback_min=None,
                 elastic_grow=None):
        self.seed = seed
        self.rng = random.Random(seed)
        self.eng = SimEngine()
        clock = self.eng.now
        api = self.api = APIServer()
        state = ClusterState()
        NodeController(api, state, SliceNodeInitializer(api)).bind()
        PodController(api, state).bind()
        self.ctl = new_slice_partitioner_controller(
            api, state, batch_timeout_s=BATCH_TIMEOUT_S,
            batch_idle_s=BATCH_IDLE_S, clock=clock,
            defrag_enabled=defrag,
            defrag_payback_min=(payback_min if payback_min is not None
                                else DEFRAG_PAYBACK_MIN),
            defrag_interval_s=DEFRAG_INTERVAL_S,
            defrag_drain_timeout_s=DEFRAG_DRAIN_TIMEOUT_S,
            defrag_progress_fn=self._pod_progress)
        self.ctl.bind()
        self.agents = {}
        for i in range(HOSTS):
            name = f"host-{i}"
            api.create(KIND_NODE, make_tpu_node(
                name, pod_id="pod-0", host_index=i))
            agent = SliceAgent(api, name, default_tpu_runtime(V5E),
                               FakePodResources())
            agent.start()
            self.agents[name] = agent
        grow = defrag if elastic_grow is None else elastic_grow
        self.scheduler = build_scheduler(
            api, 16, drain_preempt_after_cycles=40,
            drain_preempt_progress_fn=self._pod_progress,
            shard_chips_per_host=CHIPS_PER_HOST,
            elastic_grow_budget_per_cycle=1 if grow else 0, clock=clock)
        self.ledger = ChipSecondLedger(clock=clock)
        self.journal = DecisionJournal(maxlen=300_000, clock=clock)
        self.jobs: dict[str, Job] = {}
        self._job_seq = 0
        self._pod_job: dict[str, Job] = {}
        self.latencies: list[float] = []
        self._util_samples: list[float] = []
        self._util_raw: list[float] = []
        self.completed = 0
        self.defrag_migrated_pods = 0
        self._spawn_elastic()

    # -- workload ------------------------------------------------------------
    def _spawn_elastic(self):
        """The utilization sponges: two elastic dp gangs, alive for the
        whole trace, grown/shrunk by the control plane (two gangs so
        the grow pass — one outstanding clone per gang — soaks holes
        at twice the rate)."""
        for name in SPONGES:
            self.api.create(KIND_POD_GROUP, PodGroup(
                metadata=ObjectMeta(name=name, namespace="work"),
                spec=PodGroupSpec(min_member=ELASTIC_MIN)))
            job = Job(name, "elastic", [], TRACE_S * 2, 0.0)
            for i in range(ELASTIC_MIN):
                pod = self._make_sponge_pod(name, f"{name}-{i}")
                self.api.create(KIND_POD, pod)
                job.pods.append(pod.metadata.name)
                self._pod_job[pod.metadata.name] = job
            self.jobs[name] = job

    @staticmethod
    def _make_sponge_pod(gang, pod_name):
        return make_slice_pod(
            "1x2", 1, name=pod_name, namespace="work",
            labels={C.LABEL_POD_GROUP: gang},
            annotations={C.ANNOT_ELASTIC: C.ELASTIC_DP,
                         C.ANNOT_MIN_REPLICAS: str(ELASTIC_MIN),
                         C.ANNOT_MAX_REPLICAS: str(ELASTIC_MAX)},
            creation_timestamp=0.0)

    def _phase_targets(self):
        current = PHASES[0][1]
        for start, targets in PHASES:
            if self.eng.now() >= start:
                current = targets
        return current

    def _spawn(self):
        # Footprint targets: each class is held at a total in-flight
        # chip footprint (pending + running).  A PENDING-only backlog
        # would keep a standing queue of small jobs that instantly eats
        # every hole — starving whole-host demand by queueing, which is
        # a different disease than the fragmentation this trace is
        # built to manufacture.
        targets = self._phase_targets()
        footprint = {cls: 0.0 for cls in targets}
        for p in self.api.list(KIND_POD):
            job = self._pod_job.get(p.metadata.name)
            if job is not None and job.kind in footprint:
                footprint[job.kind] += chip_equiv(p)
        for cls, target in targets.items():
            while footprint[cls] < target:
                footprint[cls] += self._spawn_job(cls)

    def _spawn_job(self, cls):
        shapes, members, priority, (lo, hi) = CLASS_SPECS[cls]
        shape = self.rng.choice(shapes)
        self._job_seq += 1
        name = f"{cls}-{self._job_seq}"
        duration = self.rng.uniform(lo, hi)
        job = Job(name, cls, [], duration, self.eng.now(),
                  shape=shape, priority=priority)
        if members > 1:
            self.api.create(KIND_POD_GROUP, PodGroup(
                metadata=ObjectMeta(name=name, namespace="work"),
                spec=PodGroupSpec(min_member=members)))
        spawned = 0.0
        for i in range(members):
            pod = self._make_pod(job, f"{name}-{i}")
            self.api.create(KIND_POD, pod)
            job.pods.append(pod.metadata.name)
            self._pod_job[pod.metadata.name] = job
            spawned += chip_equiv(pod)
        self.jobs[name] = job
        return spawned

    def _make_pod(self, job, pod_name):
        members = CLASS_SPECS[job.kind][1]
        return make_slice_pod(
            job.shape, 1, name=pod_name, namespace="work",
            labels=({C.LABEL_POD_GROUP: job.name} if members > 1
                    else None),
            priority=job.priority, creation_timestamp=job.created)

    def _pod_progress(self, pod):
        job = self._pod_job.get(pod.metadata.name)
        if job is None or job.bound_at is None or job.duration <= 0:
            return 0.0
        return min(1.0, max(0.0, (self.eng.now() - job.bound_at)
                            / job.duration))

    def _complete_finished(self):
        for job in list(self.jobs.values()):
            if job.bound_at is None \
                    or self.eng.now() < job.bound_at + job.duration:
                continue
            # delete by gang label too: elastic growth added members the
            # job table never saw
            doomed = set(job.pods)
            doomed.update(
                p.metadata.name for p in self.api.list(
                    KIND_POD, namespace="work",
                    label_selector={C.LABEL_POD_GROUP: job.name}))
            for pname in doomed:
                try:
                    self.api.delete(KIND_POD, pname, "work")
                except NotFound:
                    pass
                self._pod_job.pop(pname, None)
            try:
                self.api.delete(KIND_POD_GROUP, job.name, "work")
            except NotFound:
                pass
            del self.jobs[job.name]
            self.completed += 1

    def _requeue_evicted(self):
        """Drain-then-rebind semantics: a migrated/preempted job loses
        its progress and requeues with its ORIGINAL creation timestamp.
        Elastic members are NOT requeued — losing one IS the shrink
        contract (the grow pass re-adds capacity when it frees up)."""
        live = {p.metadata.name for p in self.api.list(KIND_POD)}
        for job in self.jobs.values():
            if job.kind == "elastic":
                # the elastic workload controller's one duty: keep the
                # gang at >= min replicas (shrink took it no lower by
                # contract, but whole-gang eviction may have)
                alive = len(self.api.list(
                    KIND_POD, namespace="work",
                    label_selector={C.LABEL_POD_GROUP: job.name}))
                for pname in job.pods:
                    if alive >= ELASTIC_MIN:
                        break
                    if pname not in live:
                        pod = self._make_sponge_pod(job.name, pname)
                        self.api.create(KIND_POD, pod)
                        self._pod_job[pname] = job
                        alive += 1
                        job.bound_at = None
                continue
            missing = [n for n in job.pods if n not in live]
            if not missing:
                continue
            job.bound_at = None
            for pname in missing:
                pod = self._make_pod(job, pname)
                self.api.create(KIND_POD, pod)
                self._pod_job[pname] = job

    def _record_binds(self):
        bound = {p.metadata.name for p in self.api.list(KIND_POD)
                 if p.spec.node_name and p.status.phase == RUNNING}
        for job in self.jobs.values():
            if job.kind == "elastic":
                if job.bound_at is None \
                        and all(n in bound for n in job.pods):
                    job.bound_at = self.eng.now()
                continue
            if job.bound_at is None and all(n in bound for n in job.pods):
                job.bound_at = self.eng.now()
                self.latencies.append(self.eng.now() - job.created)

    def _sample_utilization(self):
        used = sum(chip_equiv(p) for p in self.api.list(KIND_POD)
                   if p.spec.node_name and p.status.phase == RUNNING)
        u = min(1.0, used / TOTAL_CHIPS)
        self._util_raw.append(u)
        if self.eng.now() >= WARMUP_S:
            window = self._util_raw[-UTIL_WINDOW_TICKS:]
            self._util_samples.append(sum(window) / len(window))

    def _tick(self):
        self._complete_finished()
        self._spawn()
        self.scheduler.run_cycle()
        self._requeue_evicted()
        self.ctl.process_if_ready()
        for a in self.agents.values():
            a.tick()
        self._record_binds()
        self._sample_utilization()

    # -- main loop -----------------------------------------------------------
    def run(self):
        with obs_scoped(journal=self.journal, ledger=self.ledger):
            self.eng.tick_loop(TICK_S, self._tick, until=TRACE_S,
                               label="ctl-tick")
            self.eng.run()
        waste = self.ledger.report()
        assert conservation_ok(waste), (
            "chip-second conservation violated: "
            + str({p: v["conservation_delta"]
                   for p, v in waste["pools"].items()}))
        self._account_migrations()
        utils = self._util_samples
        return {
            "utilization_mean": round(sum(utils) / len(utils), 4)
            if utils else 0.0,
            "utilization_min": round(min(utils), 4) if utils else 0.0,
            "jobs_completed": self.completed,
            "jobs_bound": len(self.latencies),
            "p50_schedule_latency_s": percentile(self.latencies, 0.5),
            "p90_schedule_latency_s": percentile(self.latencies, 0.9),
            "frag_stranded_chip_seconds": round(
                waste["fleet"]["chip_seconds"].get("frag_stranded", 0.0),
                1),
            "drain_chip_seconds": round(
                waste["fleet"]["chip_seconds"].get("drain", 0.0), 1),
            "defrag": self._defrag_summary(),
            "elastic": self._elastic_summary(),
            "waste": waste,
        }

    def _account_migrations(self):
        """Per-job migration counts from the journal's `moved` lists
        (shrink evictions are resizes, not migrations — counted in the
        elastic summary instead)."""
        self.migrations_by_job: dict[str, int] = {}
        for rec in self.journal.events(category=J.DEFRAG_APPLIED):
            moved = rec.attrs.get("moved", [])
            self.defrag_migrated_pods += len(moved)
            for key in moved:
                pod_name = key.split("/", 1)[-1]
                job_name = pod_name.rsplit("-", 1)[0]
                self.migrations_by_job[job_name] = \
                    self.migrations_by_job.get(job_name, 0) + 1

    def _defrag_summary(self):
        return {
            "proposed": len(self.journal.events(
                category=J.DEFRAG_PROPOSED)),
            "applied": len(self.journal.events(
                category=J.DEFRAG_APPLIED)),
            "rejected": len(self.journal.events(
                category=J.DEFRAG_REJECTED)),
            "migrated_pods": self.defrag_migrated_pods,
            "migrations_by_job_max": max(
                self.migrations_by_job.values(), default=0),
        }

    def _elastic_summary(self):
        resizes = self.journal.events(category=J.GANG_RESIZED)
        live = sum(len(self.api.list(
            KIND_POD, namespace="work",
            label_selector={C.LABEL_POD_GROUP: name},
            filter_fn=lambda p: p.status.phase in (PENDING, RUNNING)))
            for name in SPONGES)
        return {
            "grows": sum(1 for r in resizes
                         if r.attrs.get("direction") == "grow"),
            "shrinks": sum(1 for r in resizes
                           if r.attrs.get("direction") == "shrink"),
            "final_replicas": live,
        }

    def decision_trace(self):
        """(category, subject, attrs) sequence with defrag's own
        records removed and run-unique identifiers (uuid plan ids)
        normalized — the byte-identity comparison basis."""
        skip = {J.DEFRAG_PROPOSED, J.DEFRAG_APPLIED, J.DEFRAG_REJECTED}
        return [(r.category, r.subject, tuple(sorted(
            (k, str(v)) for k, v in r.attrs.items()
            if k != "plan_id")))
            for r in self.journal.events() if r.category not in skip]


def run_seed(seed, defrag=True, payback_min=None):
    return Sim(seed=seed, defrag=defrag, payback_min=payback_min).run()


def assert_gates(seed, on, off):
    failures = []
    if on["utilization_min"] < UTILIZATION_MIN_TARGET:
        failures.append(
            f"seed {seed}: utilization_min {on['utilization_min']} "
            f"< {UTILIZATION_MIN_TARGET}")
    frag_on = on["frag_stranded_chip_seconds"]
    frag_off = off["frag_stranded_chip_seconds"]
    if frag_off > 0 and frag_on > FRAG_HALVING_TARGET * frag_off:
        failures.append(
            f"seed {seed}: frag_stranded {frag_on} > "
            f"{FRAG_HALVING_TARGET} x no-defrag baseline {frag_off}")
    churn = on["defrag"]["migrations_by_job_max"]
    if churn > MAX_MIGRATIONS_PER_JOB:
        failures.append(
            f"seed {seed}: {churn} migrations for one job "
            f"(bound {MAX_MIGRATIONS_PER_JOB})")
    if on["defrag"]["migrated_pods"] > MAX_TOTAL_MIGRATIONS:
        failures.append(
            f"seed {seed}: {on['defrag']['migrated_pods']} total "
            f"migrations (bound {MAX_TOTAL_MIGRATIONS})")
    if on["defrag"]["applied"] < 1:
        failures.append(f"seed {seed}: defrag never applied a proposal")
    return failures


def check_byte_identity(disabled_sim):
    """Defrag disabled vs propose-only (payback = inf, grow off): the
    proposer's what-if forks and journal records must leak NOTHING into
    decisions.  Reuses the already-run disabled sim (same seed).
    Returns (identical, detail)."""
    propose_only = Sim(seed=disabled_sim.seed, defrag=True,
                       payback_min=float("inf"), elastic_grow=False)
    propose_only.run()
    a = disabled_sim.decision_trace()
    b = propose_only.decision_trace()
    if a == b:
        return True, f"{len(a)} records identical"
    for i, (ra, rb) in enumerate(zip(a, b)):
        if ra != rb:
            return False, f"first divergence at record {i}: {ra} vs {rb}"
    return False, f"length mismatch: {len(a)} vs {len(b)}"


def run_bench(seeds):
    per_seed = {}
    failures = []
    first_disabled = None
    for seed in seeds:
        on = run_seed(seed, defrag=True)
        off_sim = Sim(seed=seed, defrag=False)
        off = off_sim.run()
        if first_disabled is None:
            first_disabled = off_sim
        failures.extend(assert_gates(seed, on, off))
        per_seed[str(seed)] = {
            "defrag_on": {k: v for k, v in on.items() if k != "waste"},
            "no_defrag": {
                "utilization_min": off["utilization_min"],
                "utilization_mean": off["utilization_mean"],
                "frag_stranded_chip_seconds":
                    off["frag_stranded_chip_seconds"],
            },
        }
    identical, detail = check_byte_identity(first_disabled)
    if not identical:
        failures.append(f"defrag-disabled not byte-identical: {detail}")
    utils = [per_seed[s]["defrag_on"]["utilization_min"]
             for s in per_seed]
    return {
        "hosts": HOSTS,
        "total_chips": TOTAL_CHIPS,
        "trace_seconds": TRACE_S,
        "utilization_min": min(utils) if utils else 0.0,
        "per_seed": per_seed,
        "byte_identity": {"ok": identical, "detail": detail},
        "gates": {
            "utilization_min_target": UTILIZATION_MIN_TARGET,
            "frag_halving_target": FRAG_HALVING_TARGET,
            "max_migrations_per_job": MAX_MIGRATIONS_PER_JOB,
            "failures": failures,
        },
        "ok": not failures,
    }


def run_smoke():
    """CI gate (scripts/check.sh): one seed, the full churn trace, all
    four defrag gates asserted — utilization floor, frag halving,
    churn bound, byte-identity — plus conservation (asserted inside
    every run).  Three trace runs total (defrag-on, disabled baseline,
    propose-only; identity reuses the baseline).  Raises AssertionError
    on regression."""
    t0 = time.perf_counter()
    out = run_bench([0])
    out["smoke"] = "ok" if out["ok"] else "FAILED"
    out["wall_s"] = round(time.perf_counter() - t0, 1)
    assert out["ok"], "defrag gates failed: " + "; ".join(
        out["gates"]["failures"])
    assert out["wall_s"] < 420.0, \
        f"defrag smoke took {out['wall_s']}s (> 420s bound)"
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="defragmentation + malleable-gang bench")
    ap.add_argument("--smoke", action="store_true",
                    help="1-seed defrag gate (CI)")
    ap.add_argument("--seeds", type=int, default=3,
                    help="seeds for the full run")
    ap.add_argument("--defrag-report", default="",
                    help="also write the result JSON to this file "
                         "(CI uploads it as an artifact)")
    args = ap.parse_args(argv)
    if args.smoke:
        out = run_smoke()
    else:
        out = run_bench(list(range(args.seeds)))
    write_report(args.defrag_report, out, note="defrag report")
    emit(out)
    if not out.get("ok", True):
        sys.exit(1)


if __name__ == "__main__":
    main()
